"""Executor — runs Programs as single XLA computations.

Reference: python/paddle/fluid/executor.py + paddle/fluid/framework/executor.cc.
TPU-first rework: instead of a C++ op-by-op interpreter over a Scope, `run`
lowers the whole Program (forward + jax.grad backward + optimizer update) into
ONE pure function `(params, feeds, key) -> (fetches, new_params)` and jits it.
The Scope is a host-side dict of device arrays holding persistables
(parameters + optimizer slots); compiled executables are cached per
(program version, feed shapes, fetch names).
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
import numpy as np

from ..core.place import Place, _expected_place
from ..core.tensor import Tensor


def _debug_logger():
    from ..observability import log as _log
    return _log.get_logger(__name__)
from .program import (OpNode, Program, Variable, default_main_program,
                      default_startup_program)


class Scope:
    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, None)

    def find_var(self, name):
        return self._vars.get(name)

    def set(self, name, value):
        self._vars[name] = value

    def keys(self):
        return self._vars.keys()

    def __contains__(self, name):
        return name in self._vars


_global_scope = Scope()


@contextlib.contextmanager
def scope_guard(scope):
    global _global_scope
    old = _global_scope
    _global_scope = scope
    try:
        yield
    finally:
        _global_scope = old


def _forward_env(program: Program, param_vals: dict, feed_vals: dict, key):
    """Execute the op list symbolically; returns env name->value."""
    env = {}
    env.update(param_vals)
    env.update(feed_vals)
    kcount = 0
    for op in program.global_block().ops:
        vals = []
        for kind, payload in op.leaves:
            if kind == "var":
                if payload.name not in env:
                    raise KeyError(
                        f"variable {payload.name!r} used before definition "
                        f"(op {op.type})")
                vals.append(env[payload.name])
            else:
                vals.append(payload)
        args, kwargs = jax.tree_util.tree_unflatten(op.treedef, vals)
        if op.stochastic and kwargs.get("key") is None:
            kwargs = dict(kwargs)
            kwargs["key"] = jax.random.fold_in(key, kcount)
            kcount += 1
        out = op.fn(*args, **kwargs)
        outs = list(out) if op.multi else [out]
        for v, o in zip(op.out_vars, outs):
            env[v.name] = o
    return env


class Executor:
    def __init__(self, place=None):
        self.place = place if place is not None else _expected_place()
        self._cache = {}

    def close(self):
        self._cache.clear()

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_program_cache=True):
        program = program if program is not None else default_main_program()
        from .io import LoadedProgram
        if isinstance(program, LoadedProgram):  # deserialized artifact
            outs = program(feed or {})
            if fetch_list:
                names = [v.name if isinstance(v, Variable) else str(v)
                         for v in fetch_list]
                idx = {n: i for i, n in enumerate(program.fetch_names)}
                outs = [outs[idx[n]] for n in names]
            return [np.asarray(o) for o in outs] if return_numpy else outs
        if hasattr(program, "_program"):  # CompiledProgram
            program = program._program
        scope = scope if scope is not None else _global_scope
        feed = feed or {}

        # startup program: run initializers host-side into the scope
        if program.initializers and not program.global_block().ops \
                and program._loss is None:
            for var, init in program.initializers:
                if scope.find_var(var.name) is None:
                    from ..nn import initializer as I
                    fn = init or I.XavierUniform()
                    scope.set(var.name, jnp.asarray(fn(var.shape, var.dtype)))
            return []

        fetch_list = fetch_list or []
        fetch_vars = [v for v in fetch_list]
        fetch_names = [v.name if isinstance(v, Variable) else str(v)
                       for v in fetch_vars]

        feed_vals = {}
        for name, val in feed.items():
            if isinstance(val, Tensor):
                val = val._value
            feed_vals[name] = jnp.asarray(np.asarray(val)) \
                if not isinstance(val, jax.Array) else val

        # parameters currently in scope (created by startup program)
        param_names = sorted(
            v.name for v in program.global_block().vars.values()
            if v.persistable and scope.find_var(v.name) is not None)
        # lazily initialize any persistable that startup didn't cover
        for v in program.global_block().vars.values():
            if v.persistable and scope.find_var(v.name) is None \
                    and v.initializer is not None:
                scope.set(v.name, jnp.asarray(v.initializer(v.shape, v.dtype)))
                param_names.append(v.name)
        param_names = sorted(set(param_names))
        param_vals = {n: scope.find_var(n) for n in param_names}

        opt_states = {}
        if program._optimizers:
            for i, (opt, loss, params) in enumerate(program._optimizers):
                # program-scoped key: the scope is global, and two
                # programs sharing "@opt_state_0" once handed one
                # program's Adam moments to another's parameters
                sname = f"@opt_state_{getattr(program, '_uid', 0)}_{i}"
                st = scope.find_var(sname)
                if st is None:
                    ptree = {p.name: param_vals[p.name] for p in params}
                    st = opt.functional_init(ptree)
                    scope.set(sname, st)
                opt_states[sname] = st

        key_shapes = tuple(sorted((n, tuple(v.shape), str(v.dtype))
                                  for n, v in feed_vals.items()))
        # optimizer count is part of the key: the traced step bakes in the
        # update ops, and infer_from_dataset runs the same program with
        # optimizers suspended — those two steps must not share a cache slot
        cache_key = (getattr(program, "_uid", id(program)),
                     program._version, key_shapes,
                     tuple(fetch_names), len(program._optimizers))
        compiled = self._cache.get(cache_key) if use_program_cache else None

        if compiled is None:
            trainable = {p.name for _, _, params in program._optimizers
                         for p in params}

            def step(param_vals, opt_states, feed_vals, key):
                if program._optimizers:
                    # N minimize() calls compose the way the reference's
                    # op order does (fluid/optimizer.py:740): ONE forward,
                    # every backward at the pre-update parameter values,
                    # then the update ops in append order (a later
                    # optimizer sharing a param reads the updated value).
                    # GAN-style D/G programs are the standard use.
                    out_params = dict(param_vals)
                    new_states = dict(opt_states)
                    env = None
                    for i, (opt, loss_var, params) in enumerate(
                            program._optimizers):
                        pnames = [p.name for p in params]

                        def loss_fn(ptree, _loss=loss_var):
                            pv = dict(param_vals)
                            pv.update(ptree)
                            env = _forward_env(program, pv, feed_vals, key)
                            return env[_loss.name], env

                        ptree = {n: param_vals[n] for n in pnames}
                        grads, env_i = jax.grad(
                            loss_fn, has_aux=True)(ptree)
                        if env is None:
                            env = env_i
                        sname = (f"@opt_state_"
                                 f"{getattr(program, '_uid', 0)}_{i}")
                        lr = opt.get_lr() \
                            if not hasattr(opt._lr, "lr_at") else None
                        if opt._grad_clip is not None and hasattr(
                                opt._grad_clip, "clip_tree"):
                            grads = opt._grad_clip.clip_tree(grads)
                        cur = {n: out_params[n] for n in pnames}
                        new_p, new_state = opt.functional_update(
                            cur, grads, opt_states[sname], lr=lr)
                        out_params.update(new_p)
                        new_states[sname] = new_state
                        for p in params:
                            env[p.name + "@GRAD"] = grads[p.name]
                else:
                    grad_targets = [n[:-len("@GRAD")] for n in fetch_names
                                    if n.endswith("@GRAD")]
                    loss_var = getattr(program, "_loss", None)
                    if grad_targets and loss_var is not None:
                        # append_backward/gradients() without an optimizer:
                        # differentiate the marked loss w.r.t. the targets —
                        # parameters or feed/data variables alike
                        def loss_fn(dtree):
                            pv = dict(param_vals)
                            fv = dict(feed_vals)
                            for n, v in dtree.items():
                                (fv if n in fv else pv)[n] = v
                            env = _forward_env(program, pv, fv, key)
                            return env[loss_var.name], env

                        dtree = {n: (feed_vals[n] if n in feed_vals
                                     else param_vals[n])
                                 for n in grad_targets}
                        grads, env = jax.grad(loss_fn, has_aux=True)(dtree)
                        for n, g in grads.items():
                            env[n + "@GRAD"] = g
                    else:
                        env = _forward_env(program, param_vals, feed_vals, key)
                    out_params = param_vals
                    new_states = opt_states
                fetches = []
                for name in fetch_names:
                    if name not in env:
                        raise KeyError(f"fetch target {name!r} not produced")
                    fetches.append(env[name])
                return fetches, out_params, new_states

            compiled = jax.jit(step)
            self._cache[cache_key] = compiled

        from ..core import rng
        fetches, new_params, new_states = compiled(param_vals, opt_states,
                                                   feed_vals, rng.next_key())
        for n, v in new_params.items():
            scope.set(n, v)
        for n, v in new_states.items():
            scope.set(n, v)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Drive a Program over a fluid Dataset (ref: executor.py
        train_from_dataset backed by the C++ MultiTrainer). The C++
        trainer-thread pipeline is replaced by the jitted whole-Program
        step: each MultiSlot batch becomes one compiled-step call, and
        XLA's async dispatch overlaps host parsing with device compute —
        the same overlap the reference got from feed threads."""
        if dataset is None:
            raise ValueError("dataset is required")
        program = program if program is not None else default_main_program()
        feed_names = {v.name for v in program.global_block().vars.values()
                      if not v.persistable}
        step = 0
        for batch in dataset:
            feed = {n: v for n, v in batch.items() if n in feed_names} \
                or dict(batch)
            outs = self.run(program, feed=feed, fetch_list=fetch_list,
                            scope=scope)
            if debug and fetch_list and step % max(print_period, 1) == 0:
                labels = fetch_info or [
                    getattr(v, "name", str(v)) for v in fetch_list]
                msg = ", ".join(f"{lbl}={np.asarray(o).ravel()[:4]}"
                                for lbl, o in zip(labels, outs))
                _debug_logger().info("step %s: %s", step, msg)
            step += 1

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Forward-only pass over a Dataset (ref: executor.py
        infer_from_dataset = train_from_dataset with updates disabled):
        the program's optimizer ops are suspended for the duration so
        evaluation never mutates the trained weights."""
        program = program if program is not None else default_main_program()
        saved = program._optimizers
        program._optimizers = []
        try:
            return self.train_from_dataset(program, dataset, scope, thread,
                                           debug, fetch_list, fetch_info,
                                           print_period)
        finally:
            program._optimizers = saved
