"""Static-graph inference model save/load.

Reference: python/paddle/fluid/io.py save_inference_model/load_inference_model
(serializes the pruned ProgramDesc + params). TPU-first: we serialize the
scope's parameter arrays plus a spec of feed/fetch names; at load time the
caller re-binds them against a rebuilt program (programs are python-defined
here, not a portable protobuf — the deployable artifact is params + jitted
callable via paddle_tpu.jit.save / inference.Predictor).
"""
from __future__ import annotations

import os

from ..core.tensor import Tensor
from ..framework.io import load as fload
from ..framework.io import save as fsave
from .executor import _global_scope
from .program import Variable, default_main_program


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None, **kwargs):
    program = program or default_main_program()
    scope = _global_scope
    state = {}
    for v in program.global_block().vars.values():
        if v.persistable and scope.find_var(v.name) is not None:
            state[v.name] = Tensor(scope.find_var(v.name))
    spec = {
        "feed_names": [v.name if isinstance(v, Variable) else str(v)
                       for v in feed_vars],
        "fetch_names": [v.name if isinstance(v, Variable) else str(v)
                        for v in fetch_vars],
    }
    d = os.path.dirname(path_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    fsave({"params": state, "spec": spec}, path_prefix + ".pdmodel")
    return path_prefix + ".pdmodel"


def load_inference_model(path_prefix, executor, **kwargs):
    payload = fload(path_prefix + ".pdmodel")
    scope = _global_scope
    for name, t in payload["params"].items():
        scope.set(name, t._value)
    spec = payload["spec"]
    return spec["feed_names"], spec["fetch_names"]
