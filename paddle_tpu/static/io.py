"""Static-graph inference model save/load.

Reference: python/paddle/fluid/io.py:1198 save_inference_model /
load_inference_model (serializes the pruned ProgramDesc + params).
TPU-first: the Program's forward is lowered to one pure function
`(params, *feeds) -> fetches` and exported as a serialized StableHLO
module via jax.export — the SAME (.pdmodel, .pdiparams) artifact pair
jit.save produces, so a static-graph model deploys through
inference.create_predictor / a fresh process with no Program rebuild.
"""
from __future__ import annotations

import os

import jax
import numpy as np

from ..core.tensor import Tensor
from ..jit import read_artifact, write_artifact
from .executor import _global_scope
from .program import Variable, default_main_program


class LoadedProgram:
    """Runnable handle for a loaded inference artifact (plays the role of
    the reference's returned inference_program). Executor.run accepts it,
    or call it directly: fetches = loaded(feed_dict)."""

    def __init__(self, exported, params, meta):
        self._exported = exported
        self._params = params
        self.feed_names = list(meta["feed_names"])
        self.fetch_names = list(meta["fetch_names"])
        self._call = jax.jit(exported.call)

    def __call__(self, feed):
        import jax.numpy as jnp
        xs = []
        for n in self.feed_names:
            v = feed[n]
            v = v._value if isinstance(v, Tensor) else jnp.asarray(
                np.asarray(v))
            xs.append(v)
        outs = self._call(self._params, *xs)
        return list(outs) if isinstance(outs, (list, tuple)) else [outs]


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None, **kwargs):
    """Lower the Program's forward to (params, *feeds) -> fetches and write
    the StableHLO deployment artifact (ref: fluid/io.py:1198)."""
    from jax import export as jexport

    from .executor import _forward_env

    program = program or default_main_program()
    scope = _global_scope
    feed_names = [v.name if isinstance(v, Variable) else str(v)
                  for v in feed_vars]
    fetch_names = [v.name if isinstance(v, Variable) else str(v)
                   for v in fetch_vars]

    params = {}
    for v in program.global_block().vars.values():
        if v.persistable and scope.find_var(v.name) is not None:
            val = scope.find_var(v.name)
            params[v.name] = val._value if isinstance(val, Tensor) else val

    from ..jit import _symbolic_dims

    by_name = {n: v for n, v in zip(
        feed_names,
        [v for v in feed_vars if isinstance(v, Variable)] or feed_vars)}

    def shape_of(v):
        return v.shape if isinstance(v, Variable) else np.asarray(v).shape

    def is_dyn(d):
        return d is None or (isinstance(d, int) and d < 0)

    # all dynamic feed dims share ONE symbolic scope (jax.export rejects
    # scope mixing — per-dim scopes broke multi-dynamic-dim programs)
    n_dyn = sum(1 for n in feed_names for d in shape_of(by_name[n])
                if is_dyn(d))
    syms = iter(_symbolic_dims(n_dyn))
    feed_specs = []
    for n in feed_names:
        v = by_name.get(n)
        dims = tuple(next(syms) if is_dyn(d) else d for d in shape_of(v))
        dtype = v.dtype if isinstance(v, Variable) else np.asarray(v).dtype
        feed_specs.append(jax.ShapeDtypeStruct(dims, dtype))

    key = jax.random.key(0)  # inference: stochastic ops run is_test

    def pure(params, *feeds):
        fv = dict(zip(feed_names, feeds))
        env = _forward_env(program, params, fv, key)
        return tuple(env[n] for n in fetch_names)

    p_specs = jax.tree_util.tree_map(
        lambda v: jax.ShapeDtypeStruct(np.asarray(v).shape,
                                       np.asarray(v).dtype), params)
    jf = jax.jit(pure)
    try:
        exported = jexport.export(jf, platforms=("cpu", "tpu"))(
            p_specs, *feed_specs)
    except Exception:
        exported = jexport.export(jf)(p_specs, *feed_specs)

    meta = {
        "format": "paddle_tpu.static/1",
        "feed_names": feed_names,
        "fetch_names": fetch_names,
        "platforms": list(exported.platforms),
    }
    d = os.path.dirname(path_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    return write_artifact(path_prefix, exported, params, {}, meta)


def load_inference_model(path_prefix, executor, **kwargs):
    """Load the artifact back (ref returns [program, feeds, fetches]); the
    returned LoadedProgram runs standalone — no Program rebuild, no model
    code. Also primes the scope with the saved params for legacy flows."""
    exported, params, _, meta = read_artifact(path_prefix)
    scope = _global_scope
    for name, v in params.items():
        scope.set(name, v)
    loaded = LoadedProgram(exported, params, meta)
    return loaded, loaded.feed_names, loaded.fetch_names
