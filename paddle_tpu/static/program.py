"""Program / Block / Variable — the static-graph IR.

Reference: python/paddle/fluid/framework.py (Program, Block, Operator,
Variable) + backward.py (append_backward). TPU-first rework: an op node stores
the SAME pure JAX function the eager path runs, plus the arg tree with
Variables as holes. Lowering (executor.py) walks the op list to build one pure
python function over (params, feeds) and jits it — the whole Program becomes a
single XLA computation; append_backward marks the loss so lowering adds
jax.grad + optimizer update into the same compiled step (replacing the
reference's per-op grad-op graph rewrite).
"""
from __future__ import annotations

import contextlib
import itertools
from typing import Optional

import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core import mode, unique_name
from ..core.tensor import Tensor


class Variable:
    def __init__(self, block, name, shape, dtype, persistable=False,
                 is_data=False, stop_gradient=True, initializer=None,
                 trainable=False):
        self.block = block
        self.name = name
        self.shape = list(shape)
        self.dtype = dtype
        self.persistable = persistable
        self.is_data = is_data
        self.stop_gradient = stop_gradient
        self.initializer = initializer
        self.trainable = trainable
        self.op = None  # producer OpNode
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.grad = None  # populated with grad Variable by append_backward

    @property
    def ndim(self):
        return len(self.shape)

    def aval(self, dim_map=None):
        dim_map = dim_map or {}
        shape = []
        for i, s in enumerate(self.shape):
            if i in dim_map:
                shape.append(int(dim_map[i]))
            elif s is None or s < 0:
                shape.append(1)  # unknown dim placeholder (shape-infer only)
            else:
                shape.append(int(s))
        return jax.ShapeDtypeStruct(tuple(shape), self.dtype)

    def astype(self, dtype):
        from .. import ops
        return ops.cast(self, dtype)

    cast = astype

    def __hash__(self):
        return id(self)

    def __repr__(self):
        kind = "data" if self.is_data else ("param" if self.persistable else "tmp")
        return f"Variable({self.name}, shape={self.shape}, {kind})"


def _patch_variable():
    from .. import ops

    def binop(fn, reverse=False):
        def method(self, other):
            return fn(other, self) if reverse else fn(self, other)
        return method

    V = Variable
    V.__add__ = binop(ops.add)
    V.__radd__ = binop(ops.add, True)
    V.__sub__ = binop(ops.subtract)
    V.__rsub__ = binop(ops.subtract, True)
    V.__mul__ = binop(ops.multiply)
    V.__rmul__ = binop(ops.multiply, True)
    V.__truediv__ = binop(ops.divide)
    V.__rtruediv__ = binop(ops.divide, True)
    V.__pow__ = binop(ops.pow)
    V.__matmul__ = binop(ops.matmul)
    V.__neg__ = lambda self: ops.neg(self)
    V.__lt__ = binop(ops.less_than)
    V.__le__ = binop(ops.less_equal)
    V.__gt__ = binop(ops.greater_than)
    V.__ge__ = binop(ops.greater_equal)
    V.__eq__ = binop(ops.equal)
    V.__ne__ = binop(ops.not_equal)
    for name in ("sum", "mean", "max", "min", "reshape", "transpose", "matmul",
                 "flatten", "squeeze", "unsqueeze", "cast", "clip", "sqrt",
                 "exp", "log", "tanh", "abs", "square"):
        setattr(V, name, (lambda f: lambda self, *a, **k: f(self, *a, **k))(
            getattr(ops, name)))


class OpNode:
    __slots__ = ("type", "fn", "leaves", "treedef", "out_vars", "stochastic",
                 "multi")

    def __init__(self, type_, fn, leaves, treedef, out_vars, stochastic, multi):
        self.type = type_
        self.fn = fn
        # each leaf: ("var", Variable) | ("const", raw value)
        self.leaves = leaves
        self.treedef = treedef
        self.out_vars = out_vars
        self.stochastic = stochastic
        self.multi = multi


class Block:
    def __init__(self, program, idx=0):
        self.program = program
        self.idx = idx
        self.vars = {}
        self.ops = []

    def create_var(self, name=None, shape=(), dtype="float32", **kw):
        name = name or unique_name.generate("tmp")
        v = Variable(self, name, shape, dtype_mod.convert_dtype(dtype), **kw)
        self.vars[name] = v
        return v

    def create_parameter(self, shape, dtype, name=None, initializer=None,
                         trainable=True, **kw):
        name = name or unique_name.generate("param")
        v = Variable(self, name, shape, dtype_mod.convert_dtype(dtype),
                     persistable=True, stop_gradient=not trainable,
                     initializer=initializer, trainable=trainable)
        self.vars[name] = v
        # record the init in the startup program (ref: initializer appends
        # an init op to startup)
        startup = default_startup_program()
        startup.initializers.append((v, initializer))
        return v

    def var(self, name):
        return self.vars[name]

    def all_parameters(self):
        return [v for v in self.vars.values() if v.persistable and v.trainable]


_program_uid = itertools.count()


class Program:
    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.random_seed = 0
        self.initializers = []  # startup-only: [(Variable, initializer)]
        self._loss = None
        self._optimizers = []  # [(optimizer, loss_var, param_vars)]
        self._version = 0
        # executor caches key on this, NOT id(): CPython recycles ids of
        # collected Programs, which once served a stale compiled step to a
        # fresh Program that happened to reuse the address
        self._uid = next(_program_uid)

    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[-1]

    def all_parameters(self):
        return self.global_block().all_parameters()

    def data_vars(self):
        return [v for v in self.global_block().vars.values() if v.is_data]

    def list_vars(self):
        return list(self.global_block().vars.values())

    def clone(self, for_test=False):
        # programs are append-only descriptors; a clone shares structure but
        # drops the optimizer ops when for_test (ref: Program.clone)
        import copy
        p = copy.copy(self)
        if for_test:
            p = Program.__new__(Program)
            p.__dict__.update(self.__dict__)
            p._optimizers = []
            p._loss = self._loss
        # a clone is a DIFFERENT executable: with a shared uid, the
        # executor would serve the training program's cached step (with
        # its optimizer update) to the for_test clone
        p._uid = next(_program_uid)
        return p

    def __repr__(self):
        ops = "\n".join(f"  {op.type} -> {[v.name for v in op.out_vars]}"
                        for op in self.global_block().ops)
        return f"Program(\n{ops}\n)"


_default_main = Program()
_default_startup = Program()


def default_main_program() -> Program:
    return _default_main


def default_startup_program() -> Program:
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _default_main, _default_startup
    old_main, old_startup = _default_main, _default_startup
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    try:
        yield
    finally:
        _default_main, _default_startup = old_main, old_startup


@contextlib.contextmanager
def name_scope(prefix):
    with unique_name.guard(prefix):
        yield


def data(name, shape, dtype="float32", lod_level=0):
    """paddle.static.data — feed placeholder."""
    prog = default_main_program()
    v = Variable(prog.global_block(), name, shape,
                 dtype_mod.convert_dtype(dtype), is_data=True)
    prog.global_block().vars[name] = v
    return v


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.name = name

    @classmethod
    def from_tensor(cls, t, name=None):
        return cls(t.shape, t.dtype, name)


# ---------------------------------------------------------------------------
# op capture hook (registered into core.mode)
# ---------------------------------------------------------------------------

def _is_leaf(x):
    return isinstance(x, (Variable, Tensor))


def _append_op(opname, fn, args, kwargs, meta):
    prog = default_main_program()
    block = prog.current_block()
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_leaf)

    spec = []
    avals = []
    any_diff = False
    for l in leaves:
        if isinstance(l, Variable):
            spec.append(("var", l))
            avals.append(l.aval())
            if not l.stop_gradient:
                any_diff = True
        elif isinstance(l, Tensor):
            spec.append(("const", l._value))
            avals.append(l._value)
        else:
            spec.append(("const", l))
            avals.append(l)

    # shape inference via eval_shape (replaces InferShape). Only the
    # Variable slots become eval_shape ARGUMENTS — string/int/None
    # constants (data_format, strides, ...) must stay baked in the
    # closure: eval_shape rejects non-array args, and turning an int
    # stride into a traced scalar would break ops that need it static.
    var_idx = [i for i, (kind, _) in enumerate(spec) if kind == "var"]
    base_vals = list(avals)

    def infer(*var_avals):
        vals = list(base_vals)
        for i, va in zip(var_idx, var_avals):
            vals[i] = va
        a2, k2 = jax.tree_util.tree_unflatten(treedef, vals)
        if meta.get("stochastic"):
            k2 = dict(k2)
            k2["key"] = jax.random.key(0)
        return fn(*a2, **k2)

    try:
        out_shape = jax.eval_shape(infer, *[avals[i] for i in var_idx])
    except Exception as e:
        import warnings
        warnings.warn(
            f"static shape inference failed for op '{opname}' "
            f"({type(e).__name__}: {str(e)[:120]}); recording scalar "
            "shape — downstream layers sized from this output will "
            "misbehave", stacklevel=2)
        out_shape = jax.ShapeDtypeStruct((), jnp.float32)

    multi = isinstance(out_shape, (tuple, list))
    outs_meta = list(out_shape) if multi else [out_shape]
    out_vars = []
    for om in outs_meta:
        shape = list(getattr(om, "shape", ()))
        dt = getattr(om, "dtype", jnp.float32)
        v = block.create_var(unique_name.generate(opname), shape, dt)
        v.stop_gradient = (not any_diff) or bool(meta.get("nondiff", False))
        out_vars.append(v)

    node = OpNode(opname, fn, spec, treedef, out_vars,
                  bool(meta.get("stochastic")), multi)
    for v in out_vars:
        v.op = node
    block.ops.append(node)
    prog._version += 1
    if multi:
        return tuple(out_vars)
    return out_vars[0]


mode.register_static_hook(_append_op)
_patch_variable()


# ---------------------------------------------------------------------------
# backward + minimize capture
# ---------------------------------------------------------------------------

def append_backward(loss, parameter_list=None, no_grad_set=None, callbacks=None):
    """Mark loss; grads materialize at lowering via jax.grad (ref:
    python/paddle/fluid/backward.py append_backward)."""
    prog = default_main_program()
    prog._loss = loss
    params = parameter_list or prog.all_parameters()
    result = []
    for p in params:
        g = Variable(prog.global_block(), p.name + "@GRAD", p.shape, p.dtype)
        prog.global_block().vars[g.name] = g
        p.grad = g
        result.append((p, g))
    return result


def _minimize(optimizer, loss, parameter_list=None):
    prog = default_main_program()
    if parameter_list is not None:
        # the fluid API accepts Variables or their names
        params = [prog.global_block().var(p) if isinstance(p, str) else p
                  for p in parameter_list]
    else:
        params = prog.all_parameters()
    pgs = append_backward(loss, params)
    prog._optimizers.append((optimizer, loss, params))
    return pgs


def global_scope():
    from .executor import _global_scope
    return _global_scope
