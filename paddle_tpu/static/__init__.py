"""paddle.static namespace — Program/Executor static graph.

Reference: python/paddle/static/ + python/paddle/fluid/framework.py,
executor.py. Full implementation in program.py / executor.py.
"""
from __future__ import annotations

import contextlib

from ..core.mode import in_dygraph_mode  # noqa: F401
from .program import (  # noqa: F401
    Program, Variable, append_backward, data, default_main_program,
    default_startup_program, global_scope, name_scope, program_guard,
    InputSpec,
)
from .executor import Executor, scope_guard  # noqa: F401
from . import nn  # noqa: F401
from .io import load_inference_model, save_inference_model  # noqa: F401


class CompiledProgram:
    """Shim: programs are always XLA-compiled at Executor.run (ref:
    python/paddle/fluid/compiler.py CompiledProgram.with_data_parallel)."""

    def __init__(self, program, build_strategy=None):
        self._program = program

    def with_data_parallel(self, loss_name=None, **kw):
        return self


class BuildStrategy:
    pass


class ExecutionStrategy:
    pass


class ParallelExecutor(CompiledProgram):
    """1.x multi-device executor shim: devices come from the jax Mesh, and
    the single Executor already compiles to all of them (ref:
    fluid/parallel_executor.py)."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, **kw):
        from .program import default_main_program
        super().__init__(main_program or default_main_program())


def cpu_places(device_count=None):
    from ..core.place import CPUPlace
    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """CUDA name kept for parity; places map to the TPU devices."""
    import jax

    from ..core.place import TPUPlace
    ids = device_ids if device_ids is not None \
        else range(len(jax.devices()))
    return [TPUPlace(i) for i in ids]


def cuda_pinned_places(device_count=None):
    """Pinned-host staging places (ref: framework.py cuda_pinned_places);
    host arrays are already staged via the native arena on this stack."""
    from ..core.place import CUDAPinnedPlace
    n = device_count or 1
    return [CUDAPinnedPlace() for _ in range(n)]


@contextlib.contextmanager
def device_guard(device=None):
    """Pin ops created in the block to a device (ref: framework.py
    device_guard). Under XLA, placement is whole-computation: the guard
    records the request so Program lowering can honor host-pinned
    sections, and accepts the reference's "cpu"/"gpu:N" strings."""
    from .program import default_main_program
    prog = default_main_program()
    prev = getattr(prog, "_current_device", None)
    prog._current_device = device
    try:
        yield
    finally:
        prog._current_device = prev


def Print(input, first_n=-1, message=None, summarize=20,  # noqa: A002
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both"):
    """Debug print op (ref: fluid/layers/control_flow.py Print). Eager
    tensors print immediately; traced values print at run via jax.debug;
    program Variables pass through (their value only exists at Executor.run)."""
    import numpy as np

    from ..core.tensor import Tensor
    if isinstance(input, Tensor):
        import jax
        val = input._value
        if isinstance(val, jax.core.Tracer):
            jax.debug.print((message or "") + "{x}", x=val)
        else:
            print((message or "")  # cli-print: the Print op's contract
                  + str(np.asarray(val).ravel()[:summarize]))
    return input


class WeightNormParamAttr:
    """Param attr requesting weight normalization (ref: fluid/param_attr.py
    WeightNormParamAttr); consumed by nn.utils.weight_norm."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.trainable = trainable


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Static autodiff: d(targets)/d(inputs) (ref:
    python/paddle/fluid/backward.py gradients). Marks the target as the
    program loss; grad Variables materialize at Executor lowering through the
    same program-level jax.grad as append_backward."""
    from .program import Variable, default_main_program
    tgt = targets[0] if isinstance(targets, (list, tuple)) else targets
    prog = default_main_program()
    prog._loss = tgt
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    out = []
    for v in ins:
        g = Variable(prog.global_block(), v.name + "@GRAD", v.shape, v.dtype)
        prog.global_block().vars[g.name] = g
        v.grad = g
        out.append(g)
    return out


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-callback op (ref: fluid/layers/nn.py py_func). Eager values run
    `func` immediately; traced values lower to jax.pure_callback with `out`
    providing the result shape/dtype."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..core.tensor import Tensor
    xs = x if isinstance(x, (list, tuple)) else [x]
    vals = [v._value if isinstance(v, Tensor) else v for v in xs]
    traced = any(isinstance(v, jax.core.Tracer) for v in vals)
    if traced:
        out_dtype = (out._value.dtype if isinstance(out, Tensor)
                     else np.dtype(getattr(out, "dtype", np.float32)))
        res = jax.pure_callback(
            lambda *a: np.asarray(func(*[np.asarray(v) for v in a]),
                                  out_dtype),
            jax.ShapeDtypeStruct(tuple(out.shape), out_dtype), *vals)
        return Tensor(res)
    res = func(*[np.asarray(v) for v in vals])
    if isinstance(res, Tensor):
        return res
    return Tensor(jnp.asarray(np.asarray(res)))


def _program_state(program):
    """Persistable var values for a program, read from the global Scope
    (parameters live in the scope after the startup program runs)."""
    import numpy as np

    from .program import global_scope
    scope = global_scope()
    state = {}
    for v in program.global_block().vars.values():
        if getattr(v, "persistable", False):
            val = scope.find_var(v.name)
            if val is not None:
                state[v.name] = np.asarray(val)
    return state


def save(program, model_path, protocol=4, **kw):
    """Persist all persistable program vars (ref: fluid/io.py save)."""
    from ..framework.io import save as _save
    _save(_program_state(program), model_path + ".pdparams")


def load(program, model_path, executor=None, var_list=None):
    from ..framework.io import load as _load
    state = _load(model_path + ".pdparams")
    set_program_state(program, state)
    return state


def load_program_state(model_path, var_list=None):
    from ..framework.io import load as _load
    return _load(model_path + ".pdparams")


def set_program_state(program, state_dict):
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    from .program import global_scope
    scope = global_scope()
    for name, val in state_dict.items():
        if isinstance(val, Tensor):
            val = val._value
        scope.set(name, jnp.asarray(val))
    program._version = getattr(program, "_version", 0) + 1


from .executor import Scope  # noqa: E402,F401
from ..fluid.layers import create_global_var, create_parameter  # noqa: E402,F401
