"""paddle.static namespace — Program/Executor static graph.

Reference: python/paddle/static/ + python/paddle/fluid/framework.py,
executor.py. Full implementation in program.py / executor.py.
"""
from __future__ import annotations

from ..core.mode import in_dygraph_mode  # noqa: F401
from .program import (  # noqa: F401
    Program, Variable, append_backward, data, default_main_program,
    default_startup_program, global_scope, name_scope, program_guard,
    InputSpec,
)
from .executor import Executor, scope_guard  # noqa: F401
from . import nn  # noqa: F401
from .io import load_inference_model, save_inference_model  # noqa: F401


class CompiledProgram:
    """Shim: programs are always XLA-compiled at Executor.run (ref:
    python/paddle/fluid/compiler.py CompiledProgram.with_data_parallel)."""

    def __init__(self, program, build_strategy=None):
        self._program = program

    def with_data_parallel(self, loss_name=None, **kw):
        return self


class BuildStrategy:
    pass


class ExecutionStrategy:
    pass
