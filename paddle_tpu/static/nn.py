"""paddle.static.nn — fluid-style functional layers for static graphs.

Reference: python/paddle/fluid/layers/nn.py (fc, conv2d, ...) — each creates
parameters in the current program + appends compute ops.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from ..core import unique_name
from ..core.param_attr import ParamAttr
from ..nn import initializer as I
from .program import default_main_program


def _create_param(shape, dtype, attr, is_bias=False, default_init=None):
    attr = ParamAttr._to_attr(attr)
    if attr is False:
        return None
    init = attr.initializer or default_init or (
        I.Constant(0.0) if is_bias else I.XavierUniform())
    block = default_main_program().global_block()
    v = block.create_parameter(shape, dtype, name=attr.name,
                               initializer=init, trainable=attr.trainable)
    v.optimize_attr = {"learning_rate": attr.learning_rate}
    v.regularizer = attr.regularizer
    v.stop_gradient = not attr.trainable
    return v


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None, param_attr=None, act=None):
    # `param_attr`/`act` are the fluid 1.x spellings of
    # `weight_attr`/`activation`
    weight_attr = weight_attr or param_attr
    activation = activation or act
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    if len(x.shape) > num_flatten_dims + 1:
        x = ops.flatten(x, num_flatten_dims, -1) if num_flatten_dims > 0 else x
    w = _create_param((in_dim, size), "float32", weight_attr)
    b = _create_param((size,), "float32", bias_attr, is_bias=True)
    out = ops.linear(x, w, b)
    if activation:
        out = getattr(ops, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,  # noqa: A002
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None, use_cudnn=True):
    ksize = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    fan_in = (cin // groups) * int(np.prod(ksize))
    w = _create_param((num_filters, cin // groups) + tuple(ksize), "float32",
                      param_attr, default_init=I.Normal(0.0, (2.0 / fan_in) ** 0.5))
    b = _create_param((num_filters,), "float32", bias_attr, is_bias=True)
    out = ops.conv2d(input, w, b, stride, padding, dilation, groups, data_format)
    if act:
        out = getattr(ops, act)(out)
    return out


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,  # noqa: A002
           pool_padding=0, global_pooling=False, ceil_mode=False,
           data_format="NCHW", name=None, use_cudnn=True, exclusive=True):
    if global_pooling:
        return ops.adaptive_avg_pool2d(input, 1) if pool_type == "avg" \
            else ops.adaptive_max_pool2d(input, 1)
    if pool_type == "max":
        return ops.max_pool2d(input, pool_size, pool_stride, pool_padding,
                              ceil_mode, data_format)
    return ops.avg_pool2d(input, pool_size, pool_stride, pool_padding,
                          ceil_mode, exclusive, None, data_format)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,  # noqa: A002
               bias_attr=None, data_layout="NCHW", is_test=False,
               use_global_stats=False, name=None, **kw):
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = _create_param((c,), "float32", param_attr,
                          default_init=I.Constant(1.0))
    bias = _create_param((c,), "float32", bias_attr, is_bias=True)
    mean = _create_param((c,), "float32", ParamAttr(
        name=unique_name.generate("bn_mean"), trainable=False),
        default_init=I.Constant(0.0))
    var = _create_param((c,), "float32", ParamAttr(
        name=unique_name.generate("bn_var"), trainable=False),
        default_init=I.Constant(1.0))
    out, _, _ = ops.batch_norm(input, mean, var, scale, bias,
                               training=not is_test, momentum=momentum,
                               epsilon=epsilon, data_format=data_layout,
                               use_global_stats=use_global_stats)
    if act:
        out = getattr(ops, act)(out)
    return out


def embedding(input, size, padding_idx=None, param_attr=None, dtype="float32",  # noqa: A002
              is_sparse=False, name=None):
    w = _create_param(tuple(size), dtype, param_attr,
                      default_init=I.Normal(0.0, 1.0))
    return ops.embedding(input, w, padding_idx=padding_idx)


def dropout(x, dropout_prob=0.5, is_test=False, name=None, **kw):
    return ops.dropout(x, p=dropout_prob, training=not is_test)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,  # noqa: A002
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    norm_shape = tuple(input.shape[begin_norm_axis:])
    w = _create_param(norm_shape, "float32", param_attr,
                      default_init=I.Constant(1.0)) if scale else None
    b = _create_param(norm_shape, "float32", bias_attr, is_bias=True) \
        if shift else None
    out = ops.layer_norm(input, w, b, epsilon,
                         normalized_ndim=len(norm_shape))
    if act:
        out = getattr(ops, act)(out)
    return out


# --------------------------------------------------------------------------
# control flow (re-exported: ops/control.py lowers to lax.cond/while/switch;
# they trace fine inside static programs through the op-capture hook)
# ref: python/paddle/fluid/layers/control_flow.py
from ..ops.control import case, cond, switch_case, while_loop  # noqa: E402,F401


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """ref: fluid/layers/tensor.py create_parameter."""
    attr = ParamAttr._to_attr(attr) if attr is not None else ParamAttr(name=name)
    return _create_param(tuple(shape), dtype, attr, is_bias=is_bias,
                         default_init=default_initializer)


def prelu(x, mode="all", param_attr=None, name=None):
    """ref: fluid/layers/nn.py prelu — alpha shape by mode."""
    if mode == "all":
        shape = (1,)
    elif mode == "channel":
        shape = (x.shape[1],)
    else:  # element
        shape = tuple(x.shape[1:])
    a = _create_param(shape, "float32", param_attr,
                      default_init=I.Constant(0.25))
    return ops.prelu(x, a)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,  # noqa: A002
                  name=None):
    c = input.shape[1]
    w = _create_param((c,), "float32", param_attr,
                      default_init=I.Constant(1.0)) \
        if param_attr is not False else None
    b = _create_param((c,), "float32", bias_attr, is_bias=True) \
        if bias_attr is not False else None
    return ops.instance_norm(input, w, b, epsilon)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,  # noqa: A002
               act=None, data_layout="NCHW", name=None):
    c = input.shape[1]
    w = _create_param((c,), "float32", param_attr,
                      default_init=I.Constant(1.0)) \
        if param_attr is not False else None
    b = _create_param((c,), "float32", bias_attr, is_bias=True) \
        if bias_attr is not False else None
    out = ops.group_norm(input, groups, w, b, epsilon)
    if act:
        out = getattr(ops, act)(out)
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """ref: fluid/layers/nn.py spectral_norm — power iteration with
    persistable u/v vectors."""
    import jax.numpy as jnp
    w = weight
    h = w.shape[dim]
    u = _create_param((h,), "float32", ParamAttr(name=None),
                      default_init=I.Normal(0.0, 1.0))
    wm = ops.reshape(ops.transpose(
        w, [dim] + [i for i in range(len(w.shape)) if i != dim]), [h, -1])
    uv = u
    vv = None
    for _ in range(max(1, power_iters)):
        vv = ops.matmul(uv, wm)
        vv = vv / (ops.norm(vv) + eps)
        uv = ops.matmul(wm, vv)
        uv = uv / (ops.norm(uv) + eps)
    sigma = ops.sum(uv * ops.matmul(wm, vv))
    return w / sigma


def conv2d_transpose(input, num_filters, filter_size=None, output_size=None,  # noqa: A002
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None,
                     data_format="NCHW"):
    cin = input.shape[1]
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    w = _create_param((cin, num_filters // (groups or 1), fs[0], fs[1]),
                      "float32", param_attr)
    b = _create_param((num_filters,), "float32", bias_attr, is_bias=True)
    out = ops.conv2d_transpose(input, w, b, stride=stride, padding=padding,
                               dilation=dilation, groups=groups or 1)
    if act:
        out = getattr(ops, act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,  # noqa: A002
           groups=1, param_attr=None, bias_attr=None, act=None, name=None,
           data_format="NCDHW"):
    cin = input.shape[1]
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size,) * 3
    w = _create_param((num_filters, cin // (groups or 1)) + tuple(fs),
                      "float32", param_attr)
    b = _create_param((num_filters,), "float32", bias_attr, is_bias=True)
    out = ops.conv3d(input, w, b, stride=stride, padding=padding,
                     dilation=dilation, groups=groups or 1)
    if act:
        out = getattr(ops, act)(out)
    return out


def conv3d_transpose(input, num_filters, filter_size=None, output_size=None,  # noqa: A002
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None,
                     data_format="NCDHW"):
    cin = input.shape[1]
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size,) * 3
    w = _create_param((cin, num_filters // (groups or 1)) + tuple(fs),
                      "float32", param_attr)
    b = _create_param((num_filters,), "float32", bias_attr, is_bias=True)
    out = ops.conv3d_transpose(input, w, b, stride=stride, padding=padding,
                               dilation=dilation, groups=groups or 1)
    if act:
        out = getattr(ops, act)(out)
    return out


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """out_k = x^T W_k y + b_k (ref: fluid/layers/nn.py
    bilinear_tensor_product)."""
    dx, dy = x.shape[-1], y.shape[-1]
    w = _create_param((size, dx, dy), "float32", param_attr)
    b = _create_param((size,), "float32", bias_attr, is_bias=True)
    out = ops.einsum("bi,kij,bj->bk", x, w, y)
    if b is not None:
        out = out + b
    if act:
        out = getattr(ops, act)(out)
    return out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,  # noqa: A002
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              summary_decay_rate=0.9999999, sync_stats=False,
              enable_scale_and_shift=False):
    """ref: fluid/layers/nn.py data_norm — normalization by accumulated
    batch statistics (batch_size/batch_sum/batch_square_sum persistables),
    no learnable scale/shift unless enabled."""
    c = input.shape[-1] if data_layout == "NHWC" else input.shape[1]
    bsize = _create_param((c,), "float32", ParamAttr(name=None),
                          default_init=I.Constant(1e4))
    bsum = _create_param((c,), "float32", ParamAttr(name=None),
                         default_init=I.Constant(0.0))
    bsqs = _create_param((c,), "float32", ParamAttr(name=None),
                         default_init=I.Constant(1e4))
    mean = bsum / bsize
    scale = ops.rsqrt(bsqs / bsize + epsilon)
    shape = [1, -1] + [1] * (len(input.shape) - 2) \
        if data_layout == "NCHW" else [1] * (len(input.shape) - 1) + [-1]
    out = (input - ops.reshape(mean, shape)) * ops.reshape(scale, shape)
    if act:
        out = getattr(ops, act)(out)
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):  # noqa: A002
    """Lookahead row convolution (ref: row_conv_op): out[t] = sum_{i=0..F}
    w[i] * x[t+i], dense [B, T, D] layout."""
    import jax.numpy as jnp
    d = input.shape[-1]
    f = future_context_size + 1
    w = _create_param((f, d), "float32", param_attr)
    xv = input._value if hasattr(input, "_value") else input
    from ..core.tensor import Tensor
    from ..ops._registry import apply_op

    def core(xv, wv):
        pads = [(0, 0)] * xv.ndim
        pads[1] = (0, f - 1)
        xp = jnp.pad(xv, pads)
        t = xv.shape[1]
        out = sum(xp[:, i:i + t] * wv[i] for i in range(f))
        return out

    out = apply_op(core, "row_conv", (input, w), {})
    if act:
        out = getattr(ops, act)(out)
    return out


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """ref: fluid/layers/nn.py py_func — run a host Python callable inside
    the graph. Lowered with jax.pure_callback (traced) or a direct call
    (eager). `backward_func(*(inputs + grads_of_outputs)) -> grads_of_
    inputs` wires a host-side VJP (the reference's grad op pair)."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    from ..ops._registry import apply_op

    xs = x if isinstance(x, (list, tuple)) else [x]
    ts = [v if isinstance(v, Tensor) else Tensor(jnp.asarray(v))
          for v in xs]
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = tuple(jax.ShapeDtypeStruct(tuple(o.shape), o.dtype)
                   for o in outs)

    def host_fwd(*arrs):
        r = func(*arrs)
        rs = r if isinstance(r, (list, tuple)) else [r]
        return tuple(np.asarray(v, dtype=s.dtype)
                     for v, s in zip(rs, shapes))

    if backward_func is None:
        def core(*vals):
            res = jax.pure_callback(host_fwd, shapes, *vals)
            return res if len(res) > 1 else res[0]

        r = apply_op(core, "py_func", tuple(ts), {}, nondiff=True)
    else:
        # integer inputs (indices/labels) take float0 tangents, never
        # host-computed cotangents — backward_func's outputs are consumed
        # positionally for the FLOAT inputs only
        is_float = [jnp.issubdtype(v._value.dtype, jnp.inexact) for v in ts]
        in_shapes = tuple(jax.ShapeDtypeStruct(v._value.shape,
                                               v._value.dtype)
                          for v, f in zip(ts, is_float) if f)

        def host_bwd(*arrs):
            g = backward_func(*arrs)
            gs = list(g) if isinstance(g, (list, tuple)) else [g]
            floats = [v for v, f in zip(gs, is_float) if f] \
                if len(gs) == len(is_float) else gs
            return tuple(np.asarray(v, dtype=s.dtype)
                         for v, s in zip(floats, in_shapes))

        @jax.custom_vjp
        def pyf(*vals):
            res = jax.pure_callback(host_fwd, shapes, *vals)
            return res if len(res) > 1 else res[0]

        def pyf_fwd(*vals):
            return pyf(*vals), vals

        def pyf_bwd(vals, g):
            gs = g if isinstance(g, tuple) else (g,)
            fgrads = iter(jax.pure_callback(host_bwd, in_shapes,
                                            *vals, *gs))
            from jax.dtypes import float0
            return tuple(
                next(fgrads) if f else np.zeros(v.shape, float0)
                for v, f in zip(vals, is_float))

        pyf.defvjp(pyf_fwd, pyf_bwd)
        r = apply_op(pyf, "py_func", tuple(ts), {})

    res = r if isinstance(r, (list, tuple)) else [r]
    res = [v if isinstance(v, Tensor) else Tensor(v) for v in res]
    return res if isinstance(out, (list, tuple)) else res[0]


def crf_decoding(input, param_attr=None, length=None, label=None):  # noqa: A002
    """Viterbi decode over a linear-chain CRF (ref: crf_decoding_op).
    input: [B, T, N] unary potentials (dense layout), transition param
    [N+2, N] with paddle's start/stop rows at indices 0/1."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    from ..ops._registry import apply_op

    n = input.shape[-1]
    w = _create_param((n + 2, n), "float32", param_attr)

    def core(emis, trans):
        start, stop, t_mat = trans[0], trans[1], trans[2:]

        def viterbi(emis_b):
            a0 = start + emis_b[0]

            def step(alpha, e_t):
                scores = alpha[:, None] + t_mat + e_t[None, :]
                return jnp.max(scores, axis=0), jnp.argmax(scores, axis=0)

            alpha, bps = jax.lax.scan(step, a0, emis_b[1:])
            last = jnp.argmax(alpha + stop)

            def back(tag, bp):
                return bp[tag], bp[tag]

            _, path_rev = jax.lax.scan(back, last, bps, reverse=True)
            return jnp.concatenate([path_rev, jnp.asarray([last])])

        return jax.vmap(viterbi)(emis)

    return apply_op(core, "crf_decoding", (input, w), {}, nondiff=True)


def nce(input, label, num_total_classes, sample_weight=None,  # noqa: A002
        param_attr=None, bias_attr=None, num_neg_samples=5, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (ref: nce_op). TPU-first: dense
    uniform negative sampling, logistic loss over pos + sampled negs."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    from ..core import rng as rng_mod
    from ..ops._registry import apply_op

    d = input.shape[-1]
    w = _create_param((num_total_classes, d), "float32", param_attr)
    b = _create_param((num_total_classes,), "float32", bias_attr,
                      is_bias=True)
    key = rng_mod.next_key()

    def core(xv, lv, wv, bv):
        bsz = xv.shape[0]
        lv = lv.reshape(-1).astype(jnp.int32)
        negs = jax.random.randint(key, (bsz, num_neg_samples), 0,
                                  num_total_classes)
        pos_logit = jnp.sum(xv * wv[lv], -1) + bv[lv]
        neg_logit = jnp.einsum("bd,bnd->bn", xv, wv[negs]) + bv[negs]
        pos_loss = jax.nn.softplus(-pos_logit)
        neg_loss = jnp.sum(jax.nn.softplus(neg_logit), -1)
        return (pos_loss + neg_loss)[:, None]

    return apply_op(core, "nce", (input, label, w, b), {})


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,  # noqa: A002
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head (ref: fluid/layers/detection.py multi_box_head):
    per feature map, a conv predicts loc+conf and prior_box generates the
    anchors; outputs concatenated over maps."""
    from ..nn.functional.detection import prior_box as _prior_box
    import jax.numpy as jnp
    from ..core.tensor import Tensor

    if min_sizes is None:
        # reference ratio interpolation
        num_layer = len(inputs)
        min_ratio = min_ratio or 20
        max_ratio = max_ratio or 90
        step = int((max_ratio - min_ratio) / max(1, (num_layer - 2)))
        min_sizes, max_sizes = [], []
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes[: num_layer - 1]
        max_sizes = [base_size * 0.2] + max_sizes[: num_layer - 1]

    class _ShapeOnly:  # prior_box only consumes .shape; Variables aren't
        def __init__(self, shape):  # convertible to arrays
            self.shape = tuple(shape)

    image_s = _ShapeOnly(image.shape)
    locs, confs, boxes, vars_ = [], [], [], []
    for i, feat in enumerate(inputs):
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i], (list, tuple)) \
            else [aspect_ratios[i]]
        mn = min_sizes[i] if isinstance(min_sizes[i], (list, tuple)) \
            else [min_sizes[i]]
        mx = [max_sizes[i]] if max_sizes and max_sizes[i] else None
        box, var = _prior_box(_ShapeOnly(feat.shape), image_s, mn, mx, ar,
                              variance, flip, clip, offset=offset)
        num_priors = int(np.prod(box.shape[:-1])) // int(
            np.prod(feat.shape[2:]))
        loc = conv2d(feat, num_priors * 4, kernel_size, stride=stride,
                     padding=pad)
        conf = conv2d(feat, num_priors * num_classes, kernel_size,
                      stride=stride, padding=pad)
        bsz = feat.shape[0]
        loc = ops.reshape(ops.transpose(loc, [0, 2, 3, 1]), [bsz, -1, 4])
        conf = ops.reshape(ops.transpose(conf, [0, 2, 3, 1]),
                           [bsz, -1, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes.append(ops.reshape(box, [-1, 4]))
        vars_.append(ops.reshape(var, [-1, 4]))
    mbox_locs = ops.concat(locs, 1)
    mbox_confs = ops.concat(confs, 1)
    box = ops.concat(boxes, 0)
    var = ops.concat(vars_, 0)
    return mbox_locs, mbox_confs, box, var


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    """Deformable conv v2 (ref: deformable_conv_op): bilinear sampling at
    offset locations then a dense contraction. Dense TPU formulation:
    gather the kH*kW sampled patches with vectorized bilinear interp."""
    import jax
    import jax.numpy as jnp
    from ..ops._registry import apply_op

    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    kh, kw = fs
    cin = x.shape[1]
    w = _create_param((num_filters, cin // (groups or 1), kh, kw),
                      "float32", param_attr)
    b = _create_param((num_filters,), "float32", bias_attr, is_bias=True)
    st = stride if isinstance(stride, (list, tuple)) else (stride, stride)
    pd = padding if isinstance(padding, (list, tuple)) else (padding, padding)
    dl = dilation if isinstance(dilation, (list, tuple)) \
        else (dilation, dilation)

    def core(xv, off, msk, wv, *bias):
        bsz, c, h, wdt = xv.shape
        ho = (h + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
        wo = (wdt + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
        xp = jnp.pad(xv, ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])))
        # base sampling grid [ho, wo, kh, kw]
        oy = jnp.arange(ho) * st[0]
        ox = jnp.arange(wo) * st[1]
        ky = jnp.arange(kh) * dl[0]
        kx = jnp.arange(kw) * dl[1]
        base_y = oy[:, None, None, None] + ky[None, None, :, None]
        base_x = ox[None, :, None, None] + kx[None, None, None, :]
        off = off.reshape(bsz, deformable_groups, kh * kw, 2, ho, wo)
        dy = jnp.moveaxis(off[:, :, :, 0], -2, 2).reshape(
            bsz, deformable_groups, ho, wo, kh, kw)
        dx = jnp.moveaxis(off[:, :, :, 1], -2, 2).reshape(
            bsz, deformable_groups, ho, wo, kh, kw)
        sy = base_y[None, None] + dy
        sx = base_x[None, None] + dx
        hp, wp = xp.shape[2], xp.shape[3]
        sy = jnp.clip(sy, 0.0, hp - 1.0)
        sx = jnp.clip(sx, 0.0, wp - 1.0)
        y0 = jnp.floor(sy).astype(jnp.int32)
        x0 = jnp.floor(sx).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, hp - 1)
        x1 = jnp.minimum(x0 + 1, wp - 1)
        wy = sy - y0
        wx = sx - x0
        cg = c // deformable_groups

        def gather(yi, xi):
            # xp: [B, C, HP, WP]; yi/xi: [B, G, ho, wo, kh, kw]
            yi = jnp.repeat(yi, cg, axis=1)  # -> [B, C, ...]
            xi = jnp.repeat(xi, cg, axis=1)
            bidx = jnp.arange(bsz)[:, None, None, None, None, None]
            cidx = jnp.arange(c)[None, :, None, None, None, None]
            return xp[bidx, cidx, yi, xi]

        w00 = ((1 - wy) * (1 - wx))
        w01 = ((1 - wy) * wx)
        w10 = (wy * (1 - wx))
        w11 = (wy * wx)

        def wexp(wt):
            return jnp.repeat(wt, cg, axis=1)

        patches = (gather(y0, x0) * wexp(w00) + gather(y0, x1) * wexp(w01)
                   + gather(y1, x0) * wexp(w10) + gather(y1, x1) * wexp(w11))
        if msk is not None:
            m = msk.reshape(bsz, deformable_groups, kh * kw, ho, wo)
            m = jnp.moveaxis(m, 2, -1).reshape(
                bsz, deformable_groups, ho, wo, kh, kw)
            patches = patches * jnp.repeat(m, cg, axis=1)
        out = jnp.einsum("bchwyx,ocyx->bohw", patches, wv)
        if bias:
            out = out + bias[0].reshape(1, -1, 1, 1)
        return out

    args = [x, offset, mask, w]
    if b is not None:
        args.append(b)
    return apply_op(core, "deform_conv2d", tuple(args), {})
