"""paddle.static.nn — fluid-style functional layers for static graphs.

Reference: python/paddle/fluid/layers/nn.py (fc, conv2d, ...) — each creates
parameters in the current program + appends compute ops.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from ..core import unique_name
from ..core.param_attr import ParamAttr
from ..nn import initializer as I
from .program import default_main_program


def _create_param(shape, dtype, attr, is_bias=False, default_init=None):
    attr = ParamAttr._to_attr(attr)
    if attr is False:
        return None
    init = attr.initializer or default_init or (
        I.Constant(0.0) if is_bias else I.XavierUniform())
    block = default_main_program().global_block()
    v = block.create_parameter(shape, dtype, name=attr.name,
                               initializer=init, trainable=attr.trainable)
    v.optimize_attr = {"learning_rate": attr.learning_rate}
    v.regularizer = attr.regularizer
    v.stop_gradient = not attr.trainable
    return v


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None, param_attr=None):
    weight_attr = weight_attr or param_attr
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    if len(x.shape) > num_flatten_dims + 1:
        x = ops.flatten(x, num_flatten_dims, -1) if num_flatten_dims > 0 else x
    w = _create_param((in_dim, size), "float32", weight_attr)
    b = _create_param((size,), "float32", bias_attr, is_bias=True)
    out = ops.linear(x, w, b)
    if activation:
        out = getattr(ops, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,  # noqa: A002
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None, use_cudnn=True):
    ksize = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    fan_in = (cin // groups) * int(np.prod(ksize))
    w = _create_param((num_filters, cin // groups) + tuple(ksize), "float32",
                      param_attr, default_init=I.Normal(0.0, (2.0 / fan_in) ** 0.5))
    b = _create_param((num_filters,), "float32", bias_attr, is_bias=True)
    out = ops.conv2d(input, w, b, stride, padding, dilation, groups, data_format)
    if act:
        out = getattr(ops, act)(out)
    return out


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,  # noqa: A002
           pool_padding=0, global_pooling=False, ceil_mode=False,
           data_format="NCHW", name=None, use_cudnn=True, exclusive=True):
    if global_pooling:
        return ops.adaptive_avg_pool2d(input, 1) if pool_type == "avg" \
            else ops.adaptive_max_pool2d(input, 1)
    if pool_type == "max":
        return ops.max_pool2d(input, pool_size, pool_stride, pool_padding,
                              ceil_mode, data_format)
    return ops.avg_pool2d(input, pool_size, pool_stride, pool_padding,
                          ceil_mode, exclusive, None, data_format)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,  # noqa: A002
               bias_attr=None, data_layout="NCHW", is_test=False,
               use_global_stats=False, name=None, **kw):
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = _create_param((c,), "float32", param_attr,
                          default_init=I.Constant(1.0))
    bias = _create_param((c,), "float32", bias_attr, is_bias=True)
    mean = _create_param((c,), "float32", ParamAttr(
        name=unique_name.generate("bn_mean"), trainable=False),
        default_init=I.Constant(0.0))
    var = _create_param((c,), "float32", ParamAttr(
        name=unique_name.generate("bn_var"), trainable=False),
        default_init=I.Constant(1.0))
    out, _, _ = ops.batch_norm(input, mean, var, scale, bias,
                               training=not is_test, momentum=momentum,
                               epsilon=epsilon, data_format=data_layout,
                               use_global_stats=use_global_stats)
    if act:
        out = getattr(ops, act)(out)
    return out


def embedding(input, size, padding_idx=None, param_attr=None, dtype="float32",  # noqa: A002
              is_sparse=False, name=None):
    w = _create_param(tuple(size), dtype, param_attr,
                      default_init=I.Normal(0.0, 1.0))
    return ops.embedding(input, w, padding_idx=padding_idx)


def dropout(x, dropout_prob=0.5, is_test=False, name=None, **kw):
    return ops.dropout(x, p=dropout_prob, training=not is_test)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,  # noqa: A002
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    norm_shape = tuple(input.shape[begin_norm_axis:])
    w = _create_param(norm_shape, "float32", param_attr,
                      default_init=I.Constant(1.0)) if scale else None
    b = _create_param(norm_shape, "float32", bias_attr, is_bias=True) \
        if shift else None
    out = ops.layer_norm(input, w, b, epsilon,
                         normalized_ndim=len(norm_shape))
    if act:
        out = getattr(ops, act)(out)
    return out
