"""fluid.layer_helper module path (ref: fluid/layer_helper.py).

The 1.x LayerHelper was how custom layers created parameters and
appended ops by name into the current Program. TPU-native rework: it
binds the same contract onto this stack's machinery — parameters via
the static Program block (static mode) or live Parameters (dygraph),
ops via the registered functional op library (`ops.<type>`), so simple
third-party 1.x custom layers run unchanged. The append_op protocol
maps op *types* to registry functions; exotic OpDesc-level usage should
move to the functional ops directly.
"""
from __future__ import annotations

from .. import ops as _ops


class LayerHelperBase:
    def __init__(self, name, layer_type=""):
        from . import unique_name
        self._layer_type = layer_type or name
        self.name = unique_name.generate(name or layer_type)

    @property
    def main_program(self):
        from ..static import default_main_program
        return default_main_program()

    def create_parameter(self, attr=None, shape=None, dtype="float32",
                         is_bias=False, default_initializer=None):
        # one implementation for both modes already exists — delegate
        # (static: Program-block parameter; dygraph: live Parameter)
        from .layers import create_parameter
        return create_parameter(shape, dtype, attr=attr, is_bias=is_bias,
                                default_initializer=default_initializer)


class LayerHelper(LayerHelperBase):
    def __init__(self, layer_type, **kwargs):
        super().__init__(kwargs.get("name") or layer_type, layer_type)
        self.kwargs = kwargs

    def input(self, input_param_name="input"):
        return self.kwargs[input_param_name]

    def attr(self, name):
        return self.kwargs.get(name)

    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        """1.x code pre-creates the output var then append_op fills it; on
        this stack ops RETURN their outputs, so this is a placeholder the
        append_op call below will replace."""
        return None

    def append_op(self, type, inputs=None, outputs=None, attrs=None):  # noqa: A002,E501
        """Run the registered op `type` with the 1.x-style inputs/attrs
        and return its result (also stored into `outputs` when the caller
        inspects it as a dict)."""
        fn = getattr(_ops, type, None)
        if fn is None:
            raise NotImplementedError(
                f"LayerHelper.append_op: no registered op named {type!r} —"
                " call the functional op from paddle_tpu.ops directly")
        args = []
        for v in (inputs or {}).values():
            args.append(v[0] if isinstance(v, (list, tuple)) and len(v) == 1
                        else v)
        res = fn(*args, **(attrs or {}))
        if outputs:
            k = next(iter(outputs))
            outputs[k] = [res]
        return res

    def append_activation(self, out, act=None):
        act = act or self.kwargs.get("act")
        if not act:
            return out
        return getattr(_ops, act)(out)


__all__ = ["LayerHelper", "LayerHelperBase"]
