"""fluid 1.x compatibility namespace.

Reference-era user code (`import paddle.fluid as fluid`) maps here: the
Program/Executor APIs, fluid.layers functional set, fluid.dygraph guard —
all backed by the TPU-native implementations.
"""
from __future__ import annotations

from ..core.param_attr import ParamAttr  # noqa: F401
from ..core.place import CPUPlace, CUDAPinnedPlace, CUDAPlace, TPUPlace  # noqa: F401
from ..core.tensor import Tensor  # noqa: F401
from ..static import (  # noqa: F401
    CompiledProgram, Executor, Program, data, default_main_program,
    default_startup_program, global_scope, name_scope, program_guard,
    scope_guard,
)
from ..static.program import Variable, append_backward  # noqa: F401
from .. import nn as _nn  # noqa: F401
from .. import optimizer as _optimizer_mod
from ..nn import initializer  # noqa: F401
from .. import regularizer  # noqa: F401
from . import contrib  # noqa: F401
from . import dygraph  # noqa: F401
from . import layers  # noqa: F401
from ..io import DataLoader  # noqa: F401
from ..core.mode import in_dygraph_mode  # noqa: F401


class optimizer:  # fluid.optimizer.* (classes with fluid-era ctor names)
    SGD = _optimizer_mod.SGD
    SGDOptimizer = _optimizer_mod.SGD
    Momentum = _optimizer_mod.Momentum
    MomentumOptimizer = _optimizer_mod.Momentum
    Adam = _optimizer_mod.Adam
    AdamOptimizer = _optimizer_mod.Adam
    Adamax = _optimizer_mod.Adamax
    AdamaxOptimizer = _optimizer_mod.Adamax
    Adagrad = _optimizer_mod.Adagrad
    AdagradOptimizer = _optimizer_mod.Adagrad
    RMSProp = _optimizer_mod.RMSProp
    RMSPropOptimizer = _optimizer_mod.RMSProp
    Lamb = _optimizer_mod.Lamb
    LambOptimizer = _optimizer_mod.Lamb


def embedding(*a, **kw):
    from ..static import nn as static_nn
    return static_nn.embedding(*a, **kw)


class io:
    @staticmethod
    def save_params(executor, dirname, main_program=None, filename=None):
        import os

        from ..framework.io import save as fsave
        from ..static import global_scope
        from ..static.program import default_main_program
        os.makedirs(dirname, exist_ok=True)
        prog = main_program or default_main_program()
        scope = global_scope()
        state = {}
        for v in prog.global_block().vars.values():
            if v.persistable and scope.find_var(v.name) is not None:
                from ..core.tensor import Tensor as T
                state[v.name] = T(scope.find_var(v.name))
        fsave(state, os.path.join(dirname, filename or "params.pd"))

    @staticmethod
    def load_params(executor, dirname, main_program=None, filename=None):
        import os

        from ..framework.io import load as fload
        from ..static import global_scope
        state = fload(os.path.join(dirname, filename or "params.pd"))
        scope = global_scope()
        for name, t in state.items():
            scope.set(name, t._value)


# ---- GFlags surface (ref: fluid/framework.py:5670 set_flags/get_flags).
# The C++ core's gflags become a host-side registry here; flags that map to
# XLA behaviors are consumed by the modules that honor them.
_FLAGS = {
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_cudnn_deterministic": False,
    "FLAGS_check_nan_inf": False,
    "FLAGS_use_pinned_memory": True,
}


def set_flags(flags):
    if not isinstance(flags, dict):
        raise TypeError("flags in set_flags should be a dict")
    for key, value in flags.items():
        if key not in _FLAGS:
            raise ValueError(
                f"Flag {key} cannot set its value through this function.")
        _FLAGS[key] = value


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    if not isinstance(flags, (list, tuple)):
        raise TypeError("flags in get_flags should be a list, tuple or str")
    out = {}
    for key in flags:
        if key not in _FLAGS:
            raise ValueError(f"Flag {key} is not public.")
        out[key] = _FLAGS[key]
    return out
