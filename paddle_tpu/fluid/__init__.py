"""fluid 1.x compatibility namespace.

Reference-era user code (`import paddle.fluid as fluid`) maps here: the
Program/Executor APIs, fluid.layers functional set, fluid.dygraph guard —
all backed by the TPU-native implementations.
"""
from __future__ import annotations

from ..core.param_attr import ParamAttr  # noqa: F401
from ..core.place import (  # noqa: F401
    CPUPlace, CUDAPinnedPlace, CUDAPlace, TPUPlace, XPUPlace)
from ..core.tensor import Tensor  # noqa: F401
from ..static import (  # noqa: F401
    BuildStrategy, CompiledProgram, ExecutionStrategy, Executor,
    ParallelExecutor, Program, WeightNormParamAttr, data,
    default_main_program, default_startup_program, global_scope,
    gradients, load_program_state, name_scope, program_guard, scope_guard,
    set_program_state,
)
from ..static.program import Variable, append_backward  # noqa: F401
from ..static.executor import Scope  # noqa: F401
from .. import nn as _nn  # noqa: F401
from .. import optimizer as _optimizer_mod
from ..nn import initializer  # noqa: F401
from ..nn import clip  # noqa: F401
from .. import regularizer  # noqa: F401
from . import contrib  # noqa: F401
from .reader import PyReader  # noqa: F401

# register fluid.layers.utils as an importable MODULE PATH: fluid.layers
# is a module (not a package) on this stack, but the reference exposes
# `from paddle.fluid.layers.utils import map_structure` — the sys.modules
# pre-registration makes that import resolve (r4 module-path parity)
import sys as _sys  # noqa: E402

from . import layers_utils as _layers_utils  # noqa: E402

_sys.modules[__name__ + ".layers.utils"] = _layers_utils
from . import layers as _layers_mod  # noqa: E402

_layers_mod.utils = _layers_utils
from . import core  # noqa: F401
from . import dygraph  # noqa: F401
from . import layers  # noqa: F401
from . import nets  # noqa: F401
from ..io import DataLoader  # noqa: F401
from ..core.mode import in_dygraph_mode  # noqa: F401

# module-level attribute surface of the 1.x package (ref:
# python/paddle/fluid/__init__.py:34-95 — fluid.core, fluid.profiler,
# fluid.unique_name, the LoDTensor/Tensor aliases, dygraph toggles...):
# real 1.x user code reaches these as attributes, most outside __all__.
from ..core import unique_name  # noqa: F401
from ..utils import profiler  # noqa: F401
from ..core import rng as generator  # noqa: F401
from . import dataset_feed as dataset  # noqa: F401  (fluid.dataset is the
# DatasetFactory module, NOT the paddle.dataset readers package)
from .. import framework  # noqa: F401
from .. import incubate  # noqa: F401
from .. import metric as metrics  # noqa: F401
from ..static import executor  # noqa: F401
from ..framework.io import load, save  # noqa: F401
from ..ops import one_hot  # noqa: F401
from .core import (  # noqa: F401
    LoDTensor, LoDTensorArray, VarBase, _cuda_synchronize, _Scope)
from .compat1x import (  # noqa: F401
    DataFeeder, DistributeTranspiler, DistributeTranspilerConfig,
    WeightedAverage, create_lod_tensor, create_random_int_lodtensor,
    memory_optimize, release_memory)
from .dygraph import (  # noqa: F401
    disable_dygraph, enable_dygraph, load_dygraph, save_dygraph)

enable_imperative = enable_dygraph
disable_imperative = disable_dygraph

# dygraph layer classes the reference star-imports to fluid top level
# (ref: fluid/__init__.py:86 `from .dygraph.nn import *`)
from .dygraph import (  # noqa: E402,F401
    BatchNorm, BilinearTensorProduct, Conv2D, Conv2DTranspose, Conv3D,
    Conv3DTranspose, Dropout, Embedding, Flatten, GroupNorm, GRUUnit,
    InstanceNorm, Layer, LayerNorm, Linear, NCE, Pool2D, PRelu,
    SpectralNorm, TreeConv)
from ..compat import ComplexVariable  # noqa: E402,F401
from ..static import (  # noqa: E402,F401
    cpu_places, cuda_pinned_places, cuda_places, device_guard)
from ..nn.initializer import set_global_initializer  # noqa: E402,F401
from ..utils import require_version  # noqa: E402,F401
from .core import is_compiled_with_cuda, is_compiled_with_xpu  # noqa: E402,F401
from ..distributed import fleet  # noqa: E402,F401
from ..incubate import data_generator  # noqa: E402,F401
from .dataset_feed import (  # noqa: E402,F401
    DataFeedDesc, DatasetFactory, InMemoryDataset, QueueDataset)
from . import dataset_feed as data_feed_desc  # noqa: E402,F401


class backward:  # fluid.backward (ref: fluid/backward.py)
    from ..static import gradients
    from ..static.program import append_backward
    gradients = staticmethod(gradients)
    append_backward = staticmethod(append_backward)


class compiler:  # fluid.compiler (ref: fluid/compiler.py)
    CompiledProgram = CompiledProgram
    BuildStrategy = BuildStrategy
    ExecutionStrategy = ExecutionStrategy


class parallel_executor:  # fluid.parallel_executor
    ParallelExecutor = ParallelExecutor
    BuildStrategy = BuildStrategy
    ExecutionStrategy = ExecutionStrategy


class trainer_desc:
    """Trainer pipeline descriptors (ref: fluid/trainer_desc.py). In the
    reference these serialize configs for the C++ MultiTrainer; here the
    jitted whole-Program step IS the trainer, so they are plain config
    records consumed by Executor.train_from_dataset."""

    class TrainerDesc:
        def __init__(self):
            self.config = {}

        def _set_fetch_var_and_info(self, fetch_vars, fetch_info,
                                    print_period):
            self.config.update(fetch_vars=fetch_vars,
                               fetch_info=fetch_info,
                               print_period=print_period)

        def _set_debug(self, debug):
            self.config["debug"] = debug

        def _set_thread(self, thread_num):
            self.config["thread_num"] = thread_num

    class MultiTrainer(TrainerDesc):
        pass

    class DistMultiTrainer(TrainerDesc):
        pass

    class PipelineTrainer(TrainerDesc):
        pass

    class HeterXpuTrainer(TrainerDesc):
        pass

    class HeterBoxWorker(TrainerDesc):
        pass


class evaluator:
    """ref: fluid/evaluator.py — deprecated there in favor of
    fluid.metrics; delegated accordingly."""

    class Evaluator:
        def __init__(self, name, **kwargs):
            import warnings
            warnings.warn(
                "fluid.evaluator is deprecated; use fluid.metrics",
                stacklevel=2)
            self.metrics = []
            self.helper = None
            self.name = name

    ChunkEvaluator = None  # bound below


class distribute_lookup_table:
    """ref: fluid/distribute_lookup_table.py — locate the distributed
    (parameter-server) embedding table in a Program."""

    @staticmethod
    def find_distributed_lookup_table(program):
        from ..static.program import Program
        if not isinstance(program, Program):
            raise TypeError("program must be a Program")
        # PS sparse embeddings live in distributed.ps SparseTable on this
        # stack, outside the Program's op list
        return None


class _ChunkEvaluator:
    """Accumulating chunk F1 over batches (delegates to
    metric.chunk_eval semantics)."""

    def __init__(self, **kwargs):
        self.kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_infer = self.num_label = self.num_correct = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self.num_infer += int(num_infer_chunks)
        self.num_label += int(num_label_chunks)
        self.num_correct += int(num_correct_chunks)
        return self.eval()

    def eval(self):
        p = self.num_correct / self.num_infer if self.num_infer else 0.0
        r = self.num_correct / self.num_label if self.num_label else 0.0
        f1 = 2 * p * r / (p + r) if p + r else 0.0
        return p, r, f1


evaluator.ChunkEvaluator = _ChunkEvaluator


# trainer descriptor classes are also reference top-level names
TrainerDesc = trainer_desc.TrainerDesc
MultiTrainer = trainer_desc.MultiTrainer
DistMultiTrainer = trainer_desc.DistMultiTrainer
PipelineTrainer = trainer_desc.PipelineTrainer
HeterXpuTrainer = trainer_desc.HeterXpuTrainer
HeterBoxWorker = trainer_desc.HeterBoxWorker

from ..core.rng import Generator  # noqa: E402,F401


class PSDispatcher:
    """Assign variables to parameter-server endpoints (ref:
    transpiler/ps_dispatcher.py). Used standalone by PS-lite table
    placement; the program transpiler itself is superseded (see
    DistributeTranspiler)."""

    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError


class HashName(PSDispatcher):
    """Stable name-hash placement (ref: ps_dispatcher.py:49)."""

    def _hash_block(self, block_str, total):
        import hashlib
        # md5 not python hash(): placement must agree across processes
        # regardless of PYTHONHASHSEED
        return int(hashlib.md5(str(block_str).encode()).hexdigest(),
                   16) % total

    def dispatch(self, varlist):
        return [self._eps[self._hash_block(getattr(v, "name", v),
                                           len(self._eps))]
                for v in varlist]


class RoundRobin(PSDispatcher):
    """Cyclic placement (ref: ps_dispatcher.py:91)."""

    def dispatch(self, varlist):
        out = []
        for _v in varlist:
            out.append(self._eps[self._step % len(self._eps)])
            self._step += 1
        return out


class learning_rate_decay:
    """fluid.layers.learning_rate_decay module surface at the 1.x
    top-level name (the decay schedules themselves live in
    layers_legacy and map onto optimizer.lr schedulers)."""
    from .layers_legacy import (
        cosine_decay, exponential_decay, inverse_time_decay, noam_decay,
        natural_exp_decay, piecewise_decay, polynomial_decay)
    cosine_decay = staticmethod(cosine_decay)
    exponential_decay = staticmethod(exponential_decay)
    inverse_time_decay = staticmethod(inverse_time_decay)
    noam_decay = staticmethod(noam_decay)
    natural_exp_decay = staticmethod(natural_exp_decay)
    piecewise_decay = staticmethod(piecewise_decay)
    polynomial_decay = staticmethod(polynomial_decay)


def load_op_library(lib_filename):
    """Custom C++/CUDA op loading has no meaning against XLA: custom ops
    on this stack are jax.custom_vjp / Pallas kernels (see ops/pallas) or
    ctypes-bound native code (see csrc/). Raising shim, same form as the
    ONNX drop (SURVEY §2 #39)."""
    raise NotImplementedError(
        "load_op_library loads reference-era .so op kernels, which cannot "
        "run under XLA. Implement custom ops as jax.custom_vjp functions "
        "or Pallas TPU kernels (paddle_tpu/ops/pallas has templates), or "
        "bind host code via ctypes like paddle_tpu/csrc.")


class average:  # fluid.average module surface (ref: fluid/average.py)
    WeightedAverage = WeightedAverage


class transpiler:  # fluid.transpiler (ref: fluid/transpiler/__init__.py)
    DistributeTranspiler = DistributeTranspiler
    DistributeTranspilerConfig = DistributeTranspilerConfig
    memory_optimize = staticmethod(memory_optimize)
    release_memory = staticmethod(release_memory)


class install_check:  # fluid.install_check (ref: fluid/install_check.py)
    from .compat1x import run_check
    run_check = staticmethod(run_check)


def monkey_patch_variable():
    """Tensor operator patching is applied at import on this stack; kept
    callable for 1.x code that invokes it explicitly."""


def monkey_patch_varbase():
    pass


class optimizer:  # fluid.optimizer.* (classes with fluid-era ctor names)
    SGD = _optimizer_mod.SGD
    SGDOptimizer = _optimizer_mod.SGD
    Momentum = _optimizer_mod.Momentum
    MomentumOptimizer = _optimizer_mod.Momentum
    Adam = _optimizer_mod.Adam
    AdamOptimizer = _optimizer_mod.Adam
    Adamax = _optimizer_mod.Adamax
    AdamaxOptimizer = _optimizer_mod.Adamax
    Adagrad = _optimizer_mod.Adagrad
    AdagradOptimizer = _optimizer_mod.Adagrad
    RMSProp = _optimizer_mod.RMSProp
    RMSPropOptimizer = _optimizer_mod.RMSProp
    Lamb = _optimizer_mod.Lamb
    LambOptimizer = _optimizer_mod.Lamb


def embedding(*a, **kw):
    from ..static import nn as static_nn
    return static_nn.embedding(*a, **kw)


class io:
    @staticmethod
    def save_params(executor, dirname, main_program=None, filename=None):
        import os

        from ..framework.io import save as fsave
        from ..static import global_scope
        from ..static.program import default_main_program
        os.makedirs(dirname, exist_ok=True)
        prog = main_program or default_main_program()
        scope = global_scope()
        state = {}
        for v in prog.global_block().vars.values():
            if v.persistable and scope.find_var(v.name) is not None:
                from ..core.tensor import Tensor as T
                state[v.name] = T(scope.find_var(v.name))
        fsave(state, os.path.join(dirname, filename or "params.pd"))

    @staticmethod
    def load_params(executor, dirname, main_program=None, filename=None):
        import os

        from ..framework.io import load as fload
        from ..static import global_scope
        state = fload(os.path.join(dirname, filename or "params.pd"))
        scope = global_scope()
        for name, t in state.items():
            scope.set(name, t._value)

    # persist/restore whole train states + servable artifacts (ref:
    # fluid/io.py save/load/save_inference_model/load_inference_model)
    @staticmethod
    def save(obj, path, **kw):
        from ..framework.io import save as fsave
        return fsave(obj, path, **kw)

    @staticmethod
    def load(path, **kw):
        from ..framework.io import load as fload
        return fload(path, **kw)

    @staticmethod
    def load_program_state(model_path, var_list=None):
        from ..static import load_program_state as f
        return f(model_path, var_list)

    @staticmethod
    def set_program_state(program, state_dict):
        from ..static import set_program_state as f
        return f(program, state_dict)

    @staticmethod
    def save_inference_model(dirname, feeded_var_names, target_vars,
                             executor, main_program=None, **kw):
        """1.x signature: feed names + fetch vars + a directory."""
        import os

        from ..static.io import save_inference_model as f
        from ..static.program import default_main_program
        prog = main_program or default_main_program()
        feeds = [prog.global_block().var(n) if isinstance(n, str) else n
                 for n in feeded_var_names]
        return f(os.path.join(dirname, "model"), feeds,
                 list(target_vars), executor, program=prog)

    @staticmethod
    def load_inference_model(dirname, executor, **kw):
        import os

        from ..static.io import load_inference_model as f
        return f(os.path.join(dirname, "model"), executor, **kw)


# ---- GFlags surface (ref: fluid/framework.py:5670 set_flags/get_flags).
# The C++ core's gflags become a host-side registry here; flags that map to
# XLA behaviors are consumed by the modules that honor them.
_FLAGS = {
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_cudnn_deterministic": False,
    "FLAGS_check_nan_inf": False,
    "FLAGS_use_pinned_memory": True,
}


def set_flags(flags):
    if not isinstance(flags, dict):
        raise TypeError("flags in set_flags should be a dict")
    for key, value in flags.items():
        if key not in _FLAGS:
            raise ValueError(
                f"Flag {key} cannot set its value through this function.")
        _FLAGS[key] = value


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    if not isinstance(flags, (list, tuple)):
        raise TypeError("flags in get_flags should be a list, tuple or str")
    out = {}
    for key in flags:
        if key not in _FLAGS:
            raise ValueError(f"Flag {key} is not public.")
        out[key] = _FLAGS[key]
    return out
