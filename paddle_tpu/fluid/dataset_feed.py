"""MultiSlot dataset feeding: DataFeedDesc, DatasetFactory,
InMemoryDataset, QueueDataset.

Reference: python/paddle/fluid/{data_feed_desc.py,dataset.py} + the C++
MultiSlotDataFeed. The text format is one sample per line, slots in order,
each slot `<n> <v1> ... <vn>` (same bytes the reference's data_generator
emits), so data produced for the reference feeds this implementation
unchanged. The C++ feed/trainer pipeline is replaced by a host-side parser
that yields padded, static-shape numpy batches — the shape contract XLA
compilation needs — consumed by `Executor.train_from_dataset`.
"""
from __future__ import annotations

import random
import re
import subprocess

import numpy as np

__all__ = ["DataFeedDesc", "DatasetFactory", "DatasetBase",
           "InMemoryDataset", "QueueDataset"]


class DataFeedDesc:
    """Parse / edit the proto-text feed description (ref:
    data_feed_desc.py). Only the MultiSlot fields matter here: slot name,
    type, is_dense, is_used, and batch size."""

    def __init__(self, proto_file_or_text):
        try:
            with open(proto_file_or_text) as f:
                text = f.read()
        except (OSError, ValueError):
            text = proto_file_or_text
        self.batch_size = 32
        m = re.search(r"batch_size\s*:\s*(\d+)", text)
        if m:
            self.batch_size = int(m.group(1))
        self.slots = []
        for block in re.finditer(r"slots\s*\{([^}]*)\}", text):
            body = block.group(1)

            def field(key, default=None):
                mm = re.search(rf'{key}\s*:\s*"?([\w.]+)"?', body)
                return mm.group(1) if mm else default

            self.slots.append({
                "name": field("name"),
                "type": field("type", "uint64"),
                "is_dense": field("is_dense", "false") == "true",
                "is_used": field("is_used", "false") == "true",
            })

    def set_batch_size(self, batch_size):
        self.batch_size = batch_size

    def set_dense_slots(self, dense_slots_name):
        for s in self.slots:
            if s["name"] in dense_slots_name:
                s["is_dense"] = True

    def set_use_slots(self, use_slots_name):
        for s in self.slots:
            if s["name"] in use_slots_name:
                s["is_used"] = True

    def desc(self):
        out = [f"batch_size: {self.batch_size}"]
        for s in self.slots:
            out.append(
                "slots {\n"
                f'  name: "{s["name"]}"\n'
                f'  type: "{s["type"]}"\n'
                f'  is_dense: {str(s["is_dense"]).lower()}\n'
                f'  is_used: {str(s["is_used"]).lower()}\n'
                "}")
        return "\n".join(out) + "\n"


from ..distributed.dataset import DatasetBase as _DistDatasetBase


class DatasetBase(_DistDatasetBase):
    """1.x text-contract dataset base: shares the config surface (init,
    set_batch_size/thread/filelist/use_var/pipe_command,
    set_data_generator) with distributed.dataset, and replaces the parse
    path with the MultiSlot TEXT format + use_var-typed padded batching
    the reference's C++ MultiSlotDataFeed implements."""

    def __init__(self):
        super().__init__()
        self.pipe_command = "cat"
        self.fea_eval = False

    def set_hdfs_config(self, fs_name, fs_ugi):
        pass  # no remote FS on this stack; files are local paths

    def set_fea_eval(self, record_candidate_size, fea_eval=True):
        self.fea_eval = fea_eval

    def desc(self):
        names = [getattr(v, "name", str(v)) for v in self.use_vars]
        return (f"batch_size: {self.batch_size}\n"
                + "".join(f'slots {{ name: "{n}" }}\n' for n in names))

    # -- parsing --
    def _slot_meta(self):
        meta = []
        for v in self.use_vars:
            name = getattr(v, "name", str(v))
            dt = str(getattr(v, "dtype", "int64")).replace("paddle.", "")
            is_float = "float" in dt
            # trailing static dim of the target var bounds the pad width
            shape = tuple(getattr(v, "shape", ()) or ())
            fixed = int(shape[-1]) if shape and isinstance(
                shape[-1], int) and shape[-1] > 0 else None
            meta.append((name, np.float32 if is_float else np.int64, fixed))
        return meta

    def _iter_lines(self):
        for path in self.filelist:
            if self.pipe_command and self.pipe_command != "cat":
                # preprocessing pipe, same contract as the reference's
                # pipe_command (a filter from raw file bytes to MultiSlot
                # lines on stdout)
                with open(path, "rb") as f:
                    proc = subprocess.run(
                        self.pipe_command, shell=True, stdin=f,
                        capture_output=True, check=True)
                for line in proc.stdout.decode().splitlines():
                    if line.strip():
                        yield line.rstrip("\n")
            else:
                with open(path) as f:
                    for line in f:
                        if line.strip():
                            # strip the newline BEFORE any parse: string
                            # slots via an attached generator must see the
                            # same bytes distributed.dataset delivers
                            yield line.rstrip("\n")

    def _parse_line(self, line, meta=None):
        if self._generator is not None:
            # attached-generator shortcut inherited from the shared base:
            # the generator parses RAW lines (no MultiSlot text round
            # trip), exactly like distributed.dataset
            return super()._parse_line(line)
        toks = line.split()
        if meta is None:
            meta = self._slot_meta()
        out, i = [], 0
        for name, dtype, _fixed in meta:
            if i >= len(toks):
                raise ValueError(
                    f"line ran out of tokens at slot '{name}': {line!r}")
            n = int(toks[i])
            vals = [dtype(t) for t in toks[i + 1: i + 1 + n]]
            i += 1 + n
            out.append(np.asarray(vals, dtype=dtype))
        return out

    @staticmethod
    def _batch_padded(samples):
        """Generator-parsed samples ([(name, values), ...]) collated with
        ragged slots right-padded — the fluid MultiSlot batching
        contract (the distributed base's _batch assumes equal lengths)."""
        slots = {}
        for sample in samples:
            for name, vals in sample:
                slots.setdefault(name, []).append(vals)
        batch = {}
        for name, rows in slots.items():
            width = max(len(r) for r in rows)
            dtypes = [np.asarray(r).dtype for r in rows]
            if any(d.kind in ("U", "S") for d in dtypes):
                arr = np.full((len(rows), width), "", dtype=object)
            else:
                # promote across rows: [1,2] then [0.5] must not truncate
                arr = np.zeros((len(rows), width),
                               dtype=np.result_type(*dtypes))
            for i, r in enumerate(rows):
                arr[i, : len(r)] = r
            batch[name] = arr if arr.dtype != object \
                else arr.astype(str)
        return batch

    def _batches(self, samples):
        if self._generator is not None:
            buf = []
            for s in samples:
                buf.append(s)
                if len(buf) == self.batch_size:
                    yield self._batch_padded(buf)
                    buf = []
            if buf:
                yield self._batch_padded(buf)
            return
        meta = self._slot_meta()
        buf = []
        for s in samples:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield self._collate(buf, meta)
                buf = []
        if buf:
            yield self._collate(buf, meta)

    @staticmethod
    def _collate(buf, meta):
        batch = {}
        for j, (name, dtype, fixed) in enumerate(meta):
            width = fixed or max(len(s[j]) for s in buf)
            arr = np.zeros((len(buf), width), dtype=dtype)
            for bi, s in enumerate(buf):
                v = s[j][:width]
                arr[bi, : len(v)] = v
            batch[name] = arr
        return batch


class QueueDataset(DatasetBase):
    """Streaming: parse lazily, single pass, no shuffle (ref: dataset.py
    QueueDataset)."""

    def __iter__(self):
        meta = self._slot_meta()  # once, not per line
        return self._batches(
            self._parse_line(ln, meta) for ln in self._iter_lines())

    def local_shuffle(self):
        raise NotImplementedError(
            "QueueDataset streams; use InMemoryDataset for shuffle")

    def global_shuffle(self, fleet=None, thread_num=None):
        raise NotImplementedError(
            "QueueDataset streams; use InMemoryDataset for shuffle")


class InMemoryDataset(DatasetBase):
    """Load-then-iterate with shuffle (ref: dataset.py InMemoryDataset).

    Text loads go through the native C++ MultiSlot parser when available
    (csrc/native_runtime.cpp ms_scan/ms_fill — the reference parses this
    format in C++ too): the whole filelist lands in padded [N, W] slot
    arrays, shuffle permutes one index vector, and batches are row
    slices. Falls back to the per-line Python parser (which also serves
    attached-generator and pipe_command datasets)."""

    def __init__(self):
        super().__init__()
        self._samples = None
        self._native = None   # {name: [N, W] array} fast path
        self._order = None

    def load_into_memory(self):
        self._native = None
        if self._generator is None and self._load_native():
            return
        meta = self._slot_meta()  # once, not per line
        self._samples = [self._parse_line(ln, meta)
                         for ln in self._iter_lines()]

    def _load_native(self):
        if self.pipe_command not in (None, "cat") or not self.use_vars:
            return False
        meta = self._slot_meta()
        if any(fixed is None for _, _, fixed in meta):
            # variable-width slots pad per BATCH on the Python path; the
            # native bulk parse pads globally — keep one shape contract
            # by restricting the fast path to fully-fixed slot widths
            return False
        try:
            from ..io.native_loader import parse_multislot
            buf = bytearray()
            for path in self.filelist:
                with open(path, "rb") as f:
                    buf += f.read()
                buf += b"\n"
            self._native = parse_multislot(buf, meta)
        except Exception:
            return False  # no compiler / malformed: the Python parser
            # runs next and raises with a per-line diagnostic if truly bad
        n = next(iter(self._native.values())).shape[0] \
            if self._native else 0
        self._order = np.arange(n)
        self._samples = True  # loaded marker for the shared guards
        return True

    def preload_into_memory(self, thread_num=None):
        self.load_into_memory()

    def wait_preload_done(self):
        pass

    def local_shuffle(self):
        if self._samples is None:
            raise RuntimeError("call load_into_memory() first")
        # ONE rng for both paths: shuffle order must not depend on
        # whether the native parser compiled
        perm = np.random.permutation(self.get_memory_data_size())
        if self._native is not None:
            self._order = self._order[perm]
        else:
            self._samples = [self._samples[i] for i in perm]

    def global_shuffle(self, fleet=None, thread_num=None):
        # single-trainer semantics: global == local (multi-trainer sparse
        # PS training shuffles via distributed/ps sharding instead)
        self.local_shuffle()

    def release_memory(self):
        self._samples = None
        self._native = None
        self._order = None

    def get_memory_data_size(self, fleet=None):
        if self._native is not None:
            return int(len(self._order))
        return len(self._samples or [])

    def get_shuffle_data_size(self, fleet=None):
        return self.get_memory_data_size(fleet)

    def __iter__(self):
        if self._samples is None:
            raise RuntimeError("call load_into_memory() first")
        if self._native is not None:
            def gen():
                n = len(self._order)
                for i in range(0, n, self.batch_size):
                    idx = self._order[i: i + self.batch_size]
                    yield {name: arr[idx]
                           for name, arr in self._native.items()}
            return gen()
        return self._batches(iter(self._samples))


class DatasetFactory:
    """ref: dataset.py DatasetFactory.create_dataset("InMemoryDataset")."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        try:
            cls = {"InMemoryDataset": InMemoryDataset,
                   "QueueDataset": QueueDataset}[datafeed_class]
        except KeyError:
            raise ValueError(
                f"unknown dataset type {datafeed_class!r}; expected "
                "'InMemoryDataset' or 'QueueDataset'") from None
        return cls()
