"""fluid.layers functional namespace (ref: python/paddle/fluid/layers/).

Static-graph builders come from static.nn; pure tensor ops come from the op
library (usable in both modes).
"""
from __future__ import annotations

from .. import ops as _ops
from ..ops import *  # noqa: F401,F403
from ..static.nn import (  # noqa: F401
    batch_norm, conv2d, dropout, embedding, fc, layer_norm, pool2d,
)
from ..ops.control import case, cond, switch_case, while_loop  # noqa: F401


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True):
    from ..static import data as static_data
    if append_batch_size:
        shape = [-1] + list(shape)
    return static_data(name, shape, dtype)


def fill_constant(shape, dtype, value, name=None, out=None):
    return _ops.full(shape, value, dtype)


def reduce_sum(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _ops.sum(input, axis=dim, keepdim=keep_dim)


def reduce_mean(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _ops.mean(input, axis=dim, keepdim=keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _ops.max(input, axis=dim, keepdim=keep_dim)


def elementwise_add(x, y, axis=-1, name=None):
    return _ops.add(x, y)


def elementwise_sub(x, y, axis=-1, name=None):
    return _ops.subtract(x, y)


def elementwise_mul(x, y, axis=-1, name=None):
    return _ops.multiply(x, y)


def elementwise_div(x, y, axis=-1, name=None):
    return _ops.divide(x, y)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    return _ops.matmul(_ops.flatten(x, x_num_col_dims) if x.ndim > 2 else x, y)


def mean(x, name=None):
    return _ops.mean(x)


def accuracy(input, label, k=1, **kw):  # noqa: A002
    from ..metric import accuracy as acc
    return acc(input, label, k)


def softmax_with_cross_entropy(logits, label, **kw):
    return _ops.softmax_with_cross_entropy(logits, label, **kw)


def cross_entropy(input, label, soft_label=False, ignore_index=-100):  # noqa: A002
    return _ops.cross_entropy(input, label, soft_label=soft_label,
                              ignore_index=ignore_index, reduction="none",
                              use_softmax=False)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..core.mode import in_static_mode
    if in_static_mode():
        from ..static.nn import _create_param
        return _create_param(shape, dtype, attr, is_bias, default_initializer)
    from ..core.param_attr import ParamAttr
    from ..core.tensor import Parameter
    from ..nn import initializer as I
    attr = ParamAttr._to_attr(attr)
    init = attr.initializer or default_initializer or (
        I.Constant(0.0) if is_bias else I.XavierUniform())
    return Parameter(init(shape, dtype), name=attr.name)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """A mutable global variable initialized to `value` (ref:
    fluid/layers/tensor.py create_global_var)."""
    import numpy as _np

    from ..core.tensor import Tensor
    t = Tensor(_np.full(tuple(shape), value, dtype=dtype))
    t.persistable = persistable
    return t
