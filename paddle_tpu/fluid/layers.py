"""fluid.layers functional namespace (ref: python/paddle/fluid/layers/).

Static-graph builders come from static.nn; pure tensor ops come from the op
library (usable in both modes).
"""
from __future__ import annotations

from .. import ops as _ops
from ..ops import *  # noqa: F401,F403
from ..static.nn import (  # noqa: F401
    batch_norm, bilinear_tensor_product, conv2d, conv2d_transpose, conv3d,
    conv3d_transpose, crf_decoding, data_norm, deform_conv2d as
    deformable_conv, dropout, embedding, fc, group_norm, instance_norm,
    layer_norm, multi_box_head, nce, pool2d, prelu, py_func, row_conv,
    spectral_norm,
)
from ..ops.control import (  # noqa: F401
    case, cond, switch_case, while_loop,
)
# dense LoD reworks (layout contract: nn/functional/sequence.py docstring)
from ..nn.functional.sequence import (  # noqa: F401
    sequence_concat, sequence_conv, sequence_enumerate, sequence_expand,
    sequence_expand_as, sequence_first_step, sequence_last_step,
    sequence_pad, sequence_pool, sequence_reshape, sequence_reverse,
    sequence_scatter, sequence_slice, sequence_softmax, sequence_unpad,
)
from ..nn.functional.detection import (  # noqa: F401
    anchor_generator, bipartite_match, box_clip, box_coder,
    box_decoder_and_assign, collect_fpn_proposals, density_prior_box,
    detection_output, deformable_roi_pooling, distribute_fpn_proposals,
    generate_mask_labels, generate_proposal_labels, generate_proposals,
    multiclass_nms, prior_box, prroi_pool, psroi_pool,
    retinanet_detection_output, retinanet_target_assign,
    roi_perspective_transform, roi_pool, rpn_target_assign, target_assign,
    yolo_box, yolov3_loss,
)
from ..nn.functional import (  # noqa: F401
    linear_chain_crf, roi_align, sequence_mask,
)
from ..nn.functional.detection import iou_similarity, ssd_loss  # noqa: F401
# the canonical fluid-1.x shims (fresh-params-per-unnamed-call semantics +
# LegacyParamStore for named reuse) — single source of truth, NOT
# re-implemented here (code-review r3c)
from ..nn.functional.legacy import (  # noqa: F401
    add_position_encoding, affine_channel, array_length, array_read,
    array_write, autoincreased_step_counter, birnn, bpr_loss, center_loss,
    continuous_value_model, create_array, dice_loss, dynamic_gru,
    dynamic_lstm, dynamic_lstmp, filter_by_instag, fsp_matrix, gather_tree,
    gru_unit, hash, im2sequence, image_resize, image_resize_short,
    lod_append, lod_reset, lstm, lstm_unit, merge_selected_rows, pad2d,
    pad_constant_like, polygon_box_transform, pool3d, random_crop,
    reorder_lod_tensor_by_rank, resize_bilinear, resize_nearest,
    resize_trilinear, shuffle_channel, similarity_focus, smooth_l1,
    soft_relu, space_to_depth, teacher_student_sigmoid_loss,
    tensor_array_to_tensor, warpctc,
)
# 1.x RNN-cell / decoder classes live on in paddle.nn
from ..nn import (  # noqa: F401
    BeamSearchDecoder, GRUCell, LSTMCell, dynamic_decode,
)
from ..nn.layer.rnn import RNNCellBase as RNNCell  # noqa: F401
# distributions kept their 1.x home in fluid.layers (ref:
# fluid/layers/distributions.py)
from ..distribution import (  # noqa: F401
    Categorical, Normal, Uniform,
)
from .layers_legacy import *  # noqa: F401,F403,E402
from .layers_legacy import (  # noqa: F401
    edit_distance, lrn, mean_iou, multiplex,
    rank_loss, sampled_softmax_with_cross_entropy,
)
from .layers_legacy2 import *  # noqa: F401,F403,E402
from .layers_legacy2 import (  # noqa: F401
    Assert, BasicDecoder, DecodeHelper, Decoder, DynamicRNN,
    GreedyEmbeddingHelper, IfElse, MultivariateNormalDiag, Print,
    SampleEmbeddingHelper, StaticRNN, Switch, TrainingHelper, While,
)


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True):
    from ..static import data as static_data
    if append_batch_size:
        shape = [-1] + list(shape)
    return static_data(name, shape, dtype)


def fill_constant(shape, dtype, value, name=None, out=None):
    return _ops.full(shape, value, dtype)


def reduce_sum(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _ops.sum(input, axis=dim, keepdim=keep_dim)


def reduce_mean(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _ops.mean(input, axis=dim, keepdim=keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _ops.max(input, axis=dim, keepdim=keep_dim)


def elementwise_add(x, y, axis=-1, name=None):
    return _ops.add(x, y)


def elementwise_sub(x, y, axis=-1, name=None):
    return _ops.subtract(x, y)


def elementwise_mul(x, y, axis=-1, name=None):
    return _ops.multiply(x, y)


def elementwise_div(x, y, axis=-1, name=None):
    return _ops.divide(x, y)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    return _ops.matmul(_ops.flatten(x, x_num_col_dims) if x.ndim > 2 else x, y)


def mean(x, name=None):
    return _ops.mean(x)


def accuracy(input, label, k=1, **kw):  # noqa: A002
    from ..metric import accuracy as acc
    return acc(input, label, k)


def softmax_with_cross_entropy(logits, label, **kw):
    return _ops.softmax_with_cross_entropy(logits, label, **kw)


def cross_entropy(input, label, soft_label=False, ignore_index=-100):  # noqa: A002
    return _ops.cross_entropy(input, label, soft_label=soft_label,
                              ignore_index=ignore_index, reduction="none",
                              use_softmax=False)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..core.mode import in_static_mode
    if in_static_mode():
        from ..static.nn import _create_param
        return _create_param(shape, dtype, attr, is_bias, default_initializer)
    from ..core.param_attr import ParamAttr
    from ..core.tensor import Parameter
    from ..nn import initializer as I
    attr = ParamAttr._to_attr(attr)
    init = attr.initializer or default_initializer or (
        I.Constant(0.0) if is_bias else I.XavierUniform())
    return Parameter(init(shape, dtype), name=attr.name)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """A mutable global variable initialized to `value` (ref:
    fluid/layers/tensor.py create_global_var)."""
    import numpy as _np

    from ..core.tensor import Tensor
    t = Tensor(_np.full(tuple(shape), value, dtype=dtype))
    t.persistable = persistable
    return t


# ---- reference submodule attribute surface (ref: fluid/layers/__init__
# binds nn/tensor/ops/control_flow/io/detection/... as attributes; user
# code reaches fluid.layers.nn.relu, fluid.layers.tensor.concat, ...).
# The rebuild keeps ONE flat namespace, so each submodule name points at
# it — a superset of every reference submodule's names.
import sys as _sys

nn = _sys.modules[__name__]
ops = _sys.modules[__name__]
tensor = _sys.modules[__name__]
control_flow = _sys.modules[__name__]
device = _sys.modules[__name__]
io = _sys.modules[__name__]
detection = _sys.modules[__name__]
metric_op = _sys.modules[__name__]


class math_op_patch:  # ref: fluid/layers/math_op_patch.py
    @staticmethod
    def monkey_patch_variable():
        """Operator patching is applied at import on this stack."""
