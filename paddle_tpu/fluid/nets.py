"""fluid.nets — composed multi-op building blocks.

Reference: python/paddle/fluid/nets.py (simple_img_conv_pool:29,
img_conv_group:143, sequence_conv_pool:261, glu:335,
scaled_dot_product_attention:382). Each composes the framework's real ops;
under jit the whole composition fuses into one XLA computation, so these
carry no per-op dispatch cost the way the reference's op-by-op graphs do.
"""
from __future__ import annotations

from ..static import nn as _snn
from .. import ops as _ops


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,  # noqa: A002
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    conv = _snn.conv2d(input, num_filters, filter_size, stride=conv_stride,
                       padding=conv_padding, dilation=conv_dilation,
                       groups=conv_groups, param_attr=param_attr,
                       bias_attr=bias_attr, act=act)
    return _snn.pool2d(conv, pool_size=pool_size, pool_type=pool_type,
                       pool_stride=pool_stride, pool_padding=pool_padding,
                       global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,  # noqa: A002
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    if isinstance(conv_num_filter, int):
        conv_num_filter = [conv_num_filter]

    def _per_conv(v, n):
        return v if isinstance(v, (list, tuple)) else [v] * n

    n = len(conv_num_filter)
    paddings = _per_conv(conv_padding, n)
    fsizes = _per_conv(conv_filter_size, n)
    with_bn = _per_conv(conv_with_batchnorm, n)
    drops = _per_conv(conv_batchnorm_drop_rate, n)
    out = input
    for i, nf in enumerate(conv_num_filter):
        out = _snn.conv2d(out, nf, fsizes[i], padding=paddings[i],
                          param_attr=param_attr,
                          act=None if with_bn[i] else conv_act)
        if with_bn[i]:
            out = _snn.batch_norm(out, act=conv_act)
            if drops[i] > 0:
                out = _snn.dropout(out, dropout_prob=drops[i])
    return _snn.pool2d(out, pool_size=pool_size, pool_type=pool_type,
                       pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,  # noqa: A002
                       act="sigmoid", pool_type="max", bias_attr=None):
    from ..nn.functional.sequence import sequence_conv, sequence_pool
    from ..static.nn import _create_param
    w = _create_param((filter_size * int(input.shape[-1]), num_filters),
                      "float32", param_attr)
    b = _create_param((num_filters,), "float32", bias_attr, is_bias=True)
    conv = sequence_conv(input, w, bias=b, context_length=filter_size)
    if act:
        conv = getattr(_ops, act)(conv)
    return sequence_pool(conv, pool_type)


def glu(input, dim=-1):  # noqa: A002
    return _ops.glu(input, axis=dim)


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """[B, S, D] q/k/v -> multi-head scaled-dot attention, heads re-merged.

    The reference builds this from ~10 graph ops; here it is one jnp
    composition that XLA fuses (and, inside a model, the Pallas flash path
    in ops/pallas is the production-scale variant of the same math).
    """
    import jax.numpy as jnp

    q, k, v = (t._value if hasattr(t, "_value") else t
               for t in (queries, keys, values))
    if q.ndim != 3 or k.ndim != 3 or v.ndim != 3:
        raise ValueError("inputs must be 3-D [batch, seq, hidden]")
    if q.shape[-1] % num_heads or k.shape[-1] % num_heads \
            or v.shape[-1] % num_heads:
        raise ValueError("hidden size must be divisible by num_heads")

    def split(t):  # [B,S,D] -> [B,H,S,D/H]
        b, s, d = t.shape
        return t.reshape(b, s, num_heads, d // num_heads).transpose(
            0, 2, 1, 3)

    qh, kh, vh = split(q), split(k), split(v)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / jnp.sqrt(
        jnp.asarray(qh.shape[-1], qh.dtype))
    weights = _ops.softmax(scores, axis=-1)
    if hasattr(weights, "_value"):
        weights = weights._value
    if dropout_rate:
        weights = _snn.dropout(weights, dropout_prob=dropout_rate)
        if hasattr(weights, "_value"):
            weights = weights._value
    ctx = jnp.einsum("bhqk,bhkd->bhqd", weights, vh)
    b, h, s, dh = ctx.shape
    out = ctx.transpose(0, 2, 1, 3).reshape(b, s, h * dh)
    from ..core.tensor import Tensor
    return Tensor(out, stop_gradient=False)
