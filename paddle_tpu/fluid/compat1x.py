"""fluid 1.x top-level helpers: DataFeeder, lod_tensor builders, average,
transpiler-era shims, install_check.

Reference: python/paddle/fluid/{data_feeder,lod_tensor,average,
transpiler/distribute_transpiler,install_check}.py. Real behavior where the
feature exists on this stack; loud, guided errors where it was superseded
(the distribute transpiler's role is played by fleet + distributed/ps).
"""
from __future__ import annotations

import warnings

import numpy as np

from ..core.tensor import Tensor


class DataFeeder:
    """Convert per-sample python data into an Executor feed dict
    (ref: data_feeder.py DataFeeder.feed). LoD-free: variable-length
    fields must be pre-padded, matching the static-shape contract."""

    def __init__(self, feed_list, place=None, program=None):
        self.feed_names = [
            v if isinstance(v, str) else getattr(v, "name", str(v))
            for v in feed_list]
        self.place = place

    def feed(self, iterable):
        columns = {name: [] for name in self.feed_names}
        for row in iterable:
            if len(row) != len(self.feed_names):
                raise ValueError(
                    f"each sample must have {len(self.feed_names)} fields "
                    f"({self.feed_names}), got {len(row)}")
            for name, val in zip(self.feed_names, row):
                columns[name].append(np.asarray(val))
        return {name: np.stack(vals) for name, vals in columns.items()}


class _SeqTensor(Tensor):
    """Tensor + the sequence lengths a 1.x LoDTensor carried; the base
    Tensor is __slots__-only, so the lengths need their own slot."""

    __slots__ = ("seq_lens",)

    def recursive_sequence_lengths(self):
        return self.seq_lens


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """LoD retired: variable-length data is padded/masked (SURVEY §2 #42
    design decision). Build the padded batch; lengths are returned via
    a .seq_lens attribute / recursive_sequence_lengths() for masks."""
    if isinstance(data, Tensor):
        data = data.numpy()
    if isinstance(data, np.ndarray):
        t = _SeqTensor(data)
        t.seq_lens = recursive_seq_lens
        return t
    if isinstance(data, list):
        lens = recursive_seq_lens[-1]
        rows = []
        width = max(int(l) for l in lens) if lens else 0
        flat = [np.asarray(x).reshape(-1) for x in data]
        flat = np.concatenate(flat) if flat else np.zeros(0)
        off = 0
        for l in lens:
            row = np.zeros(width, dtype=flat.dtype)
            row[: int(l)] = flat[off: off + int(l)]
            off += int(l)
            rows.append(row)
        t = _SeqTensor(np.stack(rows) if rows else np.zeros((0, 0)))
        t.seq_lens = recursive_seq_lens
        return t
    raise TypeError(f"unsupported data type {type(data)}")


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place,
                                low, high):
    # reference shape contract: [sum(seq_lens)] + base_shape (lod_tensor.py
    # create_random_int_lodtensor) — the ndarray path preserves it
    lens = recursive_seq_lens[-1]
    total = int(sum(lens))
    data = np.random.randint(low, high + 1,
                             size=[total] + list(base_shape))
    return create_lod_tensor(data, recursive_seq_lens, place)


class WeightedAverage:
    """Host-side running weighted average (ref: average.py:40)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = 0.0
        self.denominator = 0.0

    def add(self, value, weight):
        self.numerator += float(np.asarray(value).mean()) * float(weight)
        self.denominator += float(weight)

    def eval(self):
        if self.denominator == 0:
            raise ValueError(
                "There is no data to be averaged in WeightedAverage.")
        return self.numerator / self.denominator


class DistributeTranspilerConfig:
    """Accepted for signature compat; consumed by nothing — the PS design
    lives in fleet + distributed/ps (see DistributeTranspiler)."""
    slice_var_up = True
    split_method = None
    min_block_size = 8192
    sync_mode = True


class DistributeTranspiler:
    """The 1.x program-rewriting parameter-server transpiler is superseded
    on this stack: sparse PS training is `paddle.distributed.ps`
    (SparseTable/PSEmbedding) + fleet roles, dense data-parallel is mesh
    sharding. Raising shim with migration guidance (same form as the ONNX
    drop, SURVEY §2 #39)."""

    def __init__(self, config=None):
        raise NotImplementedError(
            "DistributeTranspiler program rewriting was superseded by "
            "TPU-native parallelism: use paddle.distributed.fleet (init + "
            "distributed_optimizer) for data/hybrid parallel, and "
            "paddle.distributed.ps (SparseTable, PSEmbedding) for "
            "parameter-server sparse training. See examples/recsys_ps.py.")


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    # deprecated no-op in the reference too (memory_optimization_
    # transpiler.py:18) — XLA buffer assignment owns memory planning here
    warnings.warn(
        "fluid.memory_optimize is deprecated and a no-op; XLA's buffer "
        "assignment performs memory optimization automatically",
        stacklevel=2)


def release_memory(input_program, skip_opt_set=None):
    warnings.warn(
        "fluid.release_memory is deprecated and a no-op",
        stacklevel=2)


def run_check():
    """fluid.install_check.run_check(): train one tiny layer end-to-end on
    the available device and report (ref: install_check.py:47)."""
    import jax

    from .. import nn, optimizer
    from ..core.tensor import to_tensor
    lin = nn.Linear(2, 1)
    opt = optimizer.SGD(learning_rate=0.1,
                        parameters=lin.parameters())
    x = to_tensor(np.random.rand(4, 2).astype(np.float32))
    loss = (lin(x) ** 2).mean()
    loss.backward()
    opt.step()
    print(f"Your Paddle works well on "  # cli-print: install check
          f"{jax.devices()[0].platform.upper()}.")
    print("Your Paddle is installed successfully!")  # cli-print
