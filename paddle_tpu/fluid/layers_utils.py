"""fluid.layers.utils module path (ref: fluid/layers/utils.py) — the
nest utilities (flatten / pack_sequence_as / map_structure) that 1.x
RNN/decoder user code imports directly. TPU-native: implemented over
jax pytrees, which define the same nesting semantics.
"""
from __future__ import annotations

import jax


def flatten(nest):
    """Flatten a nested structure into a list of leaves (ref:
    utils.py flatten)."""
    return jax.tree_util.tree_leaves(
        nest, is_leaf=lambda x: not isinstance(x, (list, tuple, dict)))


def pack_sequence_as(structure, flat_sequence):
    """Pack a flat list back into `structure`'s shape (ref:
    utils.py:167)."""
    treedef = jax.tree_util.tree_structure(
        structure, is_leaf=lambda x: not isinstance(x, (list, tuple, dict)))
    return jax.tree_util.tree_unflatten(treedef, list(flat_sequence))


def map_structure(func, *structures):
    """Apply func leaf-wise across parallel structures (ref:
    utils.py:189)."""
    return jax.tree_util.tree_map(
        func, *structures,
        is_leaf=lambda x: not isinstance(x, (list, tuple, dict)))


__all__ = ["flatten", "pack_sequence_as", "map_structure"]
