"""fluid.input module path (ref: fluid/input.py — embedding/one_hot with
1.x signatures)."""
from .layers import embedding, one_hot  # noqa: F401

__all__ = ["embedding", "one_hot"]
