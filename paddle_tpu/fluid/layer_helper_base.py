"""fluid.layer_helper_base module path (ref: fluid/layer_helper_base.py)."""
from .layer_helper import LayerHelperBase  # noqa: F401

__all__ = ["LayerHelperBase"]
