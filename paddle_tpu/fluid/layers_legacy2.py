"""fluid.layers 1.x completion, part 2 (ref: python/paddle/fluid/layers/
{control_flow,rnn,detection,metric_op,loss,nn}.py): decoders, host-side
debug ops, tensor arrays, metrics, and the remaining detection/loss ops.
Block-style 1.x program builders (While/IfElse/Switch/DynamicRNN/
StaticRNN) raise with migration guidance — SURVEY.md §2 #42."""
from __future__ import annotations

import numpy as np

from .. import ops as _ops
from ..core.tensor import Tensor
from ..ops._registry import apply_op


def _val(x):
    import jax.numpy as jnp
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(_val(x))


# ------------------------------------------------------------ debug ops

def Print(input, first_n=-1, message=None, summarize=20,  # noqa: A002,N802
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Host-side tensor print (ref: control_flow.py Print): direct print
    eagerly, jax.debug.print inside traced regions."""
    import jax
    import jax.core as jcore
    v = _val(input)
    msg = message or "Var"
    if isinstance(v, jcore.Tracer):
        jax.debug.print(msg + " {}", v)
    else:
        print(f"{msg} shape={tuple(v.shape)} "  # cli-print: Print op
              f"dtype={v.dtype}\n{np.asarray(v).ravel()[:summarize]}")
    return input


def Assert(cond, data=None, summarize=20, name=None):  # noqa: N802
    """Runtime assert (ref: control_flow.py Assert): raises eagerly;
    checks via jax.debug inside traced regions."""
    import jax
    import jax.core as jcore
    cv = _val(cond)
    if isinstance(cv, jcore.Tracer):
        jax.debug.print("Assert cond={} (traced check)", cv)
        return None
    if not bool(np.all(np.asarray(cv))):
        extra = [np.asarray(_val(d)).ravel()[:summarize]
                 for d in (data or [])]
        raise ValueError(f"Assert failed; data={extra}")
    return None


# -------------------------------------------------------- tensor arrays

class Decoder:
    """Abstract decoder contract (initialize/step/finalize)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states

    @property
    def tracks_own_finished(self):
        return False


class DecodeHelper:
    """Sampling contract for BasicDecoder (initialize/sample/next_inputs)."""

    def initialize(self):
        raise NotImplementedError

    def sample(self, time, outputs, states):
        raise NotImplementedError

    def next_inputs(self, time, outputs, states, sample_ids):
        raise NotImplementedError


class TrainingHelper(DecodeHelper):
    """Teacher forcing: feed the ground-truth sequence (ref: rnn.py
    TrainingHelper). inputs: [B, T, ...] (batch-major)."""

    def __init__(self, inputs, sequence_length=None, time_major=False):
        import jax.numpy as jnp
        iv = _val(inputs)
        self.inputs = iv if not time_major else jnp.swapaxes(iv, 0, 1)
        self.sequence_length = None if sequence_length is None \
            else _val(sequence_length)

    def initialize(self):
        import jax.numpy as jnp
        t0 = self.inputs[:, 0]
        finished = jnp.zeros((self.inputs.shape[0],), bool) \
            if self.sequence_length is None else (self.sequence_length <= 0)
        return Tensor(t0), Tensor(finished)

    def sample(self, time, outputs, states):
        return Tensor(_val(outputs).argmax(-1))

    def next_inputs(self, time, outputs, states, sample_ids):
        import jax.numpy as jnp
        t = int(np.asarray(_val(time))) + 1
        done = t >= self.inputs.shape[1]
        nxt = self.inputs[:, min(t, self.inputs.shape[1] - 1)]
        finished = jnp.full((self.inputs.shape[0],), done) \
            if self.sequence_length is None else \
            (jnp.asarray(t) >= self.sequence_length)
        return Tensor(finished), Tensor(nxt), states


class GreedyEmbeddingHelper(DecodeHelper):
    """Feed back argmax embeddings (ref: rnn.py GreedyEmbeddingHelper)."""

    def __init__(self, embedding_fn, start_tokens, end_token):
        self.embedding_fn = embedding_fn
        self.start_tokens = _val(start_tokens)
        self.end_token = int(end_token)

    def initialize(self):
        import jax.numpy as jnp
        finished = jnp.zeros((self.start_tokens.shape[0],), bool)
        return self.embedding_fn(Tensor(self.start_tokens)), Tensor(finished)

    def sample(self, time, outputs, states):
        return Tensor(_val(outputs).argmax(-1))

    def next_inputs(self, time, outputs, states, sample_ids):
        sid = _val(sample_ids)
        finished = sid == self.end_token
        return Tensor(finished), self.embedding_fn(_t(sid)), states


class SampleEmbeddingHelper(GreedyEmbeddingHelper):
    """Feed back SAMPLED embeddings (ref: rnn.py SampleEmbeddingHelper)."""

    def __init__(self, embedding_fn, start_tokens, end_token,
                 softmax_temperature=None, seed=None):
        super().__init__(embedding_fn, start_tokens, end_token)
        self.temperature = softmax_temperature

    def sample(self, time, outputs, states):
        import jax
        from ..core import rng as rng_mod
        logits = _val(outputs)
        if self.temperature is not None:
            logits = logits / self.temperature
        return Tensor(jax.random.categorical(rng_mod.next_key(), logits,
                                             axis=-1))


class BasicDecoder(Decoder):
    """cell + helper -> one decode step (ref: rnn.py BasicDecoder).
    Works with paddle.nn.dynamic_decode."""

    class OutputWrapper:
        def __init__(self, cell_outputs, sample_ids):
            self.cell_outputs = cell_outputs
            self.sample_ids = sample_ids

    def __init__(self, cell, helper, output_fn=None):
        self.cell = cell
        self.helper = helper
        self.output_fn = output_fn

    def initialize(self, initial_cell_states):
        (inputs, finished) = self.helper.initialize()
        return inputs, initial_cell_states, finished

    def step(self, time, inputs, states, **kwargs):
        outputs, next_states = self.cell(inputs, states, **kwargs)
        if self.output_fn is not None:
            outputs = self.output_fn(outputs)
        sample_ids = self.helper.sample(time, outputs, next_states)
        finished, next_inputs, next_states = self.helper.next_inputs(
            time, outputs, next_states, sample_ids)
        return (self.OutputWrapper(outputs, sample_ids), next_states,
                next_inputs, finished)


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False):
    """One beam step (ref: beam_search_op): [B*beam, V] scores -> top
    beam_size (ids, scores) per batch with parent indices."""
    import jax.numpy as jnp
    sv = _val(scores)
    if not is_accumulated:
        sv = _val(pre_scores).reshape(-1, 1) + jnp.log(
            jnp.maximum(sv, 1e-20))
    # rows not divisible by beam_size = the first decode step (one row per
    # batch item): each row is its own group — NEVER merge candidates
    # across batch boundaries (code-review r3c)
    nb = sv.shape[0] // beam_size if sv.shape[0] % beam_size == 0 \
        else sv.shape[0]
    v = sv.shape[-1]
    flat = sv.reshape(nb, -1)  # [B, beam*V]
    top_s, top_i = jnp.sort(flat, -1)[:, ::-1][:, :beam_size], \
        jnp.argsort(-flat, -1)[:, :beam_size]
    parent = top_i // v
    token = top_i % v
    sel_ids = token.reshape(-1, 1)
    sel_scores = top_s.reshape(-1, 1)
    if return_parent_idx:
        return (Tensor(sel_ids), Tensor(sel_scores),
                Tensor(parent.reshape(-1)))
    return Tensor(sel_ids), Tensor(sel_scores)


def beam_search_decode(ids, scores, beam_size, end_id, name=None):
    """Backtrace beams to full sequences (ref: beam_search_decode_op).
    ids/scores: lists of per-step [B*beam, 1] tensors + parent idx arrays
    — here the simplified dense contract: stacked [T, B*beam]."""
    import jax.numpy as jnp
    iv = jnp.stack([_val(t).reshape(-1) for t in ids]) \
        if isinstance(ids, (list, tuple)) else _val(ids)
    sv = jnp.stack([_val(t).reshape(-1) for t in scores]) \
        if isinstance(scores, (list, tuple)) else _val(scores)
    return Tensor(iv.T), Tensor(sv.T)


# ------------------------------------------------------------ 1.x blocks

def _block_builder(name):
    class _B:
        def __init__(self, *a, **kw):
            raise NotImplementedError(
                f"fluid.layers.{name} is a 1.x block-style program builder "
                f"superseded by lax-backed control flow; use "
                f"fluid.layers.cond/while_loop/case (SURVEY.md §2 #42)")
    _B.__name__ = name
    return _B


While = _block_builder("While")
IfElse = _block_builder("IfElse")
Switch = _block_builder("Switch")
DynamicRNN = _block_builder("DynamicRNN")
StaticRNN = _block_builder("StaticRNN")


# ---------------------------------------------------------- distributions

class MultivariateNormalDiag:
    """Diagonal-covariance multivariate normal (ref:
    fluid/layers/distributions.py MultivariateNormalDiag)."""

    def __init__(self, loc, scale):
        self.loc = _val(loc)
        # reference passes a diagonal MATRIX; accept vector or matrix
        sv = _val(scale)
        self.scale_diag = sv if sv.ndim == 1 else sv.diagonal()

    def sample(self, shape=()):
        import jax
        from ..core import rng as rng_mod
        eps = jax.random.normal(rng_mod.next_key(),
                                tuple(shape) + self.loc.shape)
        return Tensor(self.loc + eps * self.scale_diag)

    def log_prob(self, value):
        import jax.numpy as jnp
        v = _val(value)
        var = self.scale_diag ** 2
        return Tensor(-0.5 * (jnp.log(2 * np.pi * var)
                              + (v - self.loc) ** 2 / var).sum(-1))

    def entropy(self):
        import jax.numpy as jnp
        return Tensor(0.5 * (jnp.log(2 * np.pi * np.e *
                                     self.scale_diag ** 2)).sum(-1))

    def kl_divergence(self, other):
        import jax.numpy as jnp
        v1 = self.scale_diag ** 2
        v2 = other.scale_diag ** 2
        return Tensor(0.5 * (jnp.log(v2 / v1) + (v1 + (self.loc -
                      other.loc) ** 2) / v2 - 1.0).sum(-1))


# --------------------------------------------------------------- pooling

def adaptive_pool2d(input, pool_size, pool_type="max",  # noqa: A002
                    require_index=False, name=None):
    from ..nn import functional as F
    fn = F.adaptive_max_pool2d if pool_type == "max" \
        else F.adaptive_avg_pool2d
    return fn(input, pool_size)


def adaptive_pool3d(input, pool_size, pool_type="max",  # noqa: A002
                    require_index=False, name=None):
    from ..nn import functional as F
    fn = F.adaptive_max_pool3d if pool_type == "max" \
        else F.adaptive_avg_pool3d
    return fn(input, pool_size)


# ------------------------------------------------------------- misc math

def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _ops.clip(x, t_min, t_max)


def inplace_abn(input, act=None, momentum=0.9, epsilon=1e-5, **kw):  # noqa: A002
    from ..static.nn import batch_norm as _bn
    return _bn(input, act=act, momentum=momentum, epsilon=epsilon, **kw)


def clip_by_norm(x, max_norm, name=None):
    return _ops.clip_by_norm(x, max_norm)


def unique_with_counts(x, dtype="int32"):
    out, index, counts = _ops.unique(x, return_inverse=True,
                                     return_counts=True)
    return out, index, counts


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,  # noqa: A002
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    from ..nn import functional as F
    from ..static.nn import _create_param
    d = _val(input).shape[-1]
    w = _create_param((num_classes - 1, d), "float32", param_attr)
    b = _create_param((num_classes - 1,), "float32", bias_attr,
                      is_bias=True)
    return F.hsigmoid_loss(_t(input), _t(label), num_classes, w, b,
                           path_table=path_table, path_code=path_code)


# ----------------------------------------------------------------- losses

def auc(input, label, curve="ROC", num_thresholds=4095,  # noqa: A002
        topk=1, slide_steps=1):
    """Host-side AUC (ref: auc_op)."""
    from ..metric import Auc
    m = auc._metric = Auc(num_thresholds=num_thresholds)
    m.update(np.asarray(_val(input)), np.asarray(_val(label)))
    a = np.asarray(m.accumulate(), np.float32)
    return (Tensor(a), Tensor(a), [Tensor(a)])


def chunk_eval(input, label, chunk_scheme, num_chunk_types,  # noqa: A002
               excluded_chunk_types=None, seq_length=None):
    """Chunking precision/recall/F1 (ref: chunk_eval_op), IOB/IOE/IOBES
    schemes, host-side."""
    pv = np.asarray(_val(input)).reshape(-1)
    lv = np.asarray(_val(label)).reshape(-1)

    def extract(tags):
        # the O tag is num_chunk_types*n_tag (ref chunk_eval_op): it is
        # OUTSIDE every chunk — it terminates the open chunk, never
        # starts one
        chunks = []
        start = None
        ctype = None
        n_tag = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[chunk_scheme]
        o_tag = num_chunk_types * n_tag
        for i, t in enumerate(tags):
            t = int(t)
            if t >= o_tag:  # Outside
                if start is not None:
                    chunks.append((start, i - 1, ctype))
                    start = None
                continue
            tag_type = t % n_tag
            cty = t // n_tag
            begin = (chunk_scheme == "IOB" and tag_type == 0) or \
                (chunk_scheme == "IOBES" and tag_type in (0, 3)) or \
                chunk_scheme == "plain"
            if begin or (start is not None and cty != ctype):
                if start is not None:
                    chunks.append((start, i - 1, ctype))
                start, ctype = i, cty
        if start is not None:
            chunks.append((start, len(tags) - 1, ctype))
        return set(chunks)

    pc, lc = extract(pv), extract(lv)
    tp = len(pc & lc)
    prec = tp / len(pc) if pc else 0.0
    rec = tp / len(lc) if lc else 0.0
    f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
    mk = Tensor(np.asarray(f1, np.float32))
    return (Tensor(np.asarray(prec, np.float32)),
            Tensor(np.asarray(rec, np.float32)), mk,
            Tensor(np.asarray(len(pc), np.int64)),
            Tensor(np.asarray(len(lc), np.int64)),
            Tensor(np.asarray(tp, np.int64)))


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,  # noqa: A002
                       name=None):
    """Greedy CTC decode: argmax, collapse repeats, strip blanks (ref:
    ctc_align_op). Dense [B, T, C] -> [B, T] padded ids."""
    pv = np.asarray(_val(input)).argmax(-1)  # [B, T]
    outs = []
    for row in pv:
        seq = []
        prev = None
        for t in row:
            if t != prev and t != blank:
                seq.append(int(t))
            prev = t
        outs.append(seq)
    width = max((len(s) for s in outs), default=0)
    dense = np.full((len(outs), max(width, 1)), padding_value, np.int64)
    for i, s in enumerate(outs):
        dense[i, :len(s)] = s
    lens = np.asarray([len(s) for s in outs], np.int64)
    return Tensor(dense), Tensor(lens)


# --------------------------------------------------------- detection tail

def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (ref: matrix_nms_op): decay scores by pairwise IoU
    instead of hard suppression. Dense single-image [N,4]+[C,N]."""
    import jax.numpy as jnp
    from ..nn.functional.detection import _iou_matrix
    bv = _val(bboxes)
    if bv.ndim == 3:
        bv = bv[0]
    sv = _val(scores)
    if sv.ndim == 3:
        sv = sv[0]
    outs = []
    for c in range(sv.shape[0]):
        if c == background_label:
            continue
        s = sv[c]
        order = jnp.argsort(-s)[:nms_top_k]
        b = bv[order]
        s = s[order]
        # the reference pre-filters below score_threshold BEFORE decay
        pre = s >= score_threshold
        iou = _iou_matrix(b, b)
        iou = jnp.triu(iou, k=1)
        max_iou = iou.max(0)
        if use_gaussian:
            decay = jnp.exp(-(max_iou ** 2) / gaussian_sigma)
        else:
            decay = (1 - max_iou)
        s2 = s * decay
        keep = (s2 >= post_threshold) & pre
        for i in np.nonzero(np.asarray(keep))[0]:
            outs.append([c, float(s2[i]), *np.asarray(b[i])])
    outs.sort(key=lambda r: -r[1])
    outs = outs[:keep_top_k]
    arr = np.asarray(outs, np.float32) if outs else \
        np.zeros((0, 6), np.float32)
    if return_rois_num:
        return Tensor(arr), Tensor(np.asarray([len(outs)], np.int64))
    return Tensor(arr)


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    """Locality-aware NMS (ref: locality_aware_nms_op, EAST): weighted
    merge of consecutive overlapping boxes then standard NMS."""
    from ..nn.functional.detection import multiclass_nms
    return multiclass_nms(bboxes, scores,
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold,
                          background_label=background_label)


