"""fluid.lod_tensor module path (ref: fluid/lod_tensor.py)."""
from .compat1x import create_lod_tensor, create_random_int_lodtensor  # noqa: F401,E501

__all__ = ["create_lod_tensor", "create_random_int_lodtensor"]
