"""fluid.reader module path (ref: fluid/reader.py — PyReader/DataLoader).

TPU-first rework: PyReader was the 1.x way to pump python-generated
batches into the static Executor. Here it is a thin adapter that turns
the decorated generator into feed dicts keyed by the feed_list
Variables' names — exactly what `Executor.run(feed=...)` consumes — so
1.x training loops port without restructuring:

    reader = fluid.io.PyReader(feed_list=[x, y], capacity=64)
    reader.decorate_batch_generator(gen)
    for data in reader():
        exe.run(main_prog, feed=data, fetch_list=[loss])

The 2.0 path (io.DataLoader) is re-exported alongside, like the
reference does.
"""
from __future__ import annotations

import numpy as np

from ..io import DataLoader  # noqa: F401


class PyReader:
    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False):
        # capacity/use_double_buffer are accepted for signature parity:
        # prefetch depth is the consuming DataLoader/executor's concern on
        # this stack (XLA owns the device pipeline)
        self._feed_list = list(feed_list or [])
        self._iterable = iterable
        self._return_list = return_list
        self._batch_fn = None

    # -- decoration (ref: reader.py decorate_* trio) -----------------------
    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        """sample_generator yields ONE sample tuple at a time."""

        def batches():
            buf = []
            for sample in sample_generator():
                buf.append(sample if isinstance(sample, (tuple, list))
                           else (sample,))
                if len(buf) == batch_size:
                    yield buf
                    buf = []
            if buf and not drop_last:
                yield buf
        self._batch_fn = lambda: (self._stack(b) for b in batches())

    def decorate_sample_list_generator(self, reader, places=None):
        """reader yields a LIST of sample tuples per batch."""
        self._batch_fn = lambda: (self._stack(b) for b in reader())

    def decorate_batch_generator(self, reader, places=None):
        """reader yields already-batched arrays (tuple/list per feed)."""

        def norm():
            for batch in reader():
                if not isinstance(batch, (tuple, list)):
                    batch = (batch,)
                yield [np.asarray(a) for a in batch]
        self._batch_fn = norm

    # -- consumption -------------------------------------------------------
    def _stack(self, sample_list):
        n = len(sample_list[0])
        return [np.stack([np.asarray(s[i]) for s in sample_list])
                for i in range(n)]

    def _to_feed(self, arrays):
        if self._return_list or not self._feed_list:
            return list(arrays)
        names = [getattr(v, "name", str(i))
                 for i, v in enumerate(self._feed_list)]
        return dict(zip(names, arrays))

    def __call__(self):
        return iter(self)

    def __iter__(self):
        if self._batch_fn is None:
            raise RuntimeError(
                "PyReader has no source: call decorate_sample_generator / "
                "decorate_sample_list_generator / decorate_batch_generator "
                "first")
        for arrays in self._batch_fn():
            yield self._to_feed(arrays)

    # non-iterable 1.x mode ran the reader through exe.run() implicitly;
    # on this stack the executor consumes explicit feeds, so the iterable
    # protocol is the supported path (reference 2.0 defaults to it too)
    def start(self):
        if self._iterable:
            raise RuntimeError("start() is for iterable=False; this "
                               "PyReader is iterable — loop `for data in "
                               "reader():` and pass data as feed")
        raise NotImplementedError(
            "non-iterable PyReader (implicit executor feed) is not "
            "supported on this stack: construct with iterable=True and "
            "pass the yielded feed dicts to Executor.run explicitly")

    def reset(self):
        self.start()


__all__ = ["PyReader", "DataLoader"]
