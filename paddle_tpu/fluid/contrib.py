"""fluid.contrib (ref: python/paddle/fluid/contrib/) — the 1.x contrib
grab-bag, mapped onto the TPU-native stack. Cells/fusions/pooling ops get
real implementations (XLA fuses what the reference hand-fused); the
CPU-cluster-only pieces (HDFS transfer, boxPS sparse pulls, distributed
program transpiles) raise with guidance — SURVEY.md §2 #42."""
from __future__ import annotations

import numpy as np

from .. import ops as _ops
from ..core.tensor import Tensor
from ..nn.layer.rnn import GRUCell as BasicGRUUnit  # noqa: F401
from ..nn.layer.rnn import LSTMCell as BasicLSTMUnit  # noqa: F401
from ..ops._registry import apply_op


def _val(x):
    import jax.numpy as jnp
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(_val(x))


def basic_gru(input, init_hidden, hidden_size, num_layers=1,  # noqa: A002
              sequence_length=None, dropout_prob=0.0, bidirectional=False,
              batch_first=True, param_attr=None, bias_attr=None,
              gate_activation=None, activation=None, dtype="float32",
              name="basic_gru"):
    from ..nn import GRU
    from ..nn.functional.legacy import legacy_param_store
    in_dim = _val(input).shape[-1]
    # parameters are identified by NAME (1.x program semantics) via the
    # LegacyParamStore — no shape-keyed sharing across distinct call sites
    net = legacy_param_store().layer(
        f"{name}/{in_dim}x{hidden_size}l{num_layers}b{int(bidirectional)}",
        lambda: GRU(in_dim, hidden_size, num_layers=num_layers,
                    direction="bidirect" if bidirectional else "forward"))
    out, h = net(_t(input), init_hidden)
    return out, h


def basic_lstm(input, init_hidden, init_cell, hidden_size,  # noqa: A002
               num_layers=1, sequence_length=None, dropout_prob=0.0,
               bidirectional=False, batch_first=True, param_attr=None,
               bias_attr=None, gate_activation=None, activation=None,
               forget_bias=1.0, dtype="float32", name="basic_lstm"):
    from ..nn import LSTM
    from ..nn.functional.legacy import legacy_param_store
    in_dim = _val(input).shape[-1]
    net = legacy_param_store().layer(
        f"{name}/{in_dim}x{hidden_size}l{num_layers}b{int(bidirectional)}",
        lambda: LSTM(in_dim, hidden_size, num_layers=num_layers,
                     direction="bidirect" if bidirectional else "forward"))
    states = None if init_hidden is None else (init_hidden, init_cell)
    out, (h, c) = net(_t(input), states)
    return out, h, c


def fused_bn_add_act(x, y, momentum=0.9, epsilon=1e-5, param_attr=None,
                     bias_attr=None, moving_mean_name=None,
                     moving_variance_name=None, act="relu", name=None):
    """bn(x) + y then act (ref: fused_bn_add_act) — XLA fuses the chain."""
    from ..static.nn import batch_norm
    out = _ops.add(batch_norm(x, momentum=momentum, epsilon=epsilon,
                              param_attr=param_attr, bias_attr=bias_attr),
                   _t(y))
    return getattr(_ops, act)(out) if act else out


def fused_elemwise_activation(x, y, functor_list, axis=-1, scale=0.0,
                              save_intermediate_out=True):
    """Apply functor chain like ['elementwise_add','relu'] (ref:
    fused_elemwise_activation_op) — XLA fuses it anyway."""
    out = _t(x)
    other = _t(y)
    for f in functor_list:
        if f.startswith("elementwise_"):
            from . import layers as L
            out = getattr(L, f)(out, other)
        elif f == "scale":
            out = _ops.scale(out, scale)
        else:
            out = getattr(_ops, f)(out)
    return out


def fused_embedding_seq_pool(input, size, is_sparse=False,  # noqa: A002
                             padding_idx=None, combiner="sum",
                             param_attr=None, dtype="float32"):
    """Embedding lookup + sequence pool in one op (ref:
    fused_embedding_seq_pool_op). Dense [B, T] ids -> [B, D]."""
    from ..static.nn import embedding
    emb = embedding(input, size, padding_idx=padding_idx,
                    param_attr=param_attr, dtype=dtype)
    return _ops.sum(emb, axis=1) if combiner == "sum" \
        else _ops.mean(emb, axis=1)


def partial_concat(input, start_index=0, length=-1):  # noqa: A002
    """Concat column slices of each input (ref: partial_concat_op)."""
    import jax.numpy as jnp
    parts = []
    for t in input:
        v = _val(t)
        end = v.shape[1] if length < 0 else start_index + length
        parts.append(v[:, start_index:end])
    return Tensor(jnp.concatenate(parts, axis=1))


def partial_sum(input, start_index=0, length=-1):  # noqa: A002
    import jax.numpy as jnp
    parts = []
    for t in input:
        v = _val(t)
        end = v.shape[1] if length < 0 else start_index + length
        parts.append(v[:, start_index:end])
    return Tensor(sum(parts[1:], parts[0]))


def shuffle_batch(x, seed=None):
    """Shuffle rows across the batch (ref: shuffle_batch_op)."""
    import jax

    from ..core import rng as rng_mod

    def core(xv, key=None):
        perm = jax.random.permutation(key, xv.shape[0])
        return xv[perm]

    return apply_op(core, "shuffle_batch", (_t(x),),
                    {"key": rng_mod.next_key()}, nondiff=True)


def sequence_topk_avg_pooling(input, row, col, topks, channel_num):  # noqa: A002
    """Top-k average pooling over sequence scores (ref:
    sequence_topk_avg_pooling_op), dense [B, C, T] layout."""
    import jax.numpy as jnp

    def core(xv):
        outs = []
        for k in topks:
            top = jnp.sort(xv, axis=-1)[..., ::-1][..., :k]
            outs.append(top.mean(-1))
        return jnp.stack(outs, -1).reshape(xv.shape[0], -1)

    return apply_op(core, "seq_topk_avg_pool", (_t(input),), {})


def match_matrix_tensor(x, y, channel_num, act=None, param_attr=None,
                        dtype="float32", name=None):
    """Semantic match matrix (ref: match_matrix_tensor_op): x W y^T per
    channel. Dense [B, Tx, D] x [B, Ty, D] -> [B, C, Tx, Ty]."""
    import jax.numpy as jnp

    from ..static.nn import _create_param
    d = _val(x).shape[-1]
    w = _create_param((d, channel_num, d), dtype, param_attr)

    def core(xv, yv, wv):
        return jnp.einsum("btd,dce,bse->bcts", xv, wv, yv)

    out = apply_op(core, "match_matrix", (_t(x), _t(y), w), {})
    return (getattr(_ops, act)(out) if act else out), None


def var_conv_2d(input, row, col, input_channel, output_channel,  # noqa: A002
                filter_size, stride=1, param_attr=None, act=None,
                dtype="float32", name=None):
    """Variable-size 2d conv over sequence grids (ref: var_conv_2d_op) —
    dense rework: plain conv2d."""
    from ..static.nn import conv2d
    return conv2d(input, output_channel, filter_size, stride=stride,
                  param_attr=param_attr, act=act)


def rank_attention(input, rank_offset, rank_param_shape, rank_param_attr,  # noqa: A002
                   max_rank=3, max_size=0):
    """Rank-gated attention projection for CTR (ref: rank_attention_op):
    per-sample parameter block selected by rank pair."""
    import jax.numpy as jnp

    from ..static.nn import _create_param
    w = _create_param(tuple(rank_param_shape), "float32", rank_param_attr)

    def core(xv, ro, wv):
        d = xv.shape[1]
        block = wv.reshape(max_rank * max_rank, d, -1)
        ranks = jnp.clip(ro[:, 0].astype(jnp.int32), 0, max_rank - 1)
        sel = block[ranks]  # [B, D, O]
        return jnp.einsum("bd,bdo->bo", xv, sel)

    return apply_op(core, "rank_attention", (_t(input), _t(rank_offset), w),
                    {})


def tdm_child(x, node_nums, child_nums, param_attr=None, dtype="int32"):
    """Tree-based deep model child lookup (ref: tdm_child_op): static tree
    info table [node_nums, child_nums] -> per-id children + leaf mask."""
    from ..static.nn import _create_param
    info = _create_param((node_nums, child_nums), dtype, param_attr)

    def core(xv, iv):
        child = iv[xv.reshape(-1).astype("int32")]
        return child.reshape(xv.shape + (child_nums,))

    child = apply_op(core, "tdm_child", (_t(x), info), {}, nondiff=True)
    mask = _ops.cast(_ops.greater_than(
        child, _ops.zeros_like(child)), "int32")
    return child, mask


def tdm_sampler(x, neg_samples_num_list, layer_node_num_list, leaf_node_num,
                tree_travel_attr=None, tree_layer_attr=None,
                output_positive=True, output_list=None, seed=0,
                tree_dtype="int32", dtype="int32"):
    """TDM negative sampler (ref: tdm_sampler_op): per tree layer, sample
    negatives uniformly from that layer's nodes."""
    import jax

    from ..core import rng as rng_mod
    xv = np.asarray(_val(x)).reshape(-1)
    rngk = rng_mod.next_key()
    outs, labels, masks = [], [], []
    start = 0
    for li, (n_neg, n_nodes) in enumerate(zip(neg_samples_num_list,
                                              layer_node_num_list)):
        negs = np.asarray(jax.random.randint(
            jax.random.fold_in(rngk, li), (xv.shape[0], n_neg),
            start, start + n_nodes))
        pos = xv[:, None] % max(n_nodes, 1) + start
        if output_positive:
            layer = np.concatenate([pos, negs], 1)
            lab = np.concatenate([np.ones_like(pos),
                                  np.zeros_like(negs)], 1)
        else:
            layer, lab = negs, np.zeros_like(negs)
        outs.append(Tensor(layer.astype(np.int32)))
        labels.append(Tensor(lab.astype(np.int32)))
        masks.append(Tensor(np.ones_like(lab, np.int32)))
        start += n_nodes
    return outs, labels, masks


def sparse_embedding(input, size, padding_idx=None, is_test=False,  # noqa: A002
                     entry=None, param_attr=None, dtype="float32"):
    """Distributed sparse embedding (ref: contrib/layers/sparse_embedding):
    the PS-lite host table IS the sparse parameter here."""
    from ..distributed.ps import PSEmbedding
    from ..nn.functional.legacy import legacy_param_store
    nm = getattr(param_attr, "name", None) or f"sparse_emb_{size[0]}x{size[1]}"
    layer = legacy_param_store().layer(
        nm, lambda: PSEmbedding(size[0], size[1]))
    return layer(_t(input))


def ctr_metric_bundle(input, label):  # noqa: A002
    """CTR metric bundle (ref: contrib/layers/metric_op.py): returns
    (auc, batch_auc, [stat tensors])."""
    from .layers_legacy2 import auc as _auc
    return _auc(input, label)


def bilateral_slice(x, guide, grid, has_offset=False, name=None):
    """HDRNet bilateral-grid slice (ref: bilateral_slice_op): trilinear
    sample of affine coefficient grid at (x, y, guide)."""
    import jax
    import jax.numpy as jnp

    def core(xv, gv, grid_v):
        b, c, h, w = xv.shape
        gd, gh, gw = grid_v.shape[2:]
        ys = jnp.linspace(0, gh - 1, h)
        xs = jnp.linspace(0, gw - 1, w)
        yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
        zz = jnp.clip(gv[:, 0] * (gd - 1), 0, gd - 1)  # [B,H,W]

        def samp(grid_b, z_b):
            z0 = jnp.floor(z_b).astype(jnp.int32)
            z1 = jnp.minimum(z0 + 1, gd - 1)
            wz = z_b - z0
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            g0 = grid_b[:, z0, y0, x0]
            g1 = grid_b[:, z1, y0, x0]
            return g0 * (1 - wz) + g1 * wz  # [C', H, W]

        coeff = jax.vmap(samp)(grid_v, zz)  # [B, C', H, W]
        n_out = coeff.shape[1] // (c + 1) if has_offset else \
            coeff.shape[1] // c
        cc = coeff.reshape(b, n_out, -1, h, w)
        out = jnp.einsum("bochw,bchw->bohw", cc[:, :, :c], xv)
        if has_offset:
            out = out + cc[:, :, c]
        return out

    return apply_op(core, "bilateral_slice", (_t(x), _t(guide), _t(grid)),
                    {})


def correlation(x, y, pad_size, kernel_size, max_displacement, stride1,
                stride2, corr_type_multiply=1):
    """FlowNet correlation layer (ref: correlation_op): cost volume of
    shifted dot products."""
    import jax.numpy as jnp

    def core(xv, yv):
        b, c, h, w = xv.shape
        d = max_displacement
        yp = jnp.pad(yv, ((0, 0), (0, 0), (d, d), (d, d)))
        outs = []
        for dy in range(-d, d + 1, stride2):
            for dx in range(-d, d + 1, stride2):
                shifted = yp[:, :, d + dy:d + dy + h, d + dx:d + dx + w]
                outs.append((xv * shifted).mean(1))
        return jnp.stack(outs, 1)

    return apply_op(core, "correlation", (_t(x), _t(y)), {})


def batch_fc(input, param_size, param_attr, bias_size, bias_attr,  # noqa: A002
             act=None):
    """Per-slot batched fc (ref: batch_fc_op): input [S, B, D] with its own
    [S, D, O] weight per slot."""
    import jax.numpy as jnp

    from ..static.nn import _create_param
    w = _create_param(tuple(param_size), "float32", param_attr)
    bias = _create_param(tuple(bias_size), "float32", bias_attr,
                         is_bias=True)

    def core(xv, wv, bv):
        return jnp.einsum("sbd,sdo->sbo", xv, wv) + bv[:, None]

    out = apply_op(core, "batch_fc", (_t(input), w, bias), {})
    return getattr(_ops, act)(out) if act else out


def search_pyramid_hash(input, num_emb, space_len, pyramid_layer, rand_len,  # noqa: A002
                        drop_out_percent, is_training, use_filter,
                        white_list_len, black_list_len, seed, lr,
                        param_attr=None, param_attr_wl=None,
                        param_attr_bl=None, name=None,
                        distribute_update_vars=None, dtype="float32"):
    """Pyramid hash embedding (ref: search_pyramid_hash_op): n-gram ids
    hashed into a shared space, summed per pyramid layer — simplified
    dense rework."""
    from ..static.nn import _create_param
    import jax.numpy as jnp
    table = _create_param((space_len, num_emb), dtype, param_attr)

    def core(xv, tv):
        acc = 0.0
        for n in range(1, pyramid_layer + 1):
            ids = (xv * 131 + n) % space_len
            acc = acc + tv[ids.astype(jnp.int32)].sum(1)
        return acc

    return apply_op(core, "pyramid_hash", (_t(input), table), {})


def extend_with_decoupled_weight_decay(base_optimizer):
    """AdamW-style decoupled decay wrapper (ref: contrib/optimizer.py):
    returns a class whose weight_decay applies after the update."""
    class DecoupledWeightDecay(base_optimizer):
        def __init__(self, *a, weight_decay=0.0, **kw):
            kw["weight_decay"] = weight_decay
            super().__init__(*a, **kw)

        def _decoupled(self):
            return True

    DecoupledWeightDecay.__name__ = \
        f"Decoupled{base_optimizer.__name__}"
    return DecoupledWeightDecay


def memory_usage(program=None, batch_size=1):
    """Rough parameter-memory estimate (ref: contrib/memory_usage_calc):
    returns (low, high) MB for the program's persistables."""
    from ..static.program import default_main_program
    program = program or default_main_program()
    total = 0
    for v in program.global_block().vars.values():
        if getattr(v, "persistable", False) and v.shape:
            n = int(np.prod([d for d in v.shape if d and d > 0]))
            total += n * 4
    mb = total / (1 << 20)
    return mb * 0.9, mb * 1.1


class Momentum:
    """ref: contrib/optimizer.py Momentum (the fluid-era ctor); delegates
    to optimizer.Momentum."""

    def __new__(cls, *a, **kw):
        from ..optimizer import Momentum as M
        return M(*a, **kw)


# ---- CPU-cluster-only pieces: documented drops (SURVEY.md §2 #42) ----

def _cluster_only(name, why):
    def fn(*a, **kw):
        raise NotImplementedError(
            f"fluid.contrib.{name} targets the reference's CPU-cluster "
            f"runtime ({why}); not applicable to the TPU backend "
            f"(SURVEY.md §2 #42)")
    fn.__name__ = name
    return fn


from ..distributed.fleet.utils.fs import HDFSClient  # noqa: E402 — real
# hadoop-CLI client (fleet.utils.fs); raises ExecuteError with guidance
# when no hadoop install is present
multi_download = _cluster_only("multi_download", "HDFS file transfer")
multi_upload = _cluster_only("multi_upload", "HDFS file transfer")
_pull_box_extended_sparse = _cluster_only("_pull_box_extended_sparse",
                                          "BoxPS embedding service")
convert_dist_to_sparse_program = _cluster_only(
    "convert_dist_to_sparse_program", "DistributeTranspiler programs")
load_persistables_for_increment = _cluster_only(
    "load_persistables_for_increment", "lookup-table checkpoint shards")
load_persistables_for_inference = _cluster_only(
    "load_persistables_for_inference", "lookup-table checkpoint shards")
distributed_batch_reader = _cluster_only(
    "distributed_batch_reader", "trainer-sharded readers; use "
    "io.DistributedBatchSampler")
op_freq_statistic = _cluster_only("op_freq_statistic",
                                  "ProgramDesc op statistics")


def multiclass_nms2(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                    nms_threshold=0.3, normalized=True, nms_eta=1.0,
                    background_label=0, return_index=False, name=None):
    from ..nn.functional.detection import multiclass_nms
    return multiclass_nms(bboxes, scores, score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold,
                          background_label=background_label)


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    from .dygraph import TreeConv
    from ..nn.functional.legacy import legacy_param_store
    d = _val(nodes_vector).shape[-1]
    nm = (name or "tree_conv") + f"/{d}x{output_size}f{num_filters}"
    layer = legacy_param_store().layer(
        nm, lambda: TreeConv(d, output_size, num_filters, max_depth, act))
    return layer(_t(nodes_vector), _t(edge_set))


class mixed_precision:
    """Namespace shim for contrib.mixed_precision (ref:
    fluid/contrib/mixed_precision/) — decorate() maps onto amp."""

    @staticmethod
    def decorate(optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
                 use_dynamic_loss_scaling=True, **kw):
        """1.x: returns an optimizer whose backward/minimize run under
        loss scaling (ref: contrib/mixed_precision/decorator.py). On
        TPU the compute dtype is bf16 (f32 exponent range), so the
        GradScaler this wraps is a numerically-safe no-op passthrough —
        the wrapper preserves the 1.x call shape."""
        from ..amp import GradScaler

        class _AmpOptimizer:
            def __init__(self, inner):
                self._inner = inner
                self._scaler = GradScaler(
                    init_loss_scaling=init_loss_scaling,
                    use_dynamic_loss_scaling=use_dynamic_loss_scaling)

            def backward(self, loss, **bkw):
                scaled = self._scaler.scale(loss)
                scaled.backward()
                return scaled

            def minimize(self, loss, **mkw):
                self.backward(loss)
                self._scaler.step(self._inner)
                self._scaler.update()
                return None, None

            def step(self):
                self._scaler.step(self._inner)
                self._scaler.update()

            def __getattr__(self, name):
                return getattr(self._inner, name)

        return _AmpOptimizer(optimizer)


class InitState:
    """Initial decoder state descriptor (ref: contrib/decoder/
    beam_search_decoder.py InitState): holds either a concrete init
    tensor or (shape, value) to materialize lazily."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        if init is not None:
            self._init = init
        elif init_boot is not None:
            import jax.numpy as jnp

            from ..core.tensor import Tensor
            boot = init_boot._value if hasattr(init_boot, "_value") \
                else jnp.asarray(init_boot)
            # fill_constant_batch_size_like contract (ref beam_search_
            # decoder.py:83): shape[0] (usually -1) is REPLACED by the
            # boot batch dim, the rest is taken verbatim
            shape = list(shape) if shape else [-1]
            out_shape = [int(boot.shape[0])] + [int(s) for s in shape[1:]]
            self._init = Tensor(jnp.full(tuple(out_shape), value, dtype))
        else:
            raise ValueError("init or init_boot must be provided")
        self.need_reorder = need_reorder

    @property
    def value(self):
        return self._init


class StateCell:
    """Decoder state container driving a step function (ref: contrib
    StateCell): registered states update each `compute_state` call via
    the user's cell; works eagerly on this stack (the jitted decode loop
    is `paddle.nn.dynamic_decode`)."""

    def __init__(self, inputs=None, states=None, steps=None, name=None):
        self._states = dict(states or {})
        self._inputs = dict(inputs or {})
        self._cur_states = {s: (v.value if isinstance(v, InitState) else v)
                            for s, v in self._states.items()}
        self._updaters = []

    def get_state(self, name):
        if name not in self._cur_states:
            raise KeyError(f"unknown decoder state {name!r}")
        return self._cur_states[name]

    def get_input(self, name):
        if name not in self._inputs:
            raise KeyError(f"unknown decoder input {name!r}")
        return self._inputs[name]

    def set_state(self, name, value):
        self._cur_states[name] = value

    def state_updater(self, fn):
        self._updaters.append(fn)
        return fn

    def compute_state(self, inputs):
        self._inputs.update(inputs)
        for fn in self._updaters:
            fn(self)

    def out_state(self):
        return dict(self._cur_states)

    def update_states(self):
        pass  # eager semantics: set_state already committed


class TrainingDecoder:
    """The 1.x while-loop graph-builder decoder is superseded by the
    dynamic decoding stack: build a `paddle.nn.RNNCell`-style cell and
    train with teacher forcing directly, or decode with
    `paddle.nn.BeamSearchDecoder` + `paddle.nn.dynamic_decode`
    (block-style builder drop, same class as SURVEY §2 #42)."""

    def __init__(self, state_cell, name=None):
        raise NotImplementedError(
            "TrainingDecoder builds 1.x while_loop blocks; on this stack "
            "run the cell directly over the time axis (teacher forcing is "
            "a lax.scan under jit) or use paddle.nn.dynamic_decode. "
            "StateCell/InitState remain usable as state containers.")


class BeamSearchDecoder:
    """See TrainingDecoder — inference-side of the same block builder."""

    def __init__(self, state_cell, *a, **kw):
        raise NotImplementedError(
            "contrib.BeamSearchDecoder builds 1.x while_loop blocks; use "
            "paddle.nn.BeamSearchDecoder with paddle.nn.dynamic_decode "
            "(tested in tests/test_beam_search.py), or model.generate() "
            "for KV-cache decoding.")


class QuantizeTranspiler:
    """Static-graph quantization transpiler (ref: contrib/slim
    QuantizeTranspiler): superseded by the imperative quantization in
    paddle.slim — ImperativeQuantAware (QAT) and
    PostTrainingQuantization (PTQ), both able to export a servable int8
    artifact via save_quantized_model."""

    def __init__(self, *a, **kw):
        raise NotImplementedError(
            "QuantizeTranspiler rewrites 1.x Programs; quantize the Layer "
            "instead: paddle.slim.ImperativeQuantAware().quantize(model) "
            "for QAT or paddle.slim.PostTrainingQuantization for PTQ, "
            "then save_quantized_model() for the int8 artifact.")


# ---- reference module-attribute surface of fluid.contrib (ref:
# fluid/contrib/__init__.py import list) ----
import sys as _sys

layers = _sys.modules[__name__]  # contrib layer fns live flat, right here


class AutoMixedPrecisionLists:
    """Op allow/deny lists consulted by AMP decoration (ref:
    contrib/mixed_precision/fp16_lists.py). The TPU AMP policy casts by
    op category; custom lists extend/shrink the categories."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        self.white_list = set(custom_white_list or [])
        self.black_list = set(custom_black_list or [])
        self.black_varnames = set(custom_black_varnames or [])


decorate = mixed_precision.decorate  # 1.x top-level spelling


class decoder:  # ref: contrib/decoder/__init__
    pass


class beam_search_decoder:  # ref: contrib/decoder/beam_search_decoder.py
    pass


class quantize:  # ref: contrib/quantize/__init__
    pass


class extend_optimizer:  # ref: contrib/extend_optimizer/__init__
    @staticmethod
    def extend_with_decoupled_weight_decay(base_optimizer):
        """Build <Base>WithDecoupledWeightDecay: the BASE update rule
        plus weight decay applied directly to params, not to grads (ref:
        contrib/extend_optimizer/extend_optimizer_with_weight_decay.py).
        Adam maps onto the native AdamW; any other optimizer gets a
        subclass that decays params before its own step."""
        from ..optimizer import Adam, AdamW
        if base_optimizer is Adam:
            class AdamWithDecoupledWeightDecay(AdamW):
                def __init__(self, *args, coeff=0.01, **kwargs):
                    # 1.x spells the decay strength `coeff`
                    kwargs.setdefault("weight_decay", coeff)
                    super().__init__(*args, **kwargs)

            return AdamWithDecoupledWeightDecay

        class OptimizerWithDecoupledWeightDecay(base_optimizer):
            def __init__(self, *args, coeff=0.01, **kwargs):
                super().__init__(*args, **kwargs)
                self._wd_coeff = float(coeff)

            def step(self):
                lr = float(self.get_lr())
                for p in self._parameter_list or []:
                    if p is not None and p.trainable \
                            and p.grad is not None:
                        p._value = p._value * (1.0 - lr * self._wd_coeff)
                super().step()

        OptimizerWithDecoupledWeightDecay.__name__ = \
            base_optimizer.__name__ + "WithDecoupledWeightDecay"
        return OptimizerWithDecoupledWeightDecay


def memory_usage(program, batch_size=1):
    """Rough activation+param memory of a Program in MB (ref:
    contrib/memory_usage_calc.py): sum of var numel × dtype width, batch
    dim filled with `batch_size`."""
    import numpy as np
    total = 0
    for var in program.global_block().vars.values():
        shape = [batch_size if (s is None or s < 0) else s
                 for s in (var.shape or ())]
        width = 2 if "16" in str(var.dtype) else 8 \
            if "64" in str(var.dtype) else 4
        total += int(np.prod(shape)) * width if shape else width
    return total / (1 << 20)


class memory_usage_calc:
    memory_usage = staticmethod(memory_usage)


class model_stat:  # ref: contrib/model_stat.py (param/flops table)
    @staticmethod
    def summary(main_prog):
        n_params = sum(
            1 for v in main_prog.global_block().vars.values()
            if getattr(v, "persistable", False))
        print(f"Program: {n_params} persistable vars")  # cli-print: report


def op_freq_statistic(program):
    """Op-type frequency of a Program (ref: contrib/op_frequence.py)."""
    from collections import Counter
    uni = Counter(op.type for op in program.global_block().ops)
    adj = Counter()
    ops_ = program.global_block().ops
    for a, b in zip(ops_, ops_[1:]):
        adj[f"{a.type}->{b.type}"] += 1
    return uni, adj


class op_frequence:
    op_freq_statistic = staticmethod(op_freq_statistic)


class _QatModule:
    """slim.quantization.imperative.qat — the 1.x import home of
    ImperativeQuantAware (ref: contrib/slim/quantization/imperative/
    qat.py); the implementation is paddle_tpu.slim."""


class slim:  # ref: contrib/slim/__init__ — 1.x home of quantization
    class quantization:
        class imperative:
            qat = _QatModule

        @staticmethod
        def _bind():
            pass


def _bind_slim():
    from .. import slim as _slim_mod
    slim.quantization.ImperativeQuantAware = _slim_mod.ImperativeQuantAware
    slim.quantization.PostTrainingQuantization = \
        _slim_mod.PostTrainingQuantization
    slim.quantization.QuantizeTranspiler = QuantizeTranspiler
    _QatModule.ImperativeQuantAware = _slim_mod.ImperativeQuantAware
    decoder.InitState = InitState
    decoder.StateCell = StateCell
    decoder.TrainingDecoder = TrainingDecoder
    decoder.BeamSearchDecoder = BeamSearchDecoder
    decoder.beam_search_decoder = beam_search_decoder
    beam_search_decoder.InitState = InitState
    beam_search_decoder.StateCell = StateCell
    beam_search_decoder.TrainingDecoder = TrainingDecoder
    beam_search_decoder.BeamSearchDecoder = BeamSearchDecoder
    quantize.QuantizeTranspiler = QuantizeTranspiler
    from .. import optimizer as _opt
    from .. import reader as _reader
    globals()["optimizer"] = _opt
    globals()["reader"] = _reader


_bind_slim()


class utils:
    """contrib.utils (ref: contrib/utils/hdfs_utils.py). No HDFS exists
    on this zero-egress stack; HDFSClient operates on LOCAL paths with
    the same method surface so staging code runs against mounted
    filesystems (a real cluster FS appears as a mount on TPU VMs)."""

    class HDFSClient:
        def __init__(self, hadoop_home=None, configs=None):
            pass

        def is_exist(self, path):
            import os
            return os.path.exists(path)

        def is_dir(self, path):
            import os
            return os.path.isdir(path)

        def ls(self, path):
            import os
            return sorted(os.path.join(path, f)
                          for f in os.listdir(path))

        def mkdirs(self, path):
            import os
            os.makedirs(path, exist_ok=True)

        def delete(self, path):
            import os
            import shutil
            if os.path.isdir(path):
                shutil.rmtree(path)
            elif os.path.exists(path):
                os.remove(path)

        def upload(self, hdfs_path, local_path, overwrite=True,
                   retry_times=5):
            import shutil
            shutil.copy(local_path, hdfs_path)

        def download(self, hdfs_path, local_path, overwrite=True):
            import shutil
            shutil.copy(hdfs_path, local_path)

    @staticmethod
    def multi_download(client, hdfs_path, local_path, trainer_id,
                      trainers, file_cnt=None):
        import os
        files = client.ls(hdfs_path)
        mine = [f for i, f in enumerate(sorted(files))
                if i % trainers == trainer_id]
        os.makedirs(local_path, exist_ok=True)
        for f in mine:
            client.download(f, os.path.join(local_path,
                                            os.path.basename(f)))
        return mine

    @staticmethod
    def multi_upload(client, hdfs_path, local_path, multi_processes=5,
                     overwrite=False):
        import os
        client.mkdirs(hdfs_path)
        for f in sorted(os.listdir(local_path)):
            client.upload(os.path.join(hdfs_path, f),
                          os.path.join(local_path, f))
