"""fluid.core — the 1.x C++-core attribute surface, Python-native here.

Reference-era user code reaches the compiled core directly
(`fluid.core.CPUPlace()`, `fluid.core.Scope()`, `fluid.core.LoDTensor`;
ref: python/paddle/fluid/__init__.py:71 re-exporting from .core). On this
stack there is no separate C++ tensor type — a LoDTensor IS the framework
Tensor (jax.Array-backed, LoD retired with static padding/masking), and a
Scope is the executor's name->value mapping.
"""
from __future__ import annotations

from ..core.place import (  # noqa: F401
    CPUPlace, CUDAPinnedPlace, CUDAPlace, Place, TPUPlace, XPUPlace)
from ..core.tensor import Tensor  # noqa: F401
from ..static.executor import Scope  # noqa: F401

# In the reference, LoDTensor is the C++ dense tensor and VarBase the
# dygraph tensor; both unify onto the one jax.Array-backed Tensor here.
LoDTensor = Tensor
LoDTensorArray = list
VarBase = Tensor
_Scope = Scope

NPUPlace = TPUPlace  # accepted, mapped to the accelerator place
IPUPlace = TPUPlace
MLUPlace = TPUPlace


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_rocm():
    return False


def get_cuda_device_count():
    return 0


def _cuda_synchronize(place=None):
    """Block until pending device work completes (ref: core._cuda_synchronize).
    XLA dispatch is async the same way CUDA streams are; effectful_barrier
    is a device-agnostic drain."""
    import jax
    (jax.device_put(0) + 0).block_until_ready()


def globals():  # noqa: A001 — reference name (core.globals() flag registry)
    from . import _FLAGS
    return dict(_FLAGS)


def set_num_threads(n):  # host-side op threading is XLA's concern
    return None
