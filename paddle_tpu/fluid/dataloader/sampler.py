"""fluid.dataloader.sampler module path (ref: fluid/dataloader/sampler.py)."""
from ...io import RandomSampler, Sampler, SequenceSampler  # noqa: F401

__all__ = ["Sampler", "RandomSampler", "SequenceSampler"]
