"""fluid.dataloader package path (ref: fluid/dataloader/) — the 1.x
home of Dataset/IterableDataset/samplers, which live in paddle_tpu.io."""
from ...io import (  # noqa: F401
    BatchSampler, Dataset, IterableDataset, RandomSampler, Sampler,
    SequenceSampler,
)
from ...io import get_worker_info  # noqa: F401

__all__ = ["Dataset", "IterableDataset", "BatchSampler", "Sampler",
           "RandomSampler", "SequenceSampler", "get_worker_info"]
