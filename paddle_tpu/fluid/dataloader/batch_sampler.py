"""fluid.dataloader.batch_sampler module path (ref:
fluid/dataloader/batch_sampler.py)."""
from ...io import BatchSampler  # noqa: F401

__all__ = ["BatchSampler"]
