"""fluid.dataloader.dataset module path (ref: fluid/dataloader/dataset.py)."""
from ...io import Dataset, IterableDataset  # noqa: F401

__all__ = ["Dataset", "IterableDataset"]
