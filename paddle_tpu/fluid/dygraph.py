"""fluid.dygraph compatibility (ref: python/paddle/fluid/dygraph/).

Dygraph is the default mode here, so `guard()` is a no-op context that also
ensures static mode is off.
"""
from __future__ import annotations

import contextlib

import numpy as np

from ..core import mode
from ..core.tensor import Tensor
from ..nn import (  # noqa: F401  fluid-era layer aliases
    BatchNorm, Embedding, LayerList, Linear, Sequential,
)
from ..nn.layer.layers import Layer  # noqa: F401
from ..jit import TranslatedLayer  # noqa: F401


@contextlib.contextmanager
def guard(place=None):
    was_static = mode.in_static_mode()
    mode.disable_static()
    try:
        yield
    finally:
        if was_static:
            mode.enable_static()


def to_variable(value, name=None, zero_copy=None, dtype=None):
    if isinstance(value, Tensor):
        return value
    return Tensor(np.asarray(value), dtype=dtype, name=name)


def enabled():
    return mode.in_dygraph_mode()


class Conv2D(Layer):
    """fluid.dygraph.Conv2D (NCHW, act fused — old ctor signature)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32"):
        super().__init__()
        from ..nn import Conv2D as NewConv2D
        self._conv = NewConv2D(num_channels, num_filters, filter_size,
                               stride=stride, padding=padding,
                               dilation=dilation, groups=groups or 1,
                               weight_attr=param_attr, bias_attr=bias_attr)
        self._act = act

    def forward(self, x):
        from .. import ops
        out = self._conv(x)
        return getattr(ops, self._act)(out) if self._act else out


class Pool2D(Layer):
    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True):
        super().__init__()
        self._cfg = dict(pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride, pool_padding=pool_padding,
                         global_pooling=global_pooling, ceil_mode=ceil_mode,
                         exclusive=exclusive)

    def forward(self, x):
        from ..static.nn import pool2d
        return pool2d(x, **{k: v for k, v in self._cfg.items()
                            if k in ("pool_size", "pool_type", "pool_stride",
                                     "pool_padding", "global_pooling",
                                     "ceil_mode", "exclusive")})


def save_dygraph(state_dict, model_path):
    from ..framework.io import save
    save(state_dict, model_path + ".pdparams")


def load_dygraph(model_path):
    import os

    from ..framework.io import load
    params = load(model_path + ".pdparams") \
        if os.path.exists(model_path + ".pdparams") else None
    opt = load(model_path + ".pdopt") \
        if os.path.exists(model_path + ".pdopt") else None
    return params, opt


no_grad = None  # populated below


def _init():
    global no_grad
    from ..core.autograd import _NoGradDecorator
    no_grad = _NoGradDecorator()


_init()


# ---- 1.x dygraph aliases onto the 2.0 implementations (ref:
# python/paddle/fluid/dygraph/{nn,learning_rate_scheduler,checkpoint}.py;
# the fluid-era ctor quirks live with the aliased classes) ----
from ..nn import (  # noqa: E402,F401
    BilinearTensorProduct, Conv2DTranspose, Conv3D, Conv3DTranspose,
    Dropout, Flatten, GRUCell, GroupNorm, InstanceNorm2D as InstanceNorm,
    LSTMCell, LayerNorm, PReLU as PRelu, ParameterList, SpectralNorm,
)
from ..optimizer.lr import (  # noqa: E402,F401
    CosineAnnealingDecay as CosineDecay, ExponentialDecay,
    InverseTimeDecay, LambdaDecay, LinearWarmup as LinearLrWarmup,
    MultiStepDecay, NaturalExpDecay, NoamDecay, PiecewiseDecay,
    PolynomialDecay, ReduceOnPlateau as ReduceLROnPlateau, StepDecay,
)
from ..amp import GradScaler as AmpScaler, auto_cast as amp_guard  # noqa: E402,F401
from ..distributed.collective import ParallelEnv  # noqa: E402,F401
from ..distributed.parallel import DataParallel  # noqa: E402,F401
from ..jit import (  # noqa: E402,F401
    ProgramTranslator, TracedLayer, declarative, not_to_static,
    set_code_level, set_verbosity, to_static as dygraph_to_static_func,
)
from ..core.autograd import grad  # noqa: E402,F401
from . import dygraph as _self_mod  # noqa: E402

save = save_dygraph
load = load_dygraph
no_grad_ = no_grad


def disable_dygraph():
    mode.enable_static()


def enable_dygraph(place=None):
    mode.disable_static()


def prepare_context(strategy=None):
    from ..distributed.parallel import init_parallel_env
    return init_parallel_env()


class GRUUnit(Layer):
    """fluid.dygraph.GRUUnit (ref: dygraph/nn.py GRUUnit): single-step GRU
    over pre-projected gate inputs [B, 3*hidden]."""

    def __init__(self, size, param_attr=None, bias_attr=None,
                 activation="tanh", gate_activation="sigmoid",
                 origin_mode=False, dtype="float32"):
        super().__init__()
        from ..nn import GRUCell as _GRUCell
        self.hidden = size // 3
        self._cell = _GRUCell(self.hidden, self.hidden)

    def forward(self, input, hidden):  # noqa: A002
        h, new = self._cell(input[:, : self.hidden], hidden)
        return new, None, h


class NCE(Layer):
    """fluid.dygraph.NCE (ref: dygraph/nn.py NCE) over static.nn.nce."""

    def __init__(self, num_total_classes, dim, sample_weight=None,
                 param_attr=None, bias_attr=None, num_neg_samples=5,
                 sampler="uniform", custom_dist=None, seed=0,
                 is_sparse=False):
        super().__init__()
        from ..core.tensor import Parameter
        from ..nn import initializer as I
        self.num_total_classes = num_total_classes
        self.num_neg_samples = num_neg_samples
        self.weight = Parameter(I.XavierUniform()((num_total_classes, dim),
                                                  "float32"))
        self.bias = Parameter(I.Constant(0.0)((num_total_classes,),
                                              "float32"))

    def forward(self, input, label, sample_weight=None):  # noqa: A002
        import jax
        import jax.numpy as jnp

        from ..core import rng as rng_mod
        from ..ops._registry import apply_op
        key = rng_mod.next_key()
        n_neg = self.num_neg_samples
        n_cls = self.num_total_classes

        def core(xv, lv, wv, bv):
            bsz = xv.shape[0]
            lv = lv.reshape(-1).astype(jnp.int32)
            negs = jax.random.randint(key, (bsz, n_neg), 0, n_cls)
            pos = jnp.sum(xv * wv[lv], -1) + bv[lv]
            neg = jnp.einsum("bd,bnd->bn", xv, wv[negs]) + bv[negs]
            return (jax.nn.softplus(-pos)
                    + jnp.sum(jax.nn.softplus(neg), -1))[:, None]

        return apply_op(core, "nce_layer",
                        (input, label, self.weight, self.bias), {})


class TreeConv(Layer):
    """fluid.dygraph.TreeConv (ref: dygraph/nn.py TreeConv): tree-based
    convolution over node features with adjacency-continuity weights.
    Dense rework: nodes [B, N, D], edges adjacency [B, N, N]."""

    def __init__(self, feature_size, output_size, num_filters=1,
                 max_depth=2, act="tanh", param_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        from ..core.tensor import Parameter
        from ..nn import initializer as I
        self.max_depth = max_depth
        self.act = act
        self.W = Parameter(I.XavierUniform()(
            (feature_size, 3, output_size, num_filters), "float32"))
        self.bias = Parameter(I.Constant(0.0)((num_filters, output_size),
                                              "float32"))

    def forward(self, nodes_vector, edge_set):
        import jax.numpy as jnp

        from ..ops._registry import apply_op

        depth = self.max_depth

        def core(xv, adj, wv, bv):
            # propagate features up to max_depth hops; weights [D,3,O,F]
            # use the 3 continuity slots as (self, child-mean, depth-mix)
            a = adj.astype(xv.dtype)
            deg = jnp.maximum(a.sum(-1, keepdims=True), 1.0)
            child = (a @ xv) / deg
            hops = child
            mix = 0.0
            for _ in range(depth - 1):
                hops = (a @ hops) / deg
                mix = mix + hops
            feats = jnp.stack([xv, child, mix if depth > 1
                               else jnp.zeros_like(xv)], axis=2)
            # y: [B, N, O, F]; bias is [F, O] -> transpose to broadcast
            return jnp.einsum("bnsd,dsof->bnof", feats, wv) \
                + bv.T[None, None]

        out = apply_op(core, "tree_conv",
                       (nodes_vector, edge_set, self.W, self.bias), {})
        from .. import ops as _ops2
        return getattr(_ops2, self.act)(out) if self.act else out


# ---- submodule attribute surface of the reference package (ref:
# fluid/dygraph/__init__.py binds base/checkpoint/container/... as
# attributes; 1.x user code reaches e.g. fluid.dygraph.base.to_variable
# and fluid.dygraph.learning_rate_scheduler.NoamDecay) ----
import sys as _sys

nn = _sys.modules[__name__]      # dygraph layer classes live right here
layers = _sys.modules[__name__]  # Layer/sublayer defs (dygraph/layers.py)


class base:  # ref: fluid/dygraph/base.py
    from ..core.mode import in_dygraph_mode
    in_dygraph_mode = staticmethod(in_dygraph_mode)
    enabled = staticmethod(enabled)
    to_variable = staticmethod(to_variable)
    guard = staticmethod(guard)

    @staticmethod
    def in_declarative_mode():
        from ..core import mode
        return mode.in_static_mode()


class checkpoint:  # ref: fluid/dygraph/checkpoint.py
    save_dygraph = staticmethod(save_dygraph)
    load_dygraph = staticmethod(load_dygraph)


class container:  # ref: fluid/dygraph/container.py
    @staticmethod
    def _bind():
        pass


class rnn:  # ref: fluid/dygraph/rnn.py
    @staticmethod
    def _bind():
        pass


class learning_rate_scheduler:  # ref: fluid/dygraph/learning_rate_scheduler.py
    @staticmethod
    def _bind():
        pass


class tracer:  # ref: fluid/dygraph/tracer.py
    class Tracer:
        """The C++ imperative tracer is the eager vjp tape on this stack
        (core/autograd.py); this shell satisfies isinstance checks and
        the train/eval flag contract."""

        def __init__(self):
            self._train_mode = True

        def train_mode(self):
            self._train_mode = True

        def eval_mode(self):
            self._train_mode = False


class StaticModelRunner:
    """1.x: run a saved static inference model inside dygraph (ref:
    fluid/dygraph/static_runner.py delegating to TranslatedLayer). Load
    the artifact with jit.load and call it like a Layer."""

    def __new__(cls, model_dir, model_filename=None, params_filename=None):
        import os

        from .. import jit as _jit
        stem = (model_filename or "__model__").replace(".pdmodel", "")
        if params_filename is not None:
            pstem = params_filename.replace(".pdiparams", "")
            if pstem != stem:
                raise ValueError(
                    f"artifact pair must share one prefix: model "
                    f"'{stem}' vs params '{pstem}' — jit.save writes "
                    "<prefix>.pdmodel + <prefix>.pdiparams")
        prefix = os.path.join(model_dir, stem)
        if not os.path.exists(prefix + ".pdmodel"):
            raise FileNotFoundError(
                f"no {prefix}.pdmodel; StaticModelRunner loads artifacts "
                "written by paddle.jit.save(prefix) — pass "
                "model_filename to pick a non-default prefix")
        return _jit.load(prefix)


def monkey_patch_math_varbase():
    """Tensor operator patching happens at import on this stack; kept
    callable for 1.x code invoking it explicitly."""


def _late_bind():
    # populated after import so the class namespaces can reference
    # modules that import THIS module (container/rnn/lr/amp/parallel/io)
    from .. import amp as _amp
    from .. import jit as _jit
    from ..distributed import parallel as _par
    from ..nn import LayerList, ParameterList, Sequential
    from ..nn import GRUCell, LSTMCell
    from ..optimizer import lr as _lr
    container.LayerList = LayerList
    container.Sequential = Sequential
    container.ParameterList = ParameterList
    rnn.LSTMCell = LSTMCell
    rnn.GRUCell = GRUCell
    for _n in ("NoamDecay", "PiecewiseDecay", "NaturalExpDecay",
               "ExponentialDecay", "InverseTimeDecay", "PolynomialDecay",
               "CosineAnnealingDecay", "StepDecay", "MultiStepDecay",
               "LambdaDecay", "LinearWarmup", "ReduceOnPlateau",
               "ReduceLROnPlateau"):
        if hasattr(_lr, _n):
            setattr(learning_rate_scheduler, _n, getattr(_lr, _n))
    learning_rate_scheduler.CosineDecay = getattr(
        _lr, "CosineAnnealingDecay", None)
    globals()["amp"] = _amp
    globals()["jit"] = _jit
    globals()["parallel"] = _par
    globals()["io"] = _jit          # TranslatedLayer machinery
    globals()["dygraph_to_static"] = _jit  # ProgramTranslator home
    globals()["static_runner"] = _sys.modules[__name__]


_late_bind()  # fluid.dygraph imports after nn/optimizer, so this is safe
