"""fluid.dygraph compatibility (ref: python/paddle/fluid/dygraph/).

Dygraph is the default mode here, so `guard()` is a no-op context that also
ensures static mode is off.
"""
from __future__ import annotations

import contextlib

import numpy as np

from ..core import mode
from ..core.tensor import Tensor
from ..nn import (  # noqa: F401  fluid-era layer aliases
    BatchNorm, Embedding, LayerList, Linear, Sequential,
)
from ..nn.layer.layers import Layer  # noqa: F401
from ..jit import TranslatedLayer  # noqa: F401


@contextlib.contextmanager
def guard(place=None):
    was_static = mode.in_static_mode()
    mode.disable_static()
    try:
        yield
    finally:
        if was_static:
            mode.enable_static()


def to_variable(value, name=None, zero_copy=None, dtype=None):
    if isinstance(value, Tensor):
        return value
    return Tensor(np.asarray(value), dtype=dtype, name=name)


def enabled():
    return mode.in_dygraph_mode()


class Conv2D(Layer):
    """fluid.dygraph.Conv2D (NCHW, act fused — old ctor signature)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32"):
        super().__init__()
        from ..nn import Conv2D as NewConv2D
        self._conv = NewConv2D(num_channels, num_filters, filter_size,
                               stride=stride, padding=padding,
                               dilation=dilation, groups=groups or 1,
                               weight_attr=param_attr, bias_attr=bias_attr)
        self._act = act

    def forward(self, x):
        from .. import ops
        out = self._conv(x)
        return getattr(ops, self._act)(out) if self._act else out


class Pool2D(Layer):
    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True):
        super().__init__()
        self._cfg = dict(pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride, pool_padding=pool_padding,
                         global_pooling=global_pooling, ceil_mode=ceil_mode,
                         exclusive=exclusive)

    def forward(self, x):
        from ..static.nn import pool2d
        return pool2d(x, **{k: v for k, v in self._cfg.items()
                            if k in ("pool_size", "pool_type", "pool_stride",
                                     "pool_padding", "global_pooling",
                                     "ceil_mode", "exclusive")})


def save_dygraph(state_dict, model_path):
    from ..framework.io import save
    save(state_dict, model_path + ".pdparams")


def load_dygraph(model_path):
    import os

    from ..framework.io import load
    params = load(model_path + ".pdparams") \
        if os.path.exists(model_path + ".pdparams") else None
    opt = load(model_path + ".pdopt") \
        if os.path.exists(model_path + ".pdopt") else None
    return params, opt


no_grad = None  # populated below


def _init():
    global no_grad
    from ..core.autograd import _NoGradDecorator
    no_grad = _NoGradDecorator()


_init()
