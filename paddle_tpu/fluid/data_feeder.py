"""fluid.data_feeder module path (ref: fluid/data_feeder.py)."""
from .compat1x import DataFeeder  # noqa: F401

__all__ = ["DataFeeder"]
