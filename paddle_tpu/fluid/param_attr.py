"""fluid.param_attr module path (ref: fluid/param_attr.py)."""
from ..core.param_attr import ParamAttr  # noqa: F401
from ..static import WeightNormParamAttr  # noqa: F401

__all__ = ["ParamAttr", "WeightNormParamAttr"]
