"""fluid.transpiler package path (ref: fluid/transpiler/) — the 1.x
distribute-transpiler API; implementations live in the fluid compat
layer (DistributeTranspiler lowers to this stack's PS/collective
mechanisms; memory_optimize/release_memory are documented no-ops under
XLA, which owns buffer liveness)."""
from .. import (  # noqa: F401
    DistributeTranspiler, DistributeTranspilerConfig, memory_optimize,
    release_memory,
)

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "memory_optimize", "release_memory"]
