"""fluid.transpiler.distribute_transpiler module path (ref:
fluid/transpiler/distribute_transpiler.py)."""
from .. import DistributeTranspiler, DistributeTranspilerConfig  # noqa: F401,E501

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig"]
