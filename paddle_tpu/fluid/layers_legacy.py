"""fluid.layers 1.x completion (ref: python/paddle/fluid/layers/*).

Everything here adapts a 1.x symbol onto the TPU-native implementations
that already power the 2.0 namespaces: sequence ops come from the dense
LoD rework (nn/functional/sequence.py), detection from
nn/functional/detection.py, decay functions return the corresponding
LRScheduler, RNN cells/decoders come from nn. A handful of 1.x
graph-construction constructs that the reference itself superseded
(py_reader pipelines, DynamicRNN/StaticRNN/IfElse/Switch/While block
builders) raise with migration guidance — recorded in SURVEY.md §2 #42.
"""
from __future__ import annotations

import numpy as np

from .. import ops as _ops
from ..core.tensor import Tensor
from ..ops._registry import apply_op


_py_range = range  # the 1.x `range` op below shadows the builtin


def _val(x):
    import jax.numpy as jnp
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# ---------------------------------------------------------------- arithmetic

def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _ops.maximum(x, y)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _ops.minimum(x, y)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _ops.mod(x, y)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _ops.pow(x, y)


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    return _ops.floor_divide(x, y)


def reduce_min(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _ops.min(input, axis=dim, keepdim=keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _ops.prod(input, axis=dim, keepdim=keep_dim)


def reduce_all(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _ops.all(input, axis=dim, keepdim=keep_dim)


def reduce_any(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _ops.any(input, axis=dim, keepdim=keep_dim)


def sums(input, out=None):  # noqa: A002
    r = input[0]
    for t in input[1:]:
        r = _ops.add(r, t)
    return r


def multiplex(inputs, index, name=None):
    """Row-wise select: out[i] = inputs[index[i]][i] (ref: multiplex_op)."""
    import jax.numpy as jnp

    def core(idx, *ts):
        stacked = jnp.stack(ts)  # [n, batch, ...]
        rows = jnp.arange(stacked.shape[1])
        return stacked[idx.reshape(-1), rows]

    args = [index if isinstance(index, Tensor) else Tensor(_val(index))]
    args += [t if isinstance(t, Tensor) else Tensor(_val(t))
             for t in inputs]
    return apply_op(core, "multiplex", tuple(args), {})


def cos_sim(X, Y):  # noqa: N803
    from ..nn.functional import cosine_similarity
    return cosine_similarity(X, Y, axis=-1)


def l2_normalize(x, axis=-1, epsilon=1e-12, name=None):
    from ..nn.functional import normalize
    return normalize(x, p=2, axis=axis, epsilon=epsilon)


def shape(input, name=None):  # noqa: A002
    return Tensor(np.asarray(_val(input).shape, np.int32))


def rank(input):  # noqa: A002
    return Tensor(np.asarray(_val(input).ndim, np.int32))


def size(input):  # noqa: A002
    return Tensor(np.asarray(int(np.prod(_val(input).shape)), np.int64))


def is_empty(x, name=None):
    return Tensor(np.asarray(int(np.prod(_val(x).shape)) == 0))


def has_inf(x):
    return _ops.any(_ops.isinf(x))


def has_nan(x):
    return _ops.any(_ops.isnan(x))


def reverse(x, axis):
    return _ops.flip(x, axis)


def range(start, end, step, dtype, name=None):  # noqa: A001
    return _ops.arange(start, end, step, dtype=dtype)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,  # noqa: A002
                   name=None):
    return _ops.uniform(shape, dtype=dtype, min=min, max=max, seed=seed)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    return _ops.add(_ops.multiply(_ops.randn(shape, dtype=dtype),
                                  Tensor(np.asarray(std, dtype))),
                    Tensor(np.asarray(mean, dtype)))


def _batch_size_like(ref, shape, input_dim_idx, output_dim_idx):
    shape = list(shape)
    shape[output_dim_idx] = _val(ref).shape[input_dim_idx]
    return shape


def fill_constant_batch_size_like(input, shape, dtype, value,  # noqa: A002
                                  input_dim_idx=0, output_dim_idx=0,
                                  force_cpu=False):
    return _ops.full(_batch_size_like(input, shape, input_dim_idx,
                                      output_dim_idx), value, dtype)


def uniform_random_batch_size_like(input, shape, dtype="float32",  # noqa: A002
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):  # noqa: A002
    return uniform_random(_batch_size_like(input, shape, input_dim_idx,
                                           output_dim_idx), dtype, min, max,
                          seed)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,  # noqa: A002
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    return gaussian_random(_batch_size_like(input, shape, input_dim_idx,
                                            output_dim_idx), mean, std, seed,
                           dtype)


def create_tensor(dtype, name=None, persistable=False):
    t = Tensor(np.zeros((0,), dtype))
    t.persistable = persistable
    return t


def create_array(dtype):
    return []


def tensor_array_to_tensor(input, axis=1, use_stack=False):  # noqa: A002
    ts = [_val(t) for t in input]
    import jax.numpy as jnp
    out = jnp.stack(ts, axis) if use_stack else jnp.concatenate(ts, axis)
    return Tensor(out), Tensor(np.asarray([t.shape[axis] for t in ts]))


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):  # noqa: A002
    """Sample one category id per row from softmax-ed scores (ref:
    sampling_id_op)."""
    from ..core import rng as rng_mod
    import jax

    def core(xv, key=None):
        return jax.random.categorical(key, jax.nn.log_softmax(xv, -1),
                                      axis=-1)

    return apply_op(core, "sampling_id",
                    (x if isinstance(x, Tensor) else Tensor(_val(x)),),
                    {"key": rng_mod.next_key()}, nondiff=True)


# ------------------------------------------------------------- activations

def hard_shrink(x, threshold=0.5):
    return _ops.hardshrink(x, threshold)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _ops.hardsigmoid(x, slope, offset)


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    return _ops.hardswish(x)


def soft_relu(x, threshold=40.0, name=None):
    import jax.numpy as jnp

    def core(xv):
        return jnp.log1p(jnp.exp(jnp.clip(xv, -threshold, threshold)))

    return apply_op(core, "soft_relu",
                    (x if isinstance(x, Tensor) else Tensor(_val(x)),), {})


# -------------------------------------------------------------- lr decays
# 1.x decay "layers" return the matching scheduler — optimizers accept it
# directly (ref: fluid/layers/learning_rate_scheduler.py)

def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    from ..optimizer import lr
    return lr.ExponentialDecay(learning_rate, gamma=decay_rate)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    from ..optimizer import lr
    return lr.NaturalExpDecay(learning_rate, gamma=decay_rate)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    from ..optimizer import lr
    return lr.InverseTimeDecay(learning_rate, gamma=decay_rate)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    from ..optimizer import lr
    return lr.PolynomialDecay(learning_rate, decay_steps, end_learning_rate,
                              power, cycle)


def piecewise_decay(boundaries, values):
    from ..optimizer import lr
    return lr.PiecewiseDecay(boundaries, values)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    from ..optimizer import lr
    return lr.CosineAnnealingDecay(learning_rate,
                                   T_max=step_each_epoch * epochs)


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    from ..optimizer import lr
    return lr.NoamDecay(d_model, warmup_steps, learning_rate)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    from ..optimizer import lr
    base = learning_rate if isinstance(learning_rate, float) \
        else getattr(learning_rate, "base_lr", end_lr)
    return lr.LinearWarmup(learning_rate, warmup_steps, start_lr, end_lr) \
        if hasattr(lr, "LinearWarmup") else lr.PolynomialDecay(
            base, warmup_steps, end_lr)


# ---------------------------------------------------------------- pooling

def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,  # noqa: A002
           pool_padding=0, global_pooling=False, ceil_mode=False, name=None,
           exclusive=True, data_format="NCDHW"):
    from ..nn import functional as F
    if global_pooling:
        return F.adaptive_max_pool3d(input, 1) if pool_type == "max" \
            else F.adaptive_avg_pool3d(input, 1)
    fn = F.max_pool3d if pool_type == "max" else F.avg_pool3d
    return fn(input, pool_size, pool_stride, pool_padding,
              ceil_mode=ceil_mode)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,  # noqa: A002
        data_format="NCHW"):
    from ..nn import functional as F
    return F.local_response_norm(input, n, alpha=alpha, beta=beta, k=k,
                                 data_format=data_format)


def grid_sampler(x, grid, name=None):
    return _ops.grid_sample(x, grid)


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,  # noqa: A002
          data_format="NCHW", name=None):
    from ..nn import functional as F
    return F.pad(input, list(paddings), mode="constant" if
                 mode == "constant" else mode, value=pad_value,
                 data_format=data_format)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    import jax.numpy as jnp

    def core(xv, yv):
        pads = [(0, xs - ys) for xs, ys in zip(xv.shape, yv.shape)]
        return jnp.pad(yv, pads, constant_values=pad_value)

    return apply_op(core, "pad_constant_like",
                    (x if isinstance(x, Tensor) else Tensor(_val(x)),
                     y if isinstance(y, Tensor) else Tensor(_val(y))), {})


def crop_tensor(x, shape=None, offsets=None, name=None):
    xv = _val(x)
    offsets = offsets or [0] * xv.ndim
    slices = tuple(slice(o, o + s) for o, s in zip(offsets, shape))

    def core(xv):
        return xv[slices]

    return apply_op(core, "crop_tensor",
                    (x if isinstance(x, Tensor) else Tensor(xv),), {})


def image_resize(input, out_shape=None, scale=None, name=None,  # noqa: A002
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1, data_format="NCHW"):
    mode = {"BILINEAR": "bilinear", "NEAREST": "nearest",
            "TRILINEAR": "trilinear", "LINEAR": "linear",
            "BICUBIC": "bicubic"}[resample.upper()]
    return _ops.interpolate(input, size=out_shape, scale_factor=scale,
                            mode=mode, align_corners=align_corners)


def image_resize_short(input, out_short_len, resample="BILINEAR"):  # noqa: A002
    h, w = _val(input).shape[2], _val(input).shape[3]
    if h < w:
        out = [out_short_len, int(w * out_short_len / h)]
    else:
        out = [int(h * out_short_len / w), out_short_len]
    return image_resize(input, out_shape=out, resample=resample)


def resize_bilinear(input, out_shape=None, scale=None, **kw):  # noqa: A002
    return image_resize(input, out_shape, scale, resample="BILINEAR")


def resize_nearest(input, out_shape=None, scale=None, **kw):  # noqa: A002
    return image_resize(input, out_shape, scale, resample="NEAREST")


def resize_linear(input, out_shape=None, scale=None, **kw):  # noqa: A002
    return image_resize(input, out_shape, scale, resample="LINEAR")


def resize_trilinear(input, out_shape=None, scale=None, **kw):  # noqa: A002
    return image_resize(input, out_shape, scale, resample="TRILINEAR")


def random_crop(x, shape, seed=None):
    import jax

    from ..core import rng as rng_mod

    def core(xv, key=None):
        starts = [jax.random.randint(jax.random.fold_in(key, i), (),
                                     0, xs - s + 1)
                  for i, (xs, s) in enumerate(zip(xv.shape[1:], shape))]
        idx = tuple([slice(None)] + [
            slice(None)] * 0)
        out = xv
        for i, (st, s) in enumerate(zip(starts, shape)):
            out = jax.lax.dynamic_slice_in_dim(out, st, s, axis=i + 1)
        return out

    return apply_op(core, "random_crop",
                    (x if isinstance(x, Tensor) else Tensor(_val(x)),),
                    {"key": rng_mod.next_key()}, nondiff=True)


def shuffle_channel(x, group, name=None):
    import jax.numpy as jnp

    def core(xv):
        b, c, h, w = xv.shape
        return xv.reshape(b, group, c // group, h, w) \
            .swapaxes(1, 2).reshape(b, c, h, w)

    return apply_op(core, "shuffle_channel",
                    (x if isinstance(x, Tensor) else Tensor(_val(x)),), {})


def space_to_depth(x, blocksize, name=None):
    import jax.numpy as jnp

    def core(xv):
        b, c, h, w = xv.shape
        bs = blocksize
        xv = xv.reshape(b, c, h // bs, bs, w // bs, bs)
        return xv.transpose(0, 3, 5, 1, 2, 4).reshape(
            b, c * bs * bs, h // bs, w // bs)

    return apply_op(core, "space_to_depth",
                    (x if isinstance(x, Tensor) else Tensor(_val(x)),), {})


def similarity_focus(input, axis, indexes, name=None):  # noqa: A002
    """Similarity-focus mask (ref: similarity_focus_op): per selected
    channel, mark max positions across the remaining dims."""
    import jax.numpy as jnp

    def core(xv):
        mask = jnp.zeros_like(xv)
        for idx in indexes:
            ch = jnp.take(xv, idx, axis=axis)  # [B, ...]
            m1 = (ch == ch.max(axis=-1, keepdims=True))
            m2 = (ch == ch.max(axis=-2, keepdims=True))
            sel = (m1 | m2).astype(xv.dtype)
            mask = mask + jnp.expand_dims(sel, axis) * 0 + \
                jnp.expand_dims(sel, axis)
        return jnp.minimum(mask, 1.0)

    return apply_op(core, "similarity_focus",
                    (input if isinstance(input, Tensor)
                     else Tensor(_val(input)),), {})


def hash(input, hash_size, num_hash=1, name=None):  # noqa: A001,A002
    """Integer feature hashing (ref: hash_op): deterministic mod-hash of
    id sequences into `hash_size` buckets, `num_hash` different salts."""
    import jax.numpy as jnp

    def core(xv):
        xv = xv.astype(jnp.int64)
        outs = []
        for i in _py_range(num_hash):
            salt = jnp.int64(0x9E3779B1 + i * 0x85EBCA77)
            h = (xv * salt) % jnp.int64(hash_size)
            outs.append(h)
        return jnp.stack(outs, -1).reshape(xv.shape[:-1] + (-1,))

    return apply_op(core, "hash",
                    (input if isinstance(input, Tensor)
                     else Tensor(_val(input)),), {}, nondiff=True)


# ------------------------------------------------------------------ losses

def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=1.0):
    from ..nn import functional as F
    delta = 1.0 / (sigma * sigma)
    return F.smooth_l1_loss(x, y, reduction="none", delta=delta)


def kldiv_loss(x, target, reduction="mean", name=None):
    from ..nn import functional as F
    return F.kl_div(x, target, reduction=reduction)


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    return _ops.relu(_ops.add(
        _ops.multiply(_ops.scale(label, -1.0),
                      _ops.subtract(left, right)),
        Tensor(np.asarray(margin, np.float32))))


def rank_loss(label, left, right, name=None):
    """RankNet pairwise loss (ref: rank_loss_op)."""
    import jax.numpy as jnp

    def core(lv, l_, r_):
        o = l_ - r_
        return jnp.log1p(jnp.exp(o)) - lv * o

    return apply_op(core, "rank_loss",
                    tuple(t if isinstance(t, Tensor) else Tensor(_val(t))
                          for t in (label, left, right)), {})


def dice_loss(input, label, epsilon=1e-5):  # noqa: A002
    from ..nn import functional as F
    return F.dice_loss(input, label, epsilon)


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    from ..nn import functional as F
    return F.log_loss(input, label, epsilon)


def teacher_student_sigmoid_loss(input, label,  # noqa: A002
                                 soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """Distillation loss (ref: teacher_student_sigmoid_loss_op): CTR
    teacher-student sigmoid cross-entropy."""
    import jax.numpy as jnp

    def core(xv, yv):
        x = jnp.clip(xv, soft_max_lower_bound, soft_max_up_bound)
        return jnp.log1p(jnp.exp(-jnp.abs(x))) + jnp.maximum(x, 0.0) \
            - x * yv

    return apply_op(core, "ts_sigmoid_loss",
                    (input if isinstance(input, Tensor)
                     else Tensor(_val(input)),
                     label if isinstance(label, Tensor)
                     else Tensor(_val(label))), {})


def fsp_matrix(x, y):
    """Flow-of-solution-procedure matrix for distillation (ref:
    fsp_op): [B, Cx, Cy] = x·y^T over spatial dims / (H*W)."""
    import jax.numpy as jnp

    def core(xv, yv):
        b, cx, h, w = xv.shape
        cy = yv.shape[1]
        xf = xv.reshape(b, cx, h * w)
        yf = yv.reshape(b, cy, h * w)
        return jnp.einsum("bxs,bys->bxy", xf, yf) / (h * w)

    return apply_op(core, "fsp_matrix",
                    (x if isinstance(x, Tensor) else Tensor(_val(x)),
                     y if isinstance(y, Tensor) else Tensor(_val(y))), {})


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=
                                       True, use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """Sampled softmax CE (ref: sample_logits_op): uniform negatives +
    the true class, softmax over the reduced set."""
    import jax
    import jax.numpy as jnp

    from ..core import rng as rng_mod

    def core(lg, lb, key=None):
        bsz, n_cls = lg.shape
        lb = lb.reshape(-1)
        negs = jax.random.randint(key, (bsz, num_samples), 0, n_cls)
        idx = jnp.concatenate([lb[:, None], negs], -1)  # true first
        sel = jnp.take_along_axis(lg, idx, axis=1)
        if remove_accidental_hits:
            hit = (idx == lb[:, None]) & \
                (jnp.arange(idx.shape[1])[None] > 0)
            sel = jnp.where(hit, -1e20, sel)
        return -jax.nn.log_softmax(sel, -1)[:, 0:1]

    return apply_op(core, "sampled_softmax_ce",
                    (logits if isinstance(logits, Tensor)
                     else Tensor(_val(logits)),
                     label if isinstance(label, Tensor)
                     else Tensor(_val(label))),
                    {"key": rng_mod.next_key()})


def warpctc(input, label, blank=0, norm_by_times=False,  # noqa: A002
            input_length=None, label_length=None):
    from ..nn import functional as F
    return F.ctc_loss(input, label, input_length, label_length, blank=blank,
                      reduction="none")


def edit_distance(input, label, normalized=True, ignored_tokens=None,  # noqa: A002
                  input_length=None, label_length=None):
    """Levenshtein distance per pair (ref: edit_distance_op). Dense
    [B, T] int sequences; host-side DP via pure_callback (the reference
    computes on CPU too)."""
    import jax

    iv, lv = _val(input), _val(label)

    def _dist(a, b):
        la, lb = len(a), len(b)
        dp = np.arange(lb + 1, dtype=np.int64)
        for i in _py_range(1, la + 1):
            prev = dp.copy()
            dp[0] = i
            for j in _py_range(1, lb + 1):
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1,
                            prev[j - 1] + (a[i - 1] != b[j - 1]))
        return dp[lb]

    def host(iv, lv, il, ll):
        out = np.zeros((iv.shape[0], 1), np.float32)
        seq_num = np.asarray([iv.shape[0]], np.int64)
        for b in _py_range(iv.shape[0]):
            a = iv[b][: int(il[b])] if il is not None else iv[b]
            c = lv[b][: int(ll[b])] if ll is not None else lv[b]
            if ignored_tokens:
                a = [t for t in a if t not in ignored_tokens]
                c = [t for t in c if t not in ignored_tokens]
            d = _dist(list(a), list(c))
            out[b, 0] = d / max(len(c), 1) if normalized else d
        return out, seq_num

    il = _val(input_length) if input_length is not None else None
    ll = _val(label_length) if label_length is not None else None
    out, seq_num = host(np.asarray(iv), np.asarray(lv),
                        np.asarray(il) if il is not None else None,
                        np.asarray(ll) if ll is not None else None)
    return Tensor(out), Tensor(seq_num)


def mean_iou(input, label, num_classes):  # noqa: A002
    """Mean intersection-over-union over classes (ref: mean_iou_op)."""
    pv, lv = np.asarray(_val(input)), np.asarray(_val(label))
    ious, wrong, correct = [], [], []
    for c in np.arange(num_classes):
        pred_c = pv == c
        lbl_c = lv == c
        inter = np.logical_and(pred_c, lbl_c).sum()
        union = np.logical_or(pred_c, lbl_c).sum()
        if union > 0:
            ious.append(inter / union)
        correct.append(inter)
        wrong.append(np.logical_xor(pred_c, lbl_c).sum())
    miou = float(np.mean(ious)) if ious else 0.0
    return (Tensor(np.asarray(miou, np.float32)),
            Tensor(np.asarray(wrong, np.int64)),
            Tensor(np.asarray(correct, np.int64)))


# ------------------------------------------------------------- rnn family

def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    from ..nn.layer.rnn import RNN
    return RNN(cell, is_reverse=is_reverse, time_major=time_major)(
        inputs, initial_states)


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,  # noqa: A002
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    """Single GRU step (ref: gru_unit_op) via nn.GRUCell."""
    from ..nn import GRUCell
    in_dim = _val(input).shape[-1]
    cell = gru_unit._cells.setdefault(
        (in_dim, size // 3), GRUCell(in_dim, size // 3))
    h, new = cell(input, hidden)
    return new, None, h


gru_unit._cells = {}


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    from ..nn import LSTMCell
    in_dim = _val(x_t).shape[-1]
    hid = _val(hidden_t_prev).shape[-1]
    cell = lstm_unit._cells.setdefault((in_dim, hid), LSTMCell(in_dim, hid))
    h, (h2, c2) = cell(x_t, (hidden_t_prev, cell_t_prev))
    return h2, c2


lstm_unit._cells = {}


def dynamic_gru(input, size, param_attr=None, bias_attr=None,  # noqa: A002
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, origin_mode=False):
    """Dense rework of the LoD dynamic_gru (ref: dynamic_gru_op): input
    [B, T, 3*size] pre-projected gates -> outputs [B, T, size]."""
    from ..nn import GRU
    in_dim = _val(input).shape[-1]
    net = dynamic_gru._nets.setdefault(
        (in_dim, size, is_reverse),
        GRU(in_dim, size, direction="backward" if is_reverse else "forward"))
    out, _ = net(input)
    return out


dynamic_gru._nets = {}


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,  # noqa: A002
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """Dense rework of LoD dynamic_lstm: [B, T, 4*size//4...] -> (h, c)."""
    from ..nn import LSTM
    in_dim = _val(input).shape[-1]
    hid = size // 4
    net = dynamic_lstm._nets.setdefault(
        (in_dim, hid, is_reverse),
        LSTM(in_dim, hid, direction="backward" if is_reverse else "forward"))
    out, (h, c) = net(input)
    return out, out


dynamic_lstm._nets = {}


def dynamic_lstmp(input, size, proj_size, **kw):  # noqa: A002
    out, cell = dynamic_lstm(input, size, **{k: v for k, v in kw.items()
                                             if k in ("is_reverse",)})
    from ..nn import Linear
    proj = dynamic_lstmp._projs.setdefault(
        (_val(out).shape[-1], proj_size),
        Linear(_val(out).shape[-1], proj_size))
    return proj(out), cell


dynamic_lstmp._projs = {}


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,  # noqa: A002
         dropout_prob=0.0, is_bidirec=False, **kw):
    from ..nn import LSTM
    in_dim = _val(input).shape[-1]
    net = lstm._nets.setdefault(
        (in_dim, hidden_size, num_layers, is_bidirec),
        LSTM(in_dim, hidden_size, num_layers=num_layers,
             direction="bidirect" if is_bidirec else "forward"))
    out, (h, c) = net(input, (init_h, init_c) if init_h is not None
                      else None)
    return out, h, c


lstm._nets = {}


# ----------------------------------------------------- 1.x-only constructs
# (documented in SURVEY.md §2 #42: superseded block-style program builders)

def _superseded(name, replacement):
    def fn(*a, **kw):
        raise NotImplementedError(
            f"fluid.layers.{name} is a 1.x block-style program builder the "
            f"reference itself superseded; use {replacement} on this "
            f"backend (SURVEY.md §2 #42)")
    fn.__name__ = name
    return fn


py_reader = _superseded("py_reader", "paddle.io.DataLoader")
create_py_reader_by_data = _superseded("create_py_reader_by_data",
                                       "paddle.io.DataLoader")
double_buffer = _superseded("double_buffer",
                            "paddle.io.DataLoader (C++ prefetch built in)")
read_file = _superseded("read_file", "paddle.io.DataLoader")
load = _superseded("load", "paddle.static.load_inference_model")


def get_tensor_from_selected_rows(x, name=None):
    return x  # dense backend: rows are already a dense tensor


def merge_selected_rows(x, name=None):
    return x


def continuous_value_model(input, cvm, use_cvm=True):  # noqa: A002
    """CTR continuous-value feature op (ref: cvm_op): keeps or strips the
    2 leading show/click columns."""
    return input if use_cvm else _ops.slice(
        input, axes=[1], starts=[2], ends=[_val(input).shape[1]])


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0):
    """Tag-filtering (ref: filter_by_instag_op), dense semantics: keep rows
    whose tag is in filter_tag."""
    iv = np.asarray(_val(ins))
    tags = np.asarray(_val(ins_tag)).reshape(-1)
    keep = np.isin(tags, np.asarray(_val(filter_tag)).reshape(-1))
    idx = np.nonzero(keep)[0]
    if idx.size == 0:
        out = np.full((1,) + iv.shape[1:], out_val_if_empty, iv.dtype)
        return Tensor(out), Tensor(np.asarray([0], np.int64)), \
            Tensor(np.asarray([0], np.int64))
    return (Tensor(iv[idx]), Tensor(idx.astype(np.int64)),
            Tensor(np.asarray([idx.size], np.int64)))
