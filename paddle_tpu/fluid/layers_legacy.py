"""fluid.layers 1.x completion (ref: python/paddle/fluid/layers/*).

Everything here adapts a 1.x symbol onto the TPU-native implementations
that already power the 2.0 namespaces: sequence ops come from the dense
LoD rework (nn/functional/sequence.py), detection from
nn/functional/detection.py, decay functions return the corresponding
LRScheduler, RNN cells/decoders come from nn. A handful of 1.x
graph-construction constructs that the reference itself superseded
(py_reader pipelines, DynamicRNN/StaticRNN/IfElse/Switch/While block
builders) raise with migration guidance — recorded in SURVEY.md §2 #42.
"""
from __future__ import annotations

import numpy as np

from .. import ops as _ops
from ..core.tensor import Tensor
from ..ops._registry import apply_op


_py_range = range  # the 1.x `range` op below shadows the builtin


def _val(x):
    import jax.numpy as jnp
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# ---------------------------------------------------------------- arithmetic

def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _ops.maximum(x, y)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _ops.minimum(x, y)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _ops.mod(x, y)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _ops.pow(x, y)


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    return _ops.floor_divide(x, y)


def reduce_min(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _ops.min(input, axis=dim, keepdim=keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _ops.prod(input, axis=dim, keepdim=keep_dim)


def reduce_all(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _ops.all(input, axis=dim, keepdim=keep_dim)


def reduce_any(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _ops.any(input, axis=dim, keepdim=keep_dim)


def sums(input, out=None):  # noqa: A002
    r = input[0]
    for t in input[1:]:
        r = _ops.add(r, t)
    return r


def multiplex(inputs, index, name=None):
    """Row-wise select: out[i] = inputs[index[i]][i] (ref: multiplex_op)."""
    import jax.numpy as jnp

    def core(idx, *ts):
        stacked = jnp.stack(ts)  # [n, batch, ...]
        rows = jnp.arange(stacked.shape[1])
        return stacked[idx.reshape(-1), rows]

    args = [index if isinstance(index, Tensor) else Tensor(_val(index))]
    args += [t if isinstance(t, Tensor) else Tensor(_val(t))
             for t in inputs]
    return apply_op(core, "multiplex", tuple(args), {})


def cos_sim(X, Y):  # noqa: N803
    from ..nn.functional import cosine_similarity
    return cosine_similarity(X, Y, axis=-1)


def l2_normalize(x, axis=-1, epsilon=1e-12, name=None):
    from ..nn.functional import normalize
    return normalize(x, p=2, axis=axis, epsilon=epsilon)


def shape(input, name=None):  # noqa: A002
    return Tensor(np.asarray(_val(input).shape, np.int32))


def rank(input):  # noqa: A002
    return Tensor(np.asarray(_val(input).ndim, np.int32))


def size(input):  # noqa: A002
    return Tensor(np.asarray(int(np.prod(_val(input).shape)), np.int64))


def is_empty(x, name=None):
    return Tensor(np.asarray(int(np.prod(_val(x).shape)) == 0))


def has_inf(x):
    return _ops.any(_ops.isinf(x))


def has_nan(x):
    return _ops.any(_ops.isnan(x))


def reverse(x, axis):
    return _ops.flip(x, axis)


def range(start, end, step, dtype, name=None):  # noqa: A001
    return _ops.arange(start, end, step, dtype=dtype)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,  # noqa: A002
                   name=None):
    return _ops.uniform(shape, dtype=dtype, min=min, max=max, seed=seed)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    return _ops.add(_ops.multiply(_ops.randn(shape, dtype=dtype),
                                  Tensor(np.asarray(std, dtype))),
                    Tensor(np.asarray(mean, dtype)))


def _batch_size_like(ref, shape, input_dim_idx, output_dim_idx):
    shape = list(shape)
    shape[output_dim_idx] = _val(ref).shape[input_dim_idx]
    return shape


def fill_constant_batch_size_like(input, shape, dtype, value,  # noqa: A002
                                  input_dim_idx=0, output_dim_idx=0,
                                  force_cpu=False):
    return _ops.full(_batch_size_like(input, shape, input_dim_idx,
                                      output_dim_idx), value, dtype)


def uniform_random_batch_size_like(input, shape, dtype="float32",  # noqa: A002
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):  # noqa: A002
    return uniform_random(_batch_size_like(input, shape, input_dim_idx,
                                           output_dim_idx), dtype, min, max,
                          seed)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,  # noqa: A002
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    return gaussian_random(_batch_size_like(input, shape, input_dim_idx,
                                            output_dim_idx), mean, std, seed,
                           dtype)


def create_tensor(dtype, name=None, persistable=False):
    t = Tensor(np.zeros((0,), dtype))
    t.persistable = persistable
    return t


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):  # noqa: A002
    """Sample one category id per row from softmax-ed scores (ref:
    sampling_id_op)."""
    from ..core import rng as rng_mod
    import jax

    def core(xv, key=None):
        return jax.random.categorical(key, jax.nn.log_softmax(xv, -1),
                                      axis=-1)

    return apply_op(core, "sampling_id",
                    (x if isinstance(x, Tensor) else Tensor(_val(x)),),
                    {"key": rng_mod.next_key()}, nondiff=True)


# ------------------------------------------------------------- activations

def hard_shrink(x, threshold=0.5):
    return _ops.hardshrink(x, threshold)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _ops.hardsigmoid(x, slope, offset)


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    return _ops.hardswish(x)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    from ..optimizer import lr
    return lr.ExponentialDecay(learning_rate, gamma=decay_rate)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    from ..optimizer import lr
    return lr.NaturalExpDecay(learning_rate, gamma=decay_rate)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    from ..optimizer import lr
    return lr.InverseTimeDecay(learning_rate, gamma=decay_rate)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    from ..optimizer import lr
    return lr.PolynomialDecay(learning_rate, decay_steps, end_learning_rate,
                              power, cycle)


def piecewise_decay(boundaries, values):
    from ..optimizer import lr
    return lr.PiecewiseDecay(boundaries, values)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    from ..optimizer import lr
    return lr.CosineAnnealingDecay(learning_rate,
                                   T_max=step_each_epoch * epochs)


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    from ..optimizer import lr
    return lr.NoamDecay(d_model, warmup_steps, learning_rate)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    from ..optimizer import lr
    base = learning_rate if isinstance(learning_rate, float) \
        else getattr(learning_rate, "base_lr", end_lr)
    return lr.LinearWarmup(learning_rate, warmup_steps, start_lr, end_lr) \
        if hasattr(lr, "LinearWarmup") else lr.PolynomialDecay(
            base, warmup_steps, end_lr)


# ---------------------------------------------------------------- pooling

def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,  # noqa: A002
        data_format="NCHW"):
    from ..nn import functional as F
    return F.local_response_norm(input, n, alpha=alpha, beta=beta, k=k,
                                 data_format=data_format)


def grid_sampler(x, grid, name=None):
    return _ops.grid_sample(x, grid)


def crop_tensor(x, shape=None, offsets=None, name=None):
    xv = _val(x)
    offsets = offsets or [0] * xv.ndim
    slices = tuple(slice(o, o + s) for o, s in zip(offsets, shape))

    def core(xv):
        return xv[slices]

    return apply_op(core, "crop_tensor",
                    (x if isinstance(x, Tensor) else Tensor(xv),), {})


def resize_linear(input, out_shape=None, scale=None, **kw):  # noqa: A002
    from ..nn.functional.legacy import image_resize
    return image_resize(input, out_shape, scale, resample="LINEAR")


def kldiv_loss(x, target, reduction="mean", name=None):
    from ..nn import functional as F
    return F.kl_div(x, target, reduction=reduction)


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    return _ops.relu(_ops.add(
        _ops.multiply(_ops.scale(label, -1.0),
                      _ops.subtract(left, right)),
        Tensor(np.asarray(margin, np.float32))))


def rank_loss(label, left, right, name=None):
    """RankNet pairwise loss (ref: rank_loss_op)."""
    import jax.numpy as jnp

    def core(lv, l_, r_):
        o = l_ - r_
        return jnp.log1p(jnp.exp(o)) - lv * o

    return apply_op(core, "rank_loss",
                    tuple(t if isinstance(t, Tensor) else Tensor(_val(t))
                          for t in (label, left, right)), {})


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    from ..nn import functional as F
    return F.log_loss(input, label, epsilon)


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=
                                       True, use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """Sampled softmax CE (ref: sample_logits_op): uniform negatives +
    the true class, softmax over the reduced set."""
    import jax
    import jax.numpy as jnp

    from ..core import rng as rng_mod

    def core(lg, lb, key=None):
        bsz, n_cls = lg.shape
        lb = lb.reshape(-1)
        negs = jax.random.randint(key, (bsz, num_samples), 0, n_cls)
        idx = jnp.concatenate([lb[:, None], negs], -1)  # true first
        sel = jnp.take_along_axis(lg, idx, axis=1)
        if remove_accidental_hits:
            hit = (idx == lb[:, None]) & \
                (jnp.arange(idx.shape[1])[None] > 0)
            sel = jnp.where(hit, -1e20, sel)
        return -jax.nn.log_softmax(sel, -1)[:, 0:1]

    return apply_op(core, "sampled_softmax_ce",
                    (logits if isinstance(logits, Tensor)
                     else Tensor(_val(logits)),
                     label if isinstance(label, Tensor)
                     else Tensor(_val(label))),
                    {"key": rng_mod.next_key()})


def edit_distance(input, label, normalized=True, ignored_tokens=None,  # noqa: A002
                  input_length=None, label_length=None):
    """Levenshtein distance per pair (ref: edit_distance_op). Dense
    [B, T] int sequences; host-side DP via pure_callback (the reference
    computes on CPU too)."""
    import jax

    iv, lv = _val(input), _val(label)

    def _dist(a, b):
        la, lb = len(a), len(b)
        dp = np.arange(lb + 1, dtype=np.int64)
        for i in _py_range(1, la + 1):
            prev = dp.copy()
            dp[0] = i
            for j in _py_range(1, lb + 1):
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1,
                            prev[j - 1] + (a[i - 1] != b[j - 1]))
        return dp[lb]

    def host(iv, lv, il, ll):
        out = np.zeros((iv.shape[0], 1), np.float32)
        seq_num = np.asarray([iv.shape[0]], np.int64)
        for b in _py_range(iv.shape[0]):
            a = iv[b][: int(il[b])] if il is not None else iv[b]
            c = lv[b][: int(ll[b])] if ll is not None else lv[b]
            if ignored_tokens:
                a = [t for t in a if t not in ignored_tokens]
                c = [t for t in c if t not in ignored_tokens]
            d = _dist(list(a), list(c))
            out[b, 0] = d / max(len(c), 1) if normalized else d
        return out, seq_num

    il = _val(input_length) if input_length is not None else None
    ll = _val(label_length) if label_length is not None else None
    out, seq_num = host(np.asarray(iv), np.asarray(lv),
                        np.asarray(il) if il is not None else None,
                        np.asarray(ll) if ll is not None else None)
    return Tensor(out), Tensor(seq_num)


def mean_iou(input, label, num_classes):  # noqa: A002
    """Mean intersection-over-union over classes (ref: mean_iou_op)."""
    pv, lv = np.asarray(_val(input)), np.asarray(_val(label))
    ious, wrong, correct = [], [], []
    for c in np.arange(num_classes):
        pred_c = pv == c
        lbl_c = lv == c
        inter = np.logical_and(pred_c, lbl_c).sum()
        union = np.logical_or(pred_c, lbl_c).sum()
        if union > 0:
            ious.append(inter / union)
        correct.append(inter)
        wrong.append(np.logical_xor(pred_c, lbl_c).sum())
    miou = float(np.mean(ious)) if ious else 0.0
    return (Tensor(np.asarray(miou, np.float32)),
            Tensor(np.asarray(wrong, np.int64)),
            Tensor(np.asarray(correct, np.int64)))


# ------------------------------------------------------------- rnn family

def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    from ..nn.layer.rnn import RNN
    return RNN(cell, is_reverse=is_reverse, time_major=time_major)(
        inputs, initial_states)


def _superseded(name, replacement):
    def fn(*a, **kw):
        raise NotImplementedError(
            f"fluid.layers.{name} is a 1.x block-style program builder the "
            f"reference itself superseded; use {replacement} on this "
            f"backend (SURVEY.md §2 #42)")
    fn.__name__ = name
    return fn


py_reader = _superseded("py_reader", "paddle.io.DataLoader")
create_py_reader_by_data = _superseded("create_py_reader_by_data",
                                       "paddle.io.DataLoader")
double_buffer = _superseded("double_buffer",
                            "paddle.io.DataLoader (C++ prefetch built in)")
read_file = _superseded("read_file", "paddle.io.DataLoader")
load = _superseded("load", "paddle.static.load_inference_model")


def get_tensor_from_selected_rows(x, name=None):
    return x  # dense backend: rows are already a dense tensor


