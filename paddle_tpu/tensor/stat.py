"""paddle.tensor.stat module path (ref: tensor/stat.py)."""
from ..compat import numel  # noqa: F401
from ..ops import mean, median, std, var  # noqa: F401

__all__ = ["mean", "median", "numel", "std", "var"]
