"""paddle.tensor namespace — functional tensor API re-export
(ref: python/paddle/tensor/__init__.py)."""
from __future__ import annotations

from ..ops import *  # noqa: F401,F403
from ..core.tensor import Tensor, to_tensor  # noqa: F401

# legacy fluid-era names the reference's paddle.tensor also re-exports
from ..compat import (  # noqa: F401,E402
    ComplexVariable, LoDTensor, LoDTensorArray, VarBase, addcmul,
    broadcast_shape, crop_tensor, elementwise_add, elementwise_div,
    elementwise_floordiv, elementwise_max, elementwise_min, elementwise_mod,
    elementwise_mul, elementwise_pow, elementwise_sub,
    get_tensor_from_selected_rows, has_inf, has_nan, is_empty, multiplex,
    numel, rank, reduce_all, reduce_any, reduce_max, reduce_mean, reduce_min,
    reduce_prod, reduce_sum, set_printoptions, shape, tensordot,
)
from ..core.tensor import is_tensor  # noqa: F401,E402
from ..fluid.layers import fill_constant  # noqa: F401,E402
print_function = None  # __future__ artifact the reference re-exported

from ..compat import reverse  # noqa: E402,F401  (1.x flip alias at paddle.tensor)
