"""paddle.tensor.tensor module path (ref: tensor/tensor.py)."""
from ..core.tensor import Tensor  # noqa: F401

__all__ = ["Tensor"]
