"""paddle.tensor.to_string module path (ref: tensor/to_string.py)."""
from ..compat import set_printoptions  # noqa: F401

__all__ = ["set_printoptions"]
