"""paddle.tensor.attribute module path (ref: tensor/attribute.py)."""
from ..compat import rank, shape  # noqa: F401
from ..ops import imag, is_complex, is_floating_point, is_integer, real  # noqa: F401,E501

__all__ = ["rank", "shape", "real", "imag", "is_complex", "is_integer",
           "is_floating_point"]
