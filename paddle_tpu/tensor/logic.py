"""paddle.tensor.logic module path (ref: tensor/logic.py)."""
from ..compat import is_empty  # noqa: F401
from ..ops import (  # noqa: F401
    allclose, equal, equal_all, greater_equal, greater_than, isclose,
    less_equal, less_than, logical_and, logical_not, logical_or,
    logical_xor, not_equal,
)

__all__ = ["equal", "equal_all", "greater_equal", "greater_than",
           "is_empty", "less_equal", "less_than", "logical_and",
           "logical_not", "logical_or", "logical_xor", "not_equal",
           "allclose", "isclose"]
