"""Pipeline parallelism — GPipe schedule over the `pp` mesh axis.

Reference: python/paddle/distributed/fleet/meta_optimizers/pipeline_optimizer.py
(graph-partitioned pipeline with send/recv ops over NCCL). TPU-first rework:
SPMD collective-permute pipelining — every pp-rank holds ONE stage's params
(stacked layer params sharded on pp), and a lax.scan over M + S - 1 ticks
rotates activations to the next stage with ppermute. Backward flows through
the scan + ppermute transpose automatically, so jax.grad of the pipelined
loss trains the pipeline without hand-written send/recv grads. Bubble
fraction = (S-1)/(M+S-1), as in GPipe.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def pipeline_apply(stage_fn, stage_params, microbatches, axis_name="pp"):
    """Run homogeneous pipeline stages inside shard_map over `axis_name`.

    stage_fn: (params, x) -> y, the per-stage computation (same structure on
        every rank; each rank's shard of `stage_params` is ITS stage).
    stage_params: pytree whose leaves are this rank's stage params (already
        sharded: leading stacked dim split over pp outside, so in here each
        rank sees its own slice).
    microbatches: [M, mb, ...] — every rank sees the same microbatch stream
        (replicated over pp); only stage 0's compute on fresh input matters,
        later stages consume permuted activations.
    Returns [M, mb, ...] outputs of the LAST stage (valid on every rank —
        replicated by a final collect).
    """
    s = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    m = microbatches.shape[0]
    ticks = m + s - 1
    mb_shape = microbatches.shape[1:]

    fwd_perm = [(i, (i + 1) % s) for i in range(s)]

    def tick(carry, t):
        buf, outs = carry
        # stage 0 ingests microbatch t (if any); others use the rotated buffer
        mb_idx = jnp.clip(t, 0, m - 1)
        fresh = jax.lax.dynamic_index_in_dim(microbatches, mb_idx, 0,
                                             keepdims=False)
        x = jnp.where(idx == 0, fresh, buf)
        y = stage_fn(stage_params, x)
        # last stage's result for microbatch (t - (s-1)) is ready at tick t
        out_idx = t - (s - 1)
        is_valid = (out_idx >= 0)
        outs = jax.lax.cond(
            is_valid,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, y, jnp.clip(out_idx, 0, m - 1), 0),
            lambda o: o, outs)
        buf_next = jax.lax.ppermute(y, axis_name, fwd_perm)
        return (buf_next, outs), None

    buf0 = jnp.zeros(mb_shape, microbatches.dtype)
    buf0 = jax.lax.pvary(buf0, axis_name)
    outs0 = jnp.zeros((m,) + mb_shape, microbatches.dtype)
    outs0 = jax.lax.pvary(outs0, axis_name)
    mbs = jax.lax.pvary(microbatches, axis_name) \
        if not _is_varying(microbatches) else microbatches
    (_, outs), _ = jax.lax.scan(tick, (buf0, outs0), jnp.arange(ticks))
    # outs holds last-stage results only on the last rank; broadcast via
    # masked psum (a one-hot "bcast from rank s-1")
    outs_masked = jnp.where(idx == s - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(outs_masked, axis_name)


def _is_varying(x):
    return True  # inputs inside shard_map are treated varying; pvary is idempotent-safe


def make_pipeline_loss(stage_fn, loss_head, mesh, num_microbatches,
                       axis_name="pp"):
    """Build loss(params_stacked, batch) running the GPipe schedule under
    shard_map on `mesh`.

    stage_fn: (stage_params, x) -> y
    loss_head: (y_last, labels) -> scalar (computed replicated)
    params_stacked: pytree with leading dim = #stages on every leaf.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    def loss_fn(params_stacked, x, labels):
        def inner(params_local, x, labels):
            # params_local leaves: [1, ...] — this rank's stage
            params_stage = jax.tree_util.tree_map(lambda p: p[0], params_local)
            m = num_microbatches
            mbs = x.reshape((m, x.shape[0] // m) + x.shape[1:])
            outs = pipeline_apply(stage_fn, params_stage, mbs, axis_name)
            y = outs.reshape((x.shape[0],) + outs.shape[2:])
            ell = loss_head(y, labels)
            # identical on every pp rank; mean keeps it consistent
            return jax.lax.pmean(ell, axis_name)

        spec_p = jax.tree_util.tree_map(
            lambda p: P(axis_name), params_stacked)
        return shard_map(
            inner, mesh=mesh,
            in_specs=(spec_p, P(), P()),
            out_specs=P(),
            check_rep=False)(params_stacked, x, labels)

    return loss_fn
