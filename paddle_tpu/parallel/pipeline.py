"""Pipeline parallelism — GPipe and circular-interleaved schedules over
the `pp` mesh axis.

Reference: python/paddle/distributed/fleet/meta_optimizers/pipeline_optimizer.py
(graph-partitioned pipeline with send/recv ops over NCCL). TPU-first rework:
SPMD collective-permute pipelining — a lax.scan over ticks rotates
activations to the next stage with ppermute. Backward flows through the
scan + ppermute transpose automatically, so jax.grad of the pipelined
loss trains the pipeline without hand-written send/recv grads.

Two schedules, selectable via `strategy.pipeline_configs["schedule"]`:

* GPipe (`pipeline_apply`): every rank holds ONE stage. M + S - 1 ticks
  of one full stage-pass each; bubble fraction = (S-1)/(M+S-1).
* Circular interleaved (`pipeline_apply_interleaved`): every rank holds
  V non-adjacent layer chunks (global layer-group l*S + r sits in chunk
  slot l of rank r — the Megatron-interleaved placement). A tick is one
  CHUNK pass (1/V of a stage), and the static schedule
      tick(m, v) = (m//S)*V*S + (v//S)*S + (m%S) + (v%S)
  keeps the exact GPipe ring dataflow — each tick's ppermute output is
  consumed on the very next tick — while the fill/drain shrinks to
  chunk granularity: bubble fraction = (S-1)/(V*M+S-1). E.g. S=2, M=4:
  GPipe burns 20% by construction, interleaved V=2 burns 11% (the
  dryrun leg's tiny M=2 config: 33% -> 20%).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .mesh import axis_size as _axis_size
from .mesh import pvary as _pvary


def pipeline_apply(stage_fn, stage_params, microbatches, axis_name="pp"):
    """Run homogeneous pipeline stages inside shard_map over `axis_name`.

    stage_fn: (params, x) -> y, the per-stage computation (same structure on
        every rank; each rank's shard of `stage_params` is ITS stage).
    stage_params: pytree whose leaves are this rank's stage params (already
        sharded: leading stacked dim split over pp outside, so in here each
        rank sees its own slice).
    microbatches: [M, mb, ...] — every rank sees the same microbatch stream
        (replicated over pp); only stage 0's compute on fresh input matters,
        later stages consume permuted activations.
    Returns [M, mb, ...] outputs of the LAST stage (valid on every rank —
        replicated by a final collect).
    """
    s = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    m = microbatches.shape[0]
    ticks = m + s - 1
    mb_shape = microbatches.shape[1:]

    fwd_perm = [(i, (i + 1) % s) for i in range(s)]

    def tick(carry, t):
        buf, outs = carry
        # stage 0 ingests microbatch t (if any); others use the rotated buffer
        mb_idx = jnp.clip(t, 0, m - 1)
        fresh = jax.lax.dynamic_index_in_dim(microbatches, mb_idx, 0,
                                             keepdims=False)
        x = jnp.where(idx == 0, fresh, buf)
        y = stage_fn(stage_params, x)
        # last stage's result for microbatch (t - (s-1)) is ready at tick t
        out_idx = t - (s - 1)
        is_valid = (out_idx >= 0)
        outs = jax.lax.cond(
            is_valid,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, y, jnp.clip(out_idx, 0, m - 1), 0),
            lambda o: o, outs)
        buf_next = jax.lax.ppermute(y, axis_name, fwd_perm)
        return (buf_next, outs), None

    buf0 = jnp.zeros(mb_shape, microbatches.dtype)
    buf0 = _pvary(buf0, axis_name)
    outs0 = jnp.zeros((m,) + mb_shape, microbatches.dtype)
    outs0 = _pvary(outs0, axis_name)
    mbs = _pvary(microbatches, axis_name) \
        if not _is_varying(microbatches) else microbatches
    (_, outs), _ = jax.lax.scan(tick, (buf0, outs0), jnp.arange(ticks))
    # outs holds last-stage results only on the last rank; broadcast via
    # masked psum (a one-hot "bcast from rank s-1")
    outs_masked = jnp.where(idx == s - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(outs_masked, axis_name)


def _is_varying(x):
    return True  # inputs inside shard_map are treated varying; pvary is idempotent-safe


def pipeline_apply_interleaved(chunk_fn, chunk_params, microbatches,
                               axis_name="pp"):
    """Circular-interleaved schedule inside shard_map over `axis_name`.

    chunk_fn: (params, x) -> y, ONE chunk's computation (1/V of a stage).
    chunk_params: pytree whose leaves are [V, ...] — this rank's V chunk
        param sets; global layer-group order is chunk l of rank r ==
        group l*S + r (reshape a [V*S, ...] stack to [V, S, ...] and
        shard dim 1 on pp to get this placement).
    microbatches: [M, mb, ...] with M % S == 0, replicated over pp.
    Returns [M, mb, ...] outputs of the LAST group (replicated).

    Derivation of the schedule (see module docstring): microbatch m's
    group v runs on rank v%S at tick
        t = (m//S)*V*S + (v//S)*S + (m%S) + (v%S),
    so consecutive groups of one microbatch run on consecutive ranks at
    consecutive ticks (including the ring wrap S-1 -> 0 into the next
    chunk level), and each rank runs at most one chunk per tick. Inverse
    (what rank r does at tick t): u = t - r; m = (u//(V*S))*S + u%S;
    chunk slot l = (u % (V*S)) // S; idle iff u < 0 or m >= M.
    """
    s = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    m_total = microbatches.shape[0]
    if m_total % s:
        raise ValueError(
            f"interleaved schedule needs num_microbatches ({m_total}) "
            f"divisible by pp degree ({s})")
    v_chunks = jax.tree_util.tree_leaves(chunk_params)[0].shape[0]
    ticks = v_chunks * m_total + s - 1
    mb_shape = microbatches.shape[1:]

    fwd_perm = [(i, (i + 1) % s) for i in range(s)]

    def tick(carry, t):
        buf, outs = carry
        u = t - idx
        uc = jnp.maximum(u, 0)
        rem = uc % (v_chunks * s)
        chunk_l = rem // s
        mb_idx = (uc // (v_chunks * s)) * s + uc % s
        valid = (u >= 0) & (mb_idx < m_total)
        mb_c = jnp.clip(mb_idx, 0, m_total - 1)
        # group v == 0 (rank 0, chunk 0) ingests a fresh microbatch;
        # everything else consumes the ring buffer
        fresh = jax.lax.dynamic_index_in_dim(microbatches, mb_c, 0,
                                             keepdims=False)
        x = jnp.where((idx == 0) & (chunk_l == 0), fresh, buf)
        params_l = jax.tree_util.tree_map(
            lambda p: jax.lax.dynamic_index_in_dim(p, chunk_l, 0,
                                                   keepdims=False),
            chunk_params)
        y = chunk_fn(params_l, x)
        # the LAST group (rank S-1, chunk V-1) finishes microbatch mb_idx
        done = (idx == s - 1) & (chunk_l == v_chunks - 1) & valid
        outs = jax.lax.cond(
            done,
            lambda o: jax.lax.dynamic_update_index_in_dim(o, y, mb_c, 0),
            lambda o: o, outs)
        buf_next = jax.lax.ppermute(y, axis_name, fwd_perm)
        return (buf_next, outs), None

    buf0 = _pvary(jnp.zeros(mb_shape, microbatches.dtype), axis_name)
    outs0 = _pvary(jnp.zeros((m_total,) + mb_shape,
                                    microbatches.dtype), axis_name)
    (_, outs), _ = jax.lax.scan(tick, (buf0, outs0), jnp.arange(ticks))
    outs_masked = jnp.where(idx == s - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(outs_masked, axis_name)


def make_pipeline_loss(stage_fn, loss_head, mesh, num_microbatches,
                       axis_name="pp", schedule="gpipe", num_virtual=1):
    """Build loss(params_stacked, batch) running the selected pipeline
    schedule under shard_map on `mesh`.

    stage_fn: (stage_params, x) -> y — one stage (gpipe) / one chunk
        (interleaved); same callable works for both: it sees a param
        tree whose leading stacked dim is whatever its slice holds.
    loss_head: (y_last, labels) -> scalar (computed replicated)
    params_stacked: pytree with leading dim = #stages (gpipe) or
        #groups = num_virtual * pp_degree (interleaved; groups in layer
        order — the reshape below produces the interleaved placement).
    schedule: "gpipe" | "interleaved" (strategy.pipeline_configs).
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    if schedule not in ("gpipe", "interleaved"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    interleaved = schedule == "interleaved" and num_virtual > 1

    def loss_fn(params_stacked, x, labels):
        s_pp = mesh.shape[axis_name]

        def inner(params_local, x, labels):
            m = num_microbatches
            mbs = x.reshape((m, x.shape[0] // m) + x.shape[1:])
            if interleaved:
                # params_local leaves: [V, 1, ...] — this rank's V chunks
                chunk_tree = jax.tree_util.tree_map(
                    lambda p: p[:, 0], params_local)
                outs = pipeline_apply_interleaved(
                    stage_fn, chunk_tree, mbs, axis_name)
            else:
                # params_local leaves: [1, ...] — this rank's stage
                params_stage = jax.tree_util.tree_map(
                    lambda p: p[0], params_local)
                outs = pipeline_apply(stage_fn, params_stage, mbs,
                                      axis_name)
            y = outs.reshape((x.shape[0],) + outs.shape[2:])
            ell = loss_head(y, labels)
            # identical on every pp rank; mean keeps it consistent
            return jax.lax.pmean(ell, axis_name)

        if interleaved:
            # [V*S, ...] in layer order -> [V, S, ...]; sharding dim 1 on
            # pp gives rank r chunks {l*S + r} — the interleaved placement
            params_in = jax.tree_util.tree_map(
                lambda p: p.reshape((num_virtual, s_pp) + p.shape[1:]),
                params_stacked)
            spec_p = jax.tree_util.tree_map(
                lambda p: P(None, axis_name), params_in)
        else:
            params_in = params_stacked
            spec_p = jax.tree_util.tree_map(
                lambda p: P(axis_name), params_stacked)
        return shard_map(
            inner, mesh=mesh,
            in_specs=(spec_p, P(), P()),
            out_specs=P(),
            check_rep=False)(params_in, x, labels)

    return loss_fn


def bubble_fraction(schedule, num_stages, num_microbatches, num_virtual=1):
    """Analytic steady-state idle fraction of each schedule (docstring
    derivation): gpipe (S-1)/(M+S-1); interleaved (S-1)/(V*M+S-1)."""
    s, m, v = num_stages, num_microbatches, num_virtual
    if schedule == "gpipe":
        return (s - 1) / (m + s - 1)
    if schedule == "interleaved":
        return (s - 1) / (v * m + s - 1)
    raise ValueError(f"unknown pipeline schedule {schedule!r}")
