"""Ring attention — sequence/context parallelism over the `sp` mesh axis.

Long sequences are sharded along seq; K/V blocks rotate around the ring via
ppermute while each shard accumulates blockwise online-softmax partial
attention (Liu et al. ring attention; public pattern). Runs inside shard_map
over axis "sp". Causal masking is handled via global block offsets.

Two within-shard implementations compose with the ring (VERDICT r1 #9):

- "flash": the Pallas flash kernels from ops/pallas/flash_attention run on
  each ring block — forward merges per-block (o, lse) with a logsumexp
  rule; the ring-level custom_vjp backward re-rotates K/V and drives the
  streaming dq/dkv kernels per block with the GLOBAL lse/delta, with the
  dk/dv accumulators traveling around the ring so each shard's K/V grads
  arrive home after n steps. VMEM residency per step is a few 512-blocks.
- "chunked": pure-jnp online softmax over k-chunks (lax.scan) — the score
  tile is [S_local, chunk] instead of [S_local, S_local]; used for shapes
  the Pallas kernels don't take (unaligned / tiny test shapes).

`ring_attention` picks automatically; `ring_attention_sharded` is the
user-facing entry that does the shard_map itself.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .mesh import axis_size as _axis_size
from .mesh import pvary as _pvary

NEG_INF = -1e30
_CHUNK = 512

# block relation to the query shard (static switch cases)
_REL_FULL, _REL_DIAG, _REL_NONE = 0, 1, 2


def _flash_ok(q):
    b, h, s, d = q.shape
    return s >= 128 and s % 128 == 0 and d in (32, 64, 128, 256)


_LAST_IMPL = {"impl": None}


def last_impl_used():
    """Which within-shard implementation the most recent ring_attention
    trace selected ("flash" | "chunked") — lets callers/dryruns verify the
    Pallas-in-ring path is actually exercised (VERDICT r2 weak #5)."""
    return _LAST_IMPL["impl"]


# ---------------------------------------------------------------- chunked jnp

def _chunk_attn(q, k, v, scale, rel, q_off, k_off, axis_name=None):
    """Online-softmax attention of q against one ring K/V block, scanning
    k-chunks — returns unnormalized (o, m, l). Score tile is [Sq, chunk]."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    chunk = min(_CHUNK, sk)
    while sk % chunk:
        chunk -= 1
    nck = sk // chunk
    kc = k.reshape(b, h, nck, chunk, d)
    vc = v.reshape(b, h, nck, chunk, d)

    def body(carry, i):
        o_acc, m_acc, l_acc = carry
        kb = kc[:, :, i]
        vb = vc[:, :, i]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kb) * scale
        qi = q_off + jnp.arange(sq)
        ki = k_off + i * chunk + jnp.arange(chunk)
        causal_mask = qi[:, None] >= ki[None, :]
        s = jnp.where(rel == _REL_DIAG,
                      jnp.where(causal_mask[None, None], s, NEG_INF), s)
        s = jnp.where(rel == _REL_NONE, NEG_INF, s)
        m = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_acc, m)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_acc - m_new)
        o_acc = o_acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p, vb)
        l_acc = l_acc * alpha + jnp.sum(p, axis=-1, keepdims=True)
        return (o_acc, m_new, l_acc), None

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq, 1), jnp.float32)
    if axis_name is not None:  # inside shard_map: carry must be sp-varying
        o0, m0, l0 = (_pvary(t, axis_name) for t in (o0, m0, l0))
    (o, m, l), _ = jax.lax.scan(body, (o0, m0, l0), jnp.arange(nck))
    return o, m, l


def ring_attention(q, k, v, axis_name="sp", causal=False, scale=None,
                   impl=None, interpret=None):
    """Blockwise ring attention inside shard_map over `axis_name`.

    q, k, v: [B, H, S_local, D] — the local sequence shard.
    Returns [B, H, S_local, D].
    impl: "flash" (Pallas per-block kernels) | "chunked" (jnp online
    softmax over k-chunks) | None = auto (flash when shapes allow).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if impl is None:
        impl = "flash" if _flash_ok(q) else "chunked"
    _LAST_IMPL["impl"] = impl
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if impl == "flash":
        return _ring_flash(q, k, v, axis_name, causal, scale, interpret)
    return _ring_chunked(q, k, v, axis_name, causal, scale)


def _ring_chunked(q, k, v, axis_name, causal, scale):
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[2]
    q_off = idx * s_local
    qf = q.astype(jnp.float32)

    def body(carry, i):
        o_acc, m_acc, l_acc, k_cur, v_cur = carry
        src_idx = (idx - i) % n  # whose K/V block we currently hold
        k_off = src_idx * s_local
        if causal:
            rel = jnp.where(src_idx == idx, _REL_DIAG,
                            jnp.where(src_idx < idx, _REL_FULL, _REL_NONE))
        else:
            rel = jnp.asarray(_REL_FULL)
        o, m, l = _chunk_attn(qf, k_cur.astype(jnp.float32),
                              v_cur.astype(jnp.float32), scale, rel,
                              q_off, k_off, axis_name)
        m_new = jnp.maximum(m_acc, m)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m - m_new)
        o_acc = o_acc * alpha + o * beta
        l_acc = l_acc * alpha + l * beta
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o_acc, m_new, l_acc, k_nxt, v_nxt), None

    b, h, s, d = q.shape
    o0 = jnp.zeros((b, h, s, d), jnp.float32)
    m0 = jnp.full((b, h, s, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s, 1), jnp.float32)
    # constants start axis-unvarying under shard_map's type system; the carry
    # becomes sp-varying after the first step, so pre-mark them varying
    o0, m0, l0 = (_pvary(t, axis_name) for t in (o0, m0, l0))
    (o, m, l, _, _), _ = jax.lax.scan(body, (o0, m0, l0, k, v),
                                      jnp.arange(n))
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


# ----------------------------------------------------------- flash-in-ring

def _block_fwd(q, k, v, scale, rel, interpret):
    """Normalized (o, lse[B,H,S]) of q against one ring block, via the
    streaming Pallas forward. rel selects full/diag-causal/none masking."""
    from ..ops.pallas.flash_attention import LSE_LANES, _flash_fwd_lse
    b, h, s, d = q.shape

    def full(_):
        o, lse = _flash_fwd_lse(q, k, v, scale, False, 512, 512, interpret)
        return o.astype(jnp.float32), lse[:, :, 0].reshape(b, h, s)

    def diag(_):
        o, lse = _flash_fwd_lse(q, k, v, scale, True, 512, 512, interpret)
        return o.astype(jnp.float32), lse[:, :, 0].reshape(b, h, s)

    def none(_):
        return (jnp.zeros((b, h, s, d), jnp.float32),
                jnp.full((b, h, s), NEG_INF, jnp.float32))

    return jax.lax.switch(rel, (full, diag, none), None)


def _block_bwd(q, k, v, o, lse_lanes, g, scale, rel, interpret):
    """(dq, dk, dv) of one ring block via the streaming Pallas backward,
    driven by the GLOBAL lse (and delta from the final o)."""
    from ..ops.pallas.flash_attention import _flash_bwd

    def run(causal):
        return _flash_bwd(q, k, v, o, lse_lanes, g, scale, causal, 512, 512,
                          interpret)[:3]

    def full(_):
        return run(False)

    def diag(_):
        return run(True)

    def none(_):
        return (jnp.zeros_like(q), jnp.zeros_like(k), jnp.zeros_like(v))

    return jax.lax.switch(rel, (full, diag, none), None)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_flash(q, k, v, axis_name, causal, scale, interpret):
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale,
                                  interpret)
    return out


def _rel_for(src_idx, idx, causal):
    if causal:
        return jnp.where(src_idx == idx, _REL_DIAG,
                         jnp.where(src_idx < idx, _REL_FULL, _REL_NONE))
    return jnp.asarray(_REL_FULL)


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale, interpret):
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, s, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(carry, i):
        o_acc, lse_acc, k_cur, v_cur = carry
        src_idx = (idx - i) % n
        rel = _rel_for(src_idx, idx, causal)
        o_b, lse_b = _block_fwd(q, k_cur, v_cur, scale, rel, interpret)
        lse_new = jnp.logaddexp(lse_acc, lse_b)
        w_old = jnp.exp(lse_acc - lse_new)[..., None]
        w_new = jnp.exp(lse_b - lse_new)[..., None]
        o_acc = o_acc * w_old + o_b * w_new
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o_acc, lse_new, k_nxt, v_nxt), None

    o0 = _pvary(jnp.zeros((b, h, s, d), jnp.float32), axis_name)
    lse0 = _pvary(jnp.full((b, h, s), NEG_INF, jnp.float32),
                         axis_name)
    (o, lse, _, _), _ = jax.lax.scan(body, (o0, lse0, k, v), jnp.arange(n))
    return o.astype(q.dtype), lse


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, interpret):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale,
                                    interpret)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, interpret, res, g):
    from ..ops.pallas.flash_attention import LSE_LANES
    q, k, v, out, lse = res
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, s, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]
    # _flash_bwd consumes lse in its [b*h, s, LSE_LANES] layout
    lse_lanes = jnp.broadcast_to(lse.reshape(b * h, s, 1),
                                 (b * h, s, LSE_LANES))

    def body(carry, i):
        dq_acc, dk_trav, dv_trav, k_cur, v_cur = carry
        src_idx = (idx - i) % n
        rel = _rel_for(src_idx, idx, causal)
        dq_b, dk_b, dv_b = _block_bwd(q, k_cur, v_cur, out, lse_lanes, g,
                                      scale, rel, interpret)
        dq_acc = dq_acc + dq_b.astype(jnp.float32)
        dk_trav = dk_trav + dk_b.astype(jnp.float32)
        dv_trav = dv_trav + dv_b.astype(jnp.float32)
        # rotate K/V together with their traveling grad accumulators; after
        # n steps each block (and its accumulated grad) is home again
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_trav, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_trav, axis_name, perm)
        return (dq_acc, dk_nxt, dv_nxt, k_nxt, v_nxt), None

    z = _pvary(jnp.zeros((b, h, s, d), jnp.float32), axis_name)
    (dq, dk, dv, _, _), _ = jax.lax.scan(body, (z, z, z, k, v),
                                         jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention_sharded(q, k, v, mesh, causal=False, scale=None,
                           axis_name="sp", impl=None, interpret=None):
    """User-facing entry: global [B, H, S, D] arrays, sharded over `mesh`'s
    `axis_name` on the sequence dim; does the shard_map itself (replaces the
    round-1 NotImplementedError stub)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, axis_name, None)

    def inner(q, k, v):
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal,
                              scale=scale, impl=impl, interpret=interpret)

    return shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_rep=False)(q, k, v)


# ----------------------------------------------------- zigzag (balanced) ring

def zigzag_order(n, s):
    """Permutation putting the global sequence into zigzag layout: of 2n
    equal chunks, rank i owns chunks (i, 2n-1-i) — so under a causal mask
    every rank carries the same attention workload (plain contiguous
    sharding gives rank 0 one live block and rank n-1 all n). Returns
    indices `perm` with zigzag_seq = seq[perm]."""
    import numpy as np
    if s % (2 * n):
        raise ValueError(f"sequence {s} must divide into 2*{n} chunks")
    half = s // (2 * n)
    order = []
    for i in range(n):
        order.extend(range(i * half, (i + 1) * half))
        order.extend(range((2 * n - 1 - i) * half, (2 * n - i) * half))
    return np.asarray(order)


def zigzag_inverse(n, s):
    import numpy as np
    perm = zigzag_order(n, s)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(s)
    return inv


def _merge_partial(acc, part):
    """Merge two unnormalized online-softmax partials (o, m, l)."""
    o1, m1, l1 = acc
    o2, m2, l2 = part
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return o1 * a1 + o2 * a2, m, l1 * a1 + l2 * a2


def zigzag_ring_attention(q, k, v, axis_name="sp", scale=None):
    """Load-balanced CAUSAL ring attention (zigzag layout, public pattern
    from the llama3 training stack / ring-flash-attention). Inputs are the
    LOCAL shard in zigzag layout: rank i holds [chunk_i ; chunk_{2n-1-i}]
    of 2n global chunks (see zigzag_order).

    Why: with contiguous sharding, causal masking makes ring step work
    rank-dependent (rank 0: 1 live block, rank n-1: n) — SPMD lockstep
    bills every rank for the worst rank, so half the FLOPs are masked
    waste. In zigzag layout every rank computes exactly TWO half-blocks
    per ring step (one branch: whole-q × first-half-K; other branch:
    second-half-q × whole-K — equal FLOPs), halving causal step cost.

    Differentiable by construction (jnp + lax.scan + ppermute autodiff);
    the first (diagonal) step runs outside the scan so the scanned steps
    are the two balanced branches only.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    if s_local % 2:
        raise ValueError("zigzag needs an even local sequence")
    half = s_local // 2
    qf = q.astype(jnp.float32)
    q_lo, q_hi = qf[:, :, :half], qf[:, :, half:]
    # global position offsets of the two local chunks; the hi chunk's
    # offset is rank-dependent, so positions enter via q_off/k_off
    off_lo = idx * half
    off_hi = (2 * n - 1 - idx) * half

    def attn(qq, kk, vv, rel, q_off, k_off):
        return _chunk_attn(qq, kk.astype(jnp.float32),
                           vv.astype(jnp.float32), scale, rel, q_off,
                           k_off, axis_name)

    # ---- step 0: self block (src == idx): lo/diag, hi×lo/full, hi/diag
    lo_acc = attn(q_lo, k[:, :, :half], v[:, :, :half],
                  jnp.asarray(_REL_DIAG), off_lo, off_lo)
    hi_acc = attn(q_hi, k[:, :, :half], v[:, :, :half],
                  jnp.asarray(_REL_FULL), off_hi, off_lo)
    hi_acc = _merge_partial(hi_acc, attn(
        q_hi, k[:, :, half:], v[:, :, half:], jnp.asarray(_REL_DIAG),
        off_hi, off_hi))

    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(carry, i):
        lo_acc, hi_acc, k_cur, v_cur = carry
        src = (idx - i) % n
        k_lo, v_lo = k_cur[:, :, :half], v_cur[:, :, :half]

        def earlier(_):
            # src < idx: both local q chunks are causally AFTER src's lo
            # chunk, and BEFORE its hi chunk → whole-q × k_lo, full
            lo_p = attn(q_lo, k_lo, v_lo, jnp.asarray(_REL_FULL), 0, 0)
            hi_p = attn(q_hi, k_lo, v_lo, jnp.asarray(_REL_FULL), 0, 0)
            return lo_p, hi_p

        def later(_):
            # src > idx: only the hi chunk (global pos 2n-1-idx) is after
            # BOTH of src's chunks → q_hi × whole-K, full; lo no-op
            lo_p = tuple(_pvary(t, axis_name) for t in (
                jnp.zeros((b, h, half, d), jnp.float32),
                jnp.full((b, h, half, 1), NEG_INF, jnp.float32),
                jnp.zeros((b, h, half, 1), jnp.float32)))
            hi_p = attn(q_hi, k_cur, v_cur, jnp.asarray(_REL_FULL), 0, 0)
            return lo_p, hi_p

        lo_p, hi_p = jax.lax.cond(src < idx, earlier, later, None)
        lo_acc = _merge_partial(lo_acc, lo_p)
        hi_acc = _merge_partial(hi_acc, hi_p)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (lo_acc, hi_acc, k_nxt, v_nxt), None

    if n > 1:
        # rotate once up front: the scan visits src = idx-1, idx-2, ...
        k1 = jax.lax.ppermute(k, axis_name, perm)
        v1 = jax.lax.ppermute(v, axis_name, perm)
        (lo_acc, hi_acc, _, _), _ = jax.lax.scan(
            body, (lo_acc, hi_acc, k1, v1), jnp.arange(1, n))
    o_lo, _, l_lo = lo_acc
    o_hi, _, l_hi = hi_acc
    out = jnp.concatenate([o_lo / jnp.maximum(l_lo, 1e-30),
                           o_hi / jnp.maximum(l_hi, 1e-30)], axis=2)
    return out.astype(q.dtype)


def zigzag_ring_attention_sharded(q, k, v, mesh, scale=None,
                                  axis_name="sp"):
    """Global-array front door: permutes [B, H, S, D] into zigzag layout,
    runs the balanced ring under shard_map, and un-permutes. Production
    training keeps activations in zigzag layout end-to-end (the
    permutation commutes with every position-independent layer) and pays
    neither gather."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis_name]
    s = q.shape[2]
    perm = jnp.asarray(zigzag_order(n, s))
    inv = jnp.asarray(zigzag_inverse(n, s))
    qz, kz, vz = (t[:, :, perm] for t in (q, k, v))
    spec = P(None, None, axis_name, None)

    def inner(q, k, v):
        return zigzag_ring_attention(q, k, v, axis_name=axis_name,
                                     scale=scale)

    out = shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec, check_rep=False)(qz, kz, vz)
    return out[:, :, inv]
