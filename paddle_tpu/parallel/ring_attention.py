"""Ring attention — sequence/context parallelism over the `sp` mesh axis.

Long sequences are sharded along seq; K/V blocks rotate around the ring via
ppermute while each shard accumulates blockwise online-softmax partial
attention (Liu et al. ring attention; public pattern). Runs inside shard_map
over axis "sp". Causal masking is handled via global block offsets.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _block_attn(q, k, v, bias, scale, causal, q_off, k_off):
    # q: [B, H, Sq, D], k/v: [B, H, Sk, D]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qi = q_off + jnp.arange(q.shape[2])
        ki = k_off + jnp.arange(k.shape[2])
        mask = qi[:, None] >= ki[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o, m, l


def ring_attention(q, k, v, axis_name="sp", causal=False, scale=None):
    """Blockwise ring attention inside shard_map over `axis_name`.

    q, k, v: [B, H, S_local, D] — the local sequence shard.
    Returns [B, H, S_local, D].
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[2]
    if scale is None:
        scale = q.shape[-1] ** -0.5

    q_off = idx * s_local

    def body(carry, i):
        o_acc, m_acc, l_acc, k_cur, v_cur = carry
        src_idx = (idx - i) % n  # whose K/V block we currently hold
        k_off = src_idx * s_local
        o, m, l = _block_attn(q, k_cur, v_cur, None, scale, causal, q_off, k_off)
        # online softmax merge
        m_new = jnp.maximum(m_acc, m)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m - m_new)
        o_acc = o_acc * alpha + o * beta
        l_acc = l_acc * alpha + l * beta
        # rotate K/V around the ring (skip after last step)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o_acc, m_new, l_acc, k_nxt, v_nxt), None

    b, h, s, d = q.shape
    o0 = jnp.zeros((b, h, s, d), q.dtype)
    m0 = jnp.full((b, h, s, 1), -1e30, q.dtype)
    l0 = jnp.zeros((b, h, s, 1), q.dtype)
    # constants start axis-unvarying under shard_map's type system; the carry
    # becomes sp-varying after the first step, so pre-mark them varying
    o0, m0, l0 = (jax.lax.pvary(t, axis_name) for t in (o0, m0, l0))
    (o, m, l, _, _), _ = jax.lax.scan(body, (o0, m0, l0, k, v),
                                      jnp.arange(n))
    return o / jnp.maximum(l, 1e-30)


def ring_attention_sharded(mesh, q, v_spec=None):
    raise NotImplementedError("use ring_attention inside shard_map")
