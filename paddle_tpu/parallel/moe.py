"""Mixture-of-Experts with expert parallelism over the `ep` mesh axis.

Reference lineage: Paddle's distributed MoE work (incubate/distributed/models/
moe in later reference versions) — rebuilt TPU-first: top-k gating, capacity-
bounded dispatch as one einsum pair, experts sharded over `ep` so each device
holds E/ep experts; under jit/GSPMD the dispatch einsums lower to all-to-all
over ICI. Everything is static-shaped (capacity factor) — XLA-friendly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def top2_gating(logits, capacity, key=None, second_policy="all"):
    """Switch/GShard-style top-2 gating with static capacity.

    logits: [T, E]. Returns (combine [T, E, C], dispatch bool [T, E, C], aux).
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)

    g1_idx = jnp.argmax(probs, axis=-1)
    g1_prob = jnp.max(probs, axis=-1)
    probs_wo1 = probs * (1 - jax.nn.one_hot(g1_idx, e, dtype=probs.dtype))
    g2_idx = jnp.argmax(probs_wo1, axis=-1)
    g2_prob = jnp.max(probs_wo1, axis=-1)

    # load-balancing auxiliary loss (GShard eq.)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(g1_idx, e, dtype=probs.dtype), axis=0)
    aux = jnp.sum(me * ce) * e

    def positions(idx):
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)
        pos = jnp.cumsum(onehot, axis=0) * onehot - 1  # position within expert
        return onehot, pos.max(axis=-1)

    oh1, pos1 = positions(g1_idx)
    # second choice positions come after all first choices
    count1 = jnp.sum(oh1, axis=0)
    oh2 = jax.nn.one_hot(g2_idx, e, dtype=jnp.int32)
    pos2 = (jnp.cumsum(oh2, axis=0) * oh2 - 1).max(axis=-1) + \
        jnp.take(count1, g2_idx)

    keep1 = pos1 < capacity
    keep2 = pos2 < capacity

    denom = jnp.maximum(g1_prob + g2_prob, 1e-9)
    w1 = jnp.where(keep1, g1_prob / denom, 0.0)
    w2 = jnp.where(keep2, g2_prob / denom, 0.0)

    def scatter(idx, pos, w, keep):
        # [T, E, C]
        e_oh = jax.nn.one_hot(idx, e, dtype=logits.dtype)
        c_oh = jax.nn.one_hot(jnp.where(keep, pos, 0), capacity,
                              dtype=logits.dtype)
        return w[:, None, None] * e_oh[:, :, None] * c_oh[:, None, :]

    combine = scatter(g1_idx, pos1, w1, keep1) + scatter(g2_idx, pos2, w2,
                                                         keep2)
    dispatch = combine > 0
    return combine, dispatch, aux


def moe_layer_apply(params, x, capacity_factor=1.25):
    """Pure MoE FFN apply.

    params: {"gate": [D, E], "w1": [E, D, H], "b1": [E, H],
             "w2": [E, H, D], "b2": [E, D]}
    x: [T, D] tokens. Returns ([T, D], aux_loss).
    Under jit with w1/w2 sharded P("ep", ...) the dispatch einsum becomes an
    all-to-all over ep.
    """
    t, d = x.shape
    e = params["gate"].shape[1]
    capacity = max(1, int(capacity_factor * t / e))
    logits = x @ params["gate"]
    combine, dispatch, aux = top2_gating(logits, capacity)
    # dispatch tokens: [E, C, D]
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", expert_in, params["w1"])
                    + params["b1"][:, None, :])
    expert_out = jnp.einsum("ech,ehd->ecd", h, params["w2"]) \
        + params["b2"][:, None, :]
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out, aux


def init_moe_params(key, d_model, d_hidden, num_experts, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    s1 = (2.0 / d_model) ** 0.5
    s2 = (2.0 / d_hidden) ** 0.5
    return {
        "gate": jax.random.normal(k1, (d_model, num_experts), dtype) * s1,
        "w1": jax.random.normal(k2, (num_experts, d_model, d_hidden), dtype) * s1,
        "b1": jnp.zeros((num_experts, d_hidden), dtype),
        "w2": jax.random.normal(k3, (num_experts, d_hidden, d_model), dtype) * s2,
        "b2": jnp.zeros((num_experts, d_model), dtype),
    }


def moe_shardings(mesh, params, ep_axis="ep"):
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = {"gate": P(), "w1": P(ep_axis), "b1": P(ep_axis),
            "w2": P(ep_axis), "b2": P(ep_axis)}
    return {k: NamedSharding(mesh, spec[k]) for k in params}
