"""paddle_tpu.parallel — mesh construction + sharded train steps.

TPU-native heart of distributed execution: build a Mesh over (dp, mp, pp, sp),
annotate parameter/activation shardings, and pjit whole train steps so XLA
emits ICI collectives (replacing the reference's NCCL ops + Fleet graph
rewrites). See mesh.py, api.py, ring_attention.py, pipeline.py.
"""
from __future__ import annotations

from .mesh import (  # noqa: F401
    current_mesh, get_mesh, make_mesh, mesh_guard, MeshConfig,
)
from .api import (  # noqa: F401
    data_parallel_shardings, replicate, shard_batch, shard_params_tp,
    sharded_train_step,
)
from .ring_attention import (  # noqa: F401
    ring_attention, zigzag_ring_attention, zigzag_ring_attention_sharded)
from .ulysses import sp_attention, ulysses_attention  # noqa: F401
