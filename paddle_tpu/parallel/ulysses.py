"""Ulysses-style all-to-all sequence parallelism.

The second long-context mode next to ring attention (lineage: DeepSpeed
Ulysses, public pattern; reference capability: sequence-parallel training
of long sequences). Where the ring rotates K/V blocks around `sp` and
keeps heads whole, Ulysses swaps the sharding axis itself with one ICI
all-to-all: seq-sharded activations [B, H, S/n, D] become head-sharded
[B, H/n, S, D], each rank runs an ordinary FULL-sequence attention over
its own heads (the Pallas flash kernel — no cross-rank softmax state at
all), and a second all-to-all restores seq sharding.

Trade-off vs ring (why both exist): Ulysses moves q,k,v,o once each
(4 tensors × 1 all-to-all) regardless of sequence length, while the ring
moves k,v n-1 times — Ulysses wins when S_local is large and H ≥ n;
the ring wins when heads are few (H < n) or memory for a full-S score
pass is tight. `sp_attention` picks by that rule.

Used inside shard_map over the `sp` mesh axis, composes with dp/pp/mp
exactly like ring_attention (drop-in: same [B, H, S_local, D] contract).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .mesh import axis_size as _axis_size


def _local_attention(q, k, v, causal, scale, interpret):
    from .ring_attention import _flash_ok
    if _flash_ok(q):
        from ..ops.pallas.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               interpret=interpret)
    s = q.shape[2]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask, logits, -1e30)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name="sp", causal=False, scale=None,
                      interpret=None):
    """q, k, v: [B, H, S_local, D] seq-sharded over `axis_name`.
    Returns [B, H, S_local, D]. Requires H % axis_size == 0."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    n = _axis_size(axis_name)
    h = q.shape[1]
    if h % n:
        raise ValueError(
            f"ulysses needs num_heads ({h}) divisible by sp ({n}); "
            f"use ring attention for head counts below the sp degree")

    def seq_to_head(x):  # [B, H, S/n, D] -> [B, H/n, S, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    def head_to_seq(x):  # [B, H/n, S, D] -> [B, H, S/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    qh, kh, vh = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    oh = _local_attention(qh, kh, vh, causal, scale, interpret)
    return head_to_seq(oh)


def sp_attention(q, k, v, axis_name="sp", causal=False, scale=None,
                 impl=None, interpret=None):
    """Sequence-parallel attention front door: impl = "ring" | "ulysses" |
    "zigzag" | None (auto: ulysses when every rank can own ≥1 head — one
    all-to-all round beats n-1 ppermute rounds — else ring).

    "zigzag" is the load-balanced causal ring: the caller must hold the
    LOCAL shard in zigzag layout (rank i = global chunks i and 2n-1-i;
    see ring_attention.zigzag_order) — it halves causal ring step cost
    and is never auto-picked because of that layout contract."""
    from .ring_attention import ring_attention, zigzag_ring_attention
    if impl == "zigzag":
        if not causal:
            raise ValueError("zigzag layout only pays off under a causal "
                             "mask; use ring/ulysses for bidirectional")
        return zigzag_ring_attention(q, k, v, axis_name=axis_name,
                                     scale=scale)
    if impl is None:
        n = _axis_size(axis_name)
        impl = "ulysses" if q.shape[1] % n == 0 else "ring"
    if impl == "ulysses":
        return ulysses_attention(q, k, v, axis_name, causal, scale,
                                 interpret)
    return ring_attention(q, k, v, axis_name=axis_name, causal=causal,
                          scale=scale, interpret=interpret)
