"""Device mesh management.

The canonical axes: dp (data), mp (tensor/model), pp (pipeline), sp
(sequence/context). Mirrors paddle.distributed.fleet's hybrid-parallel degrees
(DistributedStrategy.hybrid_configs) onto a jax.sharding.Mesh — sharding-book
style: pick a mesh, annotate, let XLA insert collectives.
"""
from __future__ import annotations

import contextlib
from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

_current_mesh = None


def axis_size(axis_name):
    """Static size of the named mesh axis inside a shard_map/jit trace.

    Newer jax spells this `jax.lax.axis_size`; the pinned toolchain
    (0.4.x) only has `jax.core.axis_frame(name)`, which returns the int
    directly. Every collective in parallel/ and distributed/ goes
    through this shim so a jax upgrade can't re-break the whole
    distributed test family at once."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.core.axis_frame(axis_name)


def pvary(x, axis_name):
    """Mark `x` as device-varying over `axis_name` (the newer-jax
    varying-axes type system). The pinned 0.4.x toolchain has no
    `jax.lax.pvary` and no varying-axes tracking to satisfy — there the
    annotation is a semantic no-op and the shim returns `x` unchanged."""
    fn = getattr(jax.lax, "pvary", None)
    if fn is not None:
        return fn(x, axis_name)
    return x


@dataclass
class MeshConfig:
    dp: int = 1
    mp: int = 1
    pp: int = 1
    sp: int = 1

    @property
    def total(self):
        return self.dp * self.mp * self.pp * self.sp


def make_mesh(dp=None, mp=1, pp=1, sp=1, devices=None):
    """Build a Mesh with axes (dp, mp, pp, sp); dp=None absorbs the rest."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if dp is None:
        dp = n // (mp * pp * sp)
    assert dp * mp * pp * sp == n, \
        f"mesh {dp}x{mp}x{pp}x{sp} != {n} devices"
    arr = np.array(devices).reshape(dp, pp, mp, sp)
    return Mesh(arr, ("dp", "pp", "mp", "sp"))


def get_mesh(dp=None, mp=1, pp=1, sp=1):
    global _current_mesh
    if _current_mesh is None:
        _current_mesh = make_mesh(dp, mp, pp, sp)
    return _current_mesh


def current_mesh():
    return _current_mesh


def set_mesh(mesh):
    global _current_mesh
    _current_mesh = mesh
    return mesh


@contextlib.contextmanager
def mesh_guard(mesh):
    global _current_mesh
    old = _current_mesh
    _current_mesh = mesh
    try:
        yield mesh
    finally:
        _current_mesh = old
