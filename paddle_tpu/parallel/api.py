"""Sharding rules + sharded train steps.

The scaling-book recipe: NamedSharding annotations on params/batch, jit with
in/out shardings, XLA inserts the collectives (grad all-reduce for dp,
activation collectives for mp) over ICI.
"""
from __future__ import annotations

import re

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def replicate(mesh):
    return NamedSharding(mesh, P())


def shard_batch(mesh, x, axis=0):
    spec = [None] * x.ndim
    spec[axis] = "dp"
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))


def data_parallel_shardings(mesh, params_tree, batch_tree):
    """Pure-dp: params replicated, batch split on dp."""
    p_sh = jax.tree_util.tree_map(lambda _: replicate(mesh), params_tree)
    b_sh = jax.tree_util.tree_map(
        lambda x: NamedSharding(mesh, P("dp", *([None] * (x.ndim - 1)))),
        batch_tree)
    return p_sh, b_sh


# Megatron-style tensor-parallel rules for transformer params, keyed by
# parameter-name regex → PartitionSpec factory (rank-dependent).
_TP_RULES = [
    (re.compile(r"(q_proj|k_proj|v_proj|qkv|fc1|gate|up_proj|w1|w3)"
                r".*weight$"), lambda nd: P(*([None] * (nd - 1) + ["mp"]))),
    (re.compile(r"(q_proj|k_proj|v_proj|qkv|fc1|gate|up_proj|w1|w3)"
                r".*bias$"), lambda nd: P("mp")),
    (re.compile(r"(out_proj|fc2|down_proj|w2|proj)"
                r".*weight$"), lambda nd: P(*(["mp"] + [None] * (nd - 1)))),
    (re.compile(r"(embedding|embed_tokens|word_emb).*weight$"),
     lambda nd: P("mp", *([None] * (nd - 1)))),
    (re.compile(r"lm_head.*weight$"), lambda nd: P(*([None] * (nd - 1) + ["mp"]))),
]


def tp_spec_for(name, ndim):
    for rx, fac in _TP_RULES:
        if rx.search(name):
            return fac(ndim)
    return P()


def shard_params_tp(mesh, named_params):
    """named_params: dict name -> jax array. Returns dict name -> NamedSharding
    following Megatron column/row rules; everything else replicated."""
    return {name: NamedSharding(mesh, tp_spec_for(name, v.ndim))
            for name, v in named_params.items()}


def sharded_train_step(step_fn, mesh, params_sharding, batch_sharding,
                       donate_params=True):
    """jit a (params, opt_state, batch, key) -> (loss, params, opt_state)
    train step with explicit shardings. XLA inserts all collectives."""
    opt_sharding = None  # inferred: follows params by propagation
    jitted = jax.jit(
        step_fn,
        in_shardings=(params_sharding, None, batch_sharding, None),
        out_shardings=(None, params_sharding, None),
        donate_argnums=(0, 1) if donate_params else ())
    return jitted
