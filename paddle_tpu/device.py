"""paddle.device module path (ref: python/paddle/device.py) — binds the
device-management API that also lives on the paddle root."""
from .core.place import (  # noqa: F401
    CPUPlace, TPUPlace, get_device, is_compiled_with_cuda,
    is_compiled_with_tpu, is_compiled_with_xpu, set_device,
)


def get_cudnn_version():
    """No cuDNN on this stack (ref parity: None when absent)."""
    return None


def is_compiled_with_cinn():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_rocm():
    return False


__all__ = ["get_cudnn_version", "set_device", "get_device",
           "is_compiled_with_xpu", "is_compiled_with_cinn",
           "is_compiled_with_npu"]
