"""paddle.tensor namespace — functional tensor API re-export
(ref: python/paddle/tensor/__init__.py)."""
from __future__ import annotations

from .ops import *  # noqa: F401,F403
from .core.tensor import Tensor, to_tensor  # noqa: F401
