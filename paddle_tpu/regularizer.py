"""Regularizers (ref: python/paddle/fluid/regularizer.py)."""
from __future__ import annotations


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self.coeff = coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self.coeff})"


class L2Decay(WeightDecayRegularizer):
    pass


class L1Decay(WeightDecayRegularizer):
    pass


L2DecayRegularizer = L2Decay
L1DecayRegularizer = L1Decay
