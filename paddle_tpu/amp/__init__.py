"""Automatic mixed precision.

Reference: python/paddle/amp/ (auto_cast, GradScaler) + fluid/contrib/
mixed_precision/. TPU-first: the native mixed-precision dtype is bfloat16 —
same exponent range as fp32, so loss scaling is a no-op (GradScaler keeps the
reference API but scales by 1 on TPU unless fp16 is forced). `auto_cast`
switches a process-global compute policy that the matmul/conv ops consult.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..core.tensor import Tensor

_amp_state = {"enable": False, "dtype": "bfloat16", "level": "O1",
              "custom_white_list": None, "custom_black_list": None}

# O1 default lists (ref: fluid/contrib/mixed_precision/fp16_lists.py)
WHITE_LIST = {"matmul", "mm", "bmm", "conv1d", "conv2d", "conv3d", "linear",
              "einsum", "addmm"}
BLACK_LIST = {"exp", "log", "mean", "sum", "softmax", "log_softmax",
              "cross_entropy", "softmax_with_cross_entropy", "layer_norm",
              "batch_norm", "norm", "cumsum", "logsumexp"}


def amp_enabled():
    return _amp_state["enable"]


def amp_dtype():
    return _amp_state["dtype"]


def amp_should_cast(opname):
    if not _amp_state["enable"]:
        return False
    white = WHITE_LIST | set(_amp_state["custom_white_list"] or ())
    black = BLACK_LIST | set(_amp_state["custom_black_list"] or ())
    if _amp_state["level"] == "O2":
        return opname not in black
    return opname in white and opname not in black


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    prev = dict(_amp_state)
    _amp_state.update(enable=enable, dtype=dtype, level=level,
                      custom_white_list=custom_white_list,
                      custom_black_list=custom_black_list)
    try:
        yield
    finally:
        _amp_state.clear()
        _amp_state.update(prev)


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2 decoration: cast model params to the AMP dtype (ref: amp.decorate)."""
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dtype)
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """Loss scaling (ref: python/paddle/amp/grad_scaler.py). With bfloat16 on
    TPU the dynamic range matches fp32, so scale stays 1.0 and this is a
    transparent pass-through that still tracks the reference API/semantics."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable and amp_dtype() == "float16"
        self._scale = init_loss_scaling if self._enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list or []:
            if p is not None and p.grad is not None:
                g = p.grad._value * inv
                found = found or bool(jnp.any(~jnp.isfinite(g)))
                p.grad = Tensor(g)
        self._found_inf = found

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def update(self):
        if not self._enable or not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_scale_ratio(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state["good_steps"]
        self._bad_steps = state["bad_steps"]
