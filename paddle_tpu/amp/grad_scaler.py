"""paddle.amp.grad_scaler module path (ref: amp/grad_scaler.py)."""
from . import GradScaler  # noqa: F401

__all__ = ["GradScaler"]
