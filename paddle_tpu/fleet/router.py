"""`FleetRouter` — replicated serving engines behind a failover front
door (fleet round, ROADMAP item 4).

The tier above one engine: N `PagedGenerationServer` replicas (each
with its own pool, journal and ops plane) behind an async router that
makes replica failure a recoverable, TESTED path instead of a
session-losing one. Four layers:

  * REPLICA STATE MACHINE (`fleet.health`): active liveness/readiness
    probes (the r18 split-/healthz satellite) plus passive dispatch
    outcomes drive ok -> degraded -> circuit-open per replica, with
    capped-backoff half-open probing; routing weight follows state,
    and at most the one implicated replica degrades per failure.
  * FAILOVER WITHOUT TOKEN DIVERGENCE: every accepted request is
    journaled AT THE ROUTER — prompt, RESOLVED seed, sampling,
    budget, then every delivered token (`SessionJournal` semantics,
    reused verbatim). When a replica dies mid-stream, its unfinished
    sessions re-admit on survivors via
    `PagedGenerationServer.admit_journal_entry` — the engine resumes
    at PRNG step len(gen0), so the completed output is
    TOKEN-IDENTICAL to a run that was never interrupted (the r12
    preempt/resume parity property, now across engines) and the
    stream keeps delivering from the next undelivered token.
  * PLANNED MIGRATION (`migrate_session`): the source engine swap-outs
    and publishes the live session (`export_session`), its K/V blocks
    cross the wire as bytes (`fleet.migration`, int8 codes + scales
    ride along) and re-publish on the target
    (`import_kv_payload`), so the re-admission warm-attaches with
    ZERO prefill recompute; a dead source degrades to journal replay
    automatically.
  * FLEET FRONT DOOR: prefix-aware placement (route to the replica
    whose content-addressed cache holds the longest prefix —
    `PagedKVCache.match_prefix_len`, the r9 signal — least-loaded
    tiebreak), per-request retry across replicas with
    `AdmissionShed.retry_after_s` propagation, global shed when every
    replica is saturated, and /metrics federation over the
    per-replica r15 exporters with a `replica` label
    (`fleet.federation`).

Chaos: `fault_plan=` installs a deterministic plan whose
`replica_kill` seam the router polls once per placement — when it
fires, the chosen replica is hard-killed (`kill()`, no futures
resolved) and its sessions fail over; r17 engine seams point at
individual replicas through their own plans. docs/FLEET.md.
"""
from __future__ import annotations

import dataclasses
import itertools
import os
import threading
import time

import numpy as np

from ..observability import log as _obs_log
from ..observability import metrics as _metrics
from ..observability import tracing as _tracing
from ..observability.slo import SLOEngine
from ..observability.trace_context import TraceContext
from ..reliability import (AdmissionShed, QuarantinedRequest,
                           ReplicaUnavailable, RequestTimeout,
                           SessionJournal, resolve_fault_plan)
from ..sampling import SamplingParams
from .federation import federate_metrics
from .migration import deserialize_kv_payload, serialize_kv_payload
from .replica import Replica

_logger = _obs_log.get_logger(__name__)

_m_requests = _metrics.counter(
    "fleet_requests_total",
    "requests the router placed, by replica (initial placement only; "
    "failover re-placements count in fleet_failover_sessions_total)",
    labelnames=("replica",))
_m_prefix_routed = _metrics.counter(
    "fleet_prefix_routed_total",
    "placements that followed the prefix-cache signal (the chosen "
    "replica already held >= 1 cached token of the prompt)")
_m_failovers = _metrics.counter(
    "fleet_failovers_total",
    "replica-level failover events: a replica died (or was killed) "
    "and its unfinished sessions were re-admitted on survivors")
_m_failover_sessions = _metrics.counter(
    "fleet_failover_sessions_total",
    "sessions re-admitted on a survivor via router-journal replay "
    "(token-identical resume at PRNG step len(gen0))")
_m_migrations = _metrics.counter(
    "fleet_migrations_total",
    "planned session migrations (export_session -> wire -> "
    "import + warm re-admission; journal replay when the source was "
    "already gone)")
_m_kills = _metrics.counter(
    "fleet_replica_kills_total",
    "replicas hard-killed by the router's replica_kill fault seam "
    "(chaos testing — opt-in via fault_plan=)")
_m_sheds = _metrics.counter(
    "fleet_sheds_total",
    "submissions refused because every routable replica was saturated "
    "(global admission shed, retry_after_s propagated)")
_m_retries = _metrics.counter(
    "fleet_submit_retries_total",
    "submissions retried on another replica after the first choice "
    "refused (engine shed / stopped)")
_m_probes = _metrics.counter(
    "fleet_probes_total",
    "active replica probes by outcome (ok | not_ready | dead)",
    labelnames=("replica", "outcome"))
_m_state = _metrics.gauge(
    "fleet_replica_state",
    "replica state machine position (0 ok, 1 degraded, 2 open/"
    "half_open, 3 not_ready, 4 dead)", labelnames=("replica",))
_m_replicas = _metrics.gauge(
    "fleet_replicas",
    "current routing-set size (dynamic membership, ISSUE 20)")
_m_added = _metrics.counter(
    "fleet_replicas_added_total",
    "replicas admitted at runtime via add_replica (warm-gated "
    "scale-up actuation)")
_m_removed = _metrics.counter(
    "fleet_replicas_removed_total",
    "replicas retired at runtime via remove_replica (post-drain "
    "scale-down actuation)")

_STATE_CODE = {"ok": 0.0, "degraded": 1.0, "open": 2.0,
               "half_open": 2.0, "not_ready": 3.0, "dead": 4.0}

#: the `stats()["autoscale"]` shape with no autoscaler attached —
#: zeroed-when-disabled, same keys `Autoscaler.stats_block` fills
AUTOSCALE_ZERO = {
    "enabled": False, "ticks": 0, "decisions": 0, "scale_ups": 0,
    "scale_downs": 0, "rebalances": 0, "holds": 0, "errors": 0,
    "migrations": 0, "replica_seconds": 0.0, "last_decision": None,
}

_rids = itertools.count()


class _Session:
    """Router-side record of one accepted request. Attribute names
    mirror the engine `_Req` fields `SessionJournal.entry_for` reads,
    so the same serialization serves journaling, failover and
    migration."""

    __slots__ = ("rid", "ids", "budget", "seed", "sampling", "meta",
                 "timeout_s", "future", "on_token", "toks", "done",
                 "stop_reason", "replica", "epoch", "failovers",
                 "t_submit", "t_first", "trace")

    def __init__(self, rid, ids, budget, seed, sampling, meta,
                 timeout_s, on_token, trace=None):
        self.rid = rid
        self.ids = ids
        self.budget = budget
        self.seed = seed
        self.sampling = sampling
        self.meta = meta
        self.timeout_s = timeout_s
        from concurrent.futures import Future

        self.future = Future()       # the client-facing future
        self.on_token = on_token
        self.toks: list[int] = []    # tokens delivered so far
        self.done = False
        self.stop_reason = None
        self.replica = None
        self.epoch = 0               # bumped on failover/migration:
        self.failovers = 0           # stale replica callbacks no-op
        self.t_submit = time.perf_counter()
        self.t_first = None
        # causal tracing (ISSUE 14): minted HERE — the router's
        # context wins over any replica-minted one, so every hop of
        # the session shares one trace_id; bumped with cause
        # "failover"/"migration" as the session moves
        self.trace = trace if trace is not None else TraceContext.mint()

    @property
    def gen0(self):
        return tuple(self.toks)

    def _tr(self, replica=None):
        return self.trace.attrs(replica=replica) \
            if self.trace is not None else {}


class FleetRouter:
    """Failover router over N serving-engine replicas.

    replicas: iterable of `fleet.Replica` (or bare not-yet-started
        `PagedGenerationServer`s, wrapped as replica0..N-1). Build the
        engines with `enable_prefix_cache=True` to get prefix-aware
        placement AND zero-recompute migration; journal-per-replica is
        optional (the ROUTER journal is what failover replays).
    journal: router-level `SessionJournal` (path or instance) — the
        failover source of truth. None disables failover persistence
        (sessions on a dead replica are then re-admitted from the
        router's in-memory mirror, which is the same data — the
        journal adds router-crash recovery via
        `recover_from_journal`).
    seed: fleet seed for auto-derived per-request PRNG seeds (resolved
        AT THE ROUTER so a replayed session samples identically on
        any replica).
    probe_interval_s: active probe cadence (the probe thread also
        notices externally-died replicas and fails their sessions
        over).
    shed_queue_depth: PER-REPLICA queue depth past which — on EVERY
        routable replica — a submit raises `AdmissionShed` with a
        retry hint (global shed). None = never.
    submit_retries: extra replicas to try when the chosen one refuses
        a submit (its own shed, stopping, ...).
    fault_plan: deterministic chaos plan; the router polls its
        `replica_kill` seam once per placement decision. Give the
        router its OWN plan (occurrence counters are plan state).
    detokenize: tokenizer for streamed text deltas (stream=True).
    expose_port: fleet ops endpoint — /metrics serves the FEDERATED
        per-replica page (replica label), /statusz the fleet view,
        /healthz ok|degraded|stalled (stalled = nothing routable),
        /slo the fleet burn-rate report when `slos=` is given.
    slos: iterable of `observability.SLO` (or True for
        `default_slos()`) — the FLEET-level burn-rate engine (ISSUE
        14), fed from router-observed TTFT and session outcomes
        (tagged per lane/tenant/replica). Evaluated every probe pass;
        a replica-scoped SLO in sustained `page`
        (>= slo_degrade_sustain_s of continuous page burn) degrades
        that replica to not_ready via the r18 state machine — the
        "stop routing new work at a latency-burning replica" hook.
    slo_degrade_sustain_s: how long a replica-scoped SLO must page
        continuously before the degrade hook fires.
    capacity_timeout_s: /capacity federation deadline per replica —
        a hung remote replica degrades to an `{"error": ...}` slot
        instead of stalling the snapshot (None = synchronous).
    """

    def __init__(self, replicas, *, journal=None, seed=0,
                 probe_interval_s=1.0, shed_queue_depth=None,
                 submit_retries=2, fault_plan=None, detokenize=None,
                 stream_buffer=256, expose_port=None, slos=None,
                 slo_degrade_sustain_s=2.0, capacity_timeout_s=2.0):
        reps = []
        for i, r in enumerate(replicas):
            if isinstance(r, Replica):
                reps.append(r)
            else:
                reps.append(Replica(f"replica{i}", r))
        if not reps:
            raise ValueError("FleetRouter needs >= 1 replica")
        names = [r.name for r in reps]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self.replicas = reps
        if isinstance(journal, (str, os.PathLike)):
            journal = SessionJournal(journal)
        elif journal is not None and not isinstance(journal,
                                                    SessionJournal):
            raise TypeError(f"journal must be a SessionJournal or a "
                            f"path, got {type(journal).__name__}")
        self._journal = journal
        self._seed0 = int(seed) & 0xFFFFFFFF
        self._auto_seeds = itertools.count()
        self.probe_interval_s = float(probe_interval_s)
        if shed_queue_depth is not None and int(shed_queue_depth) < 1:
            raise ValueError(f"shed_queue_depth must be >= 1, "
                             f"got {shed_queue_depth}")
        self._shed_depth = (None if shed_queue_depth is None
                            else int(shed_queue_depth))
        self.submit_retries = max(0, int(submit_retries))
        self._faults = resolve_fault_plan(fault_plan)
        self._detok = detokenize
        self._stream_buffer = int(stream_buffer)
        # SLO burn-rate engine (ISSUE 14): None = every feed site is
        # one `is None` branch (the telemetry discipline)
        if slos is None or slos is False:
            self._slo = None
        elif isinstance(slos, SLOEngine):
            self._slo = slos
        else:
            self._slo = SLOEngine(slos)
        self.slo_degrade_sustain_s = float(slo_degrade_sustain_s)
        # /capacity federation deadline: a HUNG replica (wedged
        # subprocess) degrades to an error slot instead of stalling
        # the snapshot; None = synchronous (never for remote fleets)
        self.capacity_timeout_s = (
            None if capacity_timeout_s is None
            else float(capacity_timeout_s))
        self._slo_degraded: dict[str, float] = {}  # replica -> since
        self._lock = threading.RLock()
        self._sessions: dict[str, _Session] = {}
        self._stop = False
        self._started = False
        self._probe_thread = None
        self._probe_wake = threading.Event()
        # window counters (reset_stats-coherent)
        self._t0 = None
        self._ttft: list[float] = []
        self._tokens_out = 0
        self._requests_done = 0
        self._failovers = 0
        self._failover_sessions = 0
        self._migrations = 0
        self._replica_kills = 0
        self._sheds = 0
        self._retries = 0
        self._prefix_routed = 0
        self._placements = 0
        # dynamic membership (ISSUE 20): auto-name counter for bare
        # engines admitted at runtime, window counters, and the
        # autoscaler hook (fleet.autoscale.Autoscaler attaches itself
        # so stats()["autoscale"] is live; None = zeroed block)
        self._rep_ids = itertools.count(len(reps))
        self._replicas_added = 0
        self._replicas_removed = 0
        self._autoscaler = None
        self.exporter = None
        self._expose_port = expose_port

    # ---- lifecycle -----------------------------------------------------
    def start(self):
        if self._started:
            return self
        if self._stop:
            raise RuntimeError("router stopped; build a new one")
        self._t0 = time.perf_counter()
        for rep in self.replicas:
            rep.start()
        _m_replicas.set(float(len(self.replicas)))
        self._probe_thread = threading.Thread(
            target=self._probe_loop, daemon=True,
            name="paddle-tpu-fleet-probe")
        self._probe_thread.start()
        self._started = True
        if self._expose_port is not None:
            from ..observability.exporter import OpsEndpoint

            _metrics.REGISTRY.enable()
            self.exporter = OpsEndpoint(
                statusz_fn=self.statusz, healthz_fn=self.health,
                metrics_fn=self.metrics_text,
                slo_fn=(self.slo_report if self._slo is not None
                        else None),
                capacity_fn=self.capacity).start(
                    port=self._expose_port)
        return self

    def stop(self):
        self._stop = True
        self._probe_wake.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=10)
            self._probe_thread = None
        for rep in self.replicas:
            rep.stop()
        with self._lock:
            for sess in self._sessions.values():
                if not sess.done:
                    sess.done = True
                    sess.future.set_exception(
                        RuntimeError("router stopped"))
        if self.exporter is not None:
            self.exporter.stop()
        if self._journal is not None:
            self._journal.flush()

    # ---- placement -----------------------------------------------------
    def _routable(self, now):
        return [r for r in self.replicas
                if not r.dead and r.health.routing_weight(now) > 0.0]

    def _place(self, ids, exclude=(), now=None):
        """Prefix-aware placement: the routable replica holding the
        longest cached prefix of `ids` wins; least-loaded, then
        first-listed, breaks ties. Returns (replica, match_len) or
        (None, 0)."""
        now = time.monotonic() if now is None else now
        best = None
        best_key = None
        best_match = 0
        for idx, rep in enumerate(self.replicas):
            if rep in exclude or rep.dead:
                continue
            if rep.health.routing_weight(now) <= 0.0:
                continue
            match = rep.prefix_match_len(ids)
            key = (match, -rep.load(), -idx)
            if best_key is None or key > best_key:
                best, best_key, best_match = rep, key, match
        return best, best_match

    def _poll_kill_seam(self):
        """The router-level chaos seam: one poll per placement
        decision; a scheduled fault hard-kills the replica just
        chosen and fails its sessions over — the forced mid-stream
        replica death the chaos gate and the bench axis exercise."""
        if self._faults is None:
            return False
        return self._faults.poll("replica_kill") is not None

    def _kill_replica(self, rep, why="injected replica_kill"):
        with self._lock:
            self._replica_kills += 1
        _m_kills.inc()
        _tracing.event("replica_kill", replica=rep.name, why=why)
        _logger.warning("killing replica %s (%s)", rep.name, why)
        rep.kill()
        self._failover_replica(rep, why=why)

    # ---- client API ----------------------------------------------------
    def submit(self, ids, max_new_tokens=None, sampling=None, *,
               meta=None, on_token=None, timeout_s=None,
               stream=False, stream_timeout_s=None, trace_ctx=None):
        """Route one prompt onto the fleet. Returns the session's
        Future (resolving to the full [prompt + generated] int32
        array regardless of how many replicas it crossed), or a
        `frontend.StreamHandle` when stream=True.

        The per-request PRNG seed is RESOLVED HERE (explicit
        `sampling.seed` wins, else derived from the fleet seed) and
        journaled with the accept, so a failover replay on any
        survivor samples token-identically. `AdmissionShed` is raised
        with a retry hint when every routable replica is saturated
        (global shed) or every tried replica shed locally."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        if ids.size == 0:
            raise ValueError("empty prompt")
        if sampling is not None and not isinstance(sampling,
                                                   SamplingParams):
            raise TypeError(f"sampling must be a SamplingParams, "
                            f"got {type(sampling).__name__}")
        # resolve the seed at the ROUTER: replicas must never
        # auto-derive (their counters differ — a replay would
        # diverge); greedy requests get one too (harmless, and the
        # journal entry is then self-contained either way)
        if sampling is not None and sampling.seed is not None:
            seed = int(sampling.seed)
        else:
            seed = (self._seed0 + 0x9E3779B9
                    * (1 + next(self._auto_seeds))) & 0xFFFFFFFF
            if sampling is not None:
                sampling = dataclasses.replace(sampling, seed=seed)
        budget = max_new_tokens
        if budget is None and sampling is not None:
            budget = sampling.max_new_tokens
        if budget is None:
            budget = self.replicas[0].server.max_new
        sess = _Session(f"f{next(_rids)}", ids, int(budget), seed,
                        sampling, meta, timeout_s, on_token,
                        trace=trace_ctx)
        handle = None
        if stream:
            from ..frontend.stream import StreamHandle

            stops = sampling.stop_strings if sampling is not None else ()
            handle = StreamHandle(
                detokenize=self._detok, stop_strings=stops,
                tail_tokens=16, max_buffered=self._stream_buffer,
                timeout_s=stream_timeout_s)
            user_cb = on_token
            if user_cb is None:
                sess.on_token = handle._on_token
            else:
                def chained(tok, reason, _h=handle._on_token,
                            _u=user_cb):
                    _h(tok, reason)
                    _u(tok, reason)
                sess.on_token = chained
            handle._bind(sess.future)
        with self._lock:
            if self._stop:
                raise RuntimeError("router stopped")
            self._shed_check_locked()
            self._sessions[sess.rid] = sess
        if self._journal is not None:
            # journal the accept BEFORE the replica sees it: a crash
            # (router or replica) between here and the first token
            # still recovers the session
            self._journal.record_accept(sess)
        try:
            self._dispatch(sess, first=True)
        except BaseException:
            with self._lock:
                sess.done = True
                self._sessions.pop(sess.rid, None)
            if self._journal is not None:
                self._journal.record_done(sess.rid, "rejected")
            raise
        return handle if stream else sess.future

    def _shed_check_locked(self):
        if self._shed_depth is None:
            return
        now = time.monotonic()
        routable = self._routable(now)
        if not routable:
            return  # nothing routable is a placement error, not shed
        depths = [r.queue_depth() for r in routable]
        if min(depths) >= self._shed_depth:
            self._sheds += 1
            _m_sheds.inc()
            slots = sum(r.server.max_slots for r in routable)
            waves = -(-min(depths) // max(1, slots))
            hint = max(0.05, 0.25 * waves)
            raise AdmissionShed(min(depths), self._shed_depth, hint)

    def _dispatch(self, sess, first=False):
        """Place `sess` (fresh or resume state) on a replica, retrying
        across candidates; raises on a fresh submit, fails the session
        future on a re-placement."""
        route_ids = (np.concatenate(
            [sess.ids, np.asarray(sess.toks, np.int32)])
            if sess.toks else sess.ids)
        tried = set()
        sheds = []
        last_exc = None
        for _attempt in range(self.submit_retries + 1):
            rep, match = self._place(route_ids, exclude=tried)
            if rep is None:
                break
            if self._poll_kill_seam():
                self._kill_replica(rep)
                tried.add(rep)
                rep, match = self._place(route_ids, exclude=tried)
                if rep is None:
                    break
            with self._lock:
                self._placements += 1
                if match > 0:
                    self._prefix_routed += 1
            if match > 0:
                _m_prefix_routed.inc()
            epoch = sess.epoch
            cb = self._make_token_cb(sess, epoch)
            try:
                if first and not sess.toks:
                    fut = rep.server.submit(
                        sess.ids, max_new_tokens=sess.budget,
                        sampling=sess.sampling, meta=sess.meta,
                        on_token=cb, timeout_s=sess.timeout_s,
                        rid=sess.rid, trace_ctx=sess.trace)
                else:
                    fut = rep.server.admit_journal_entry(
                        SessionJournal.entry_for(sess), on_token=cb)
            except AdmissionShed as e:
                sheds.append(e)
                tried.add(rep)
                last_exc = e
                with self._lock:
                    self._retries += 1
                _m_retries.inc()
                continue
            except Exception as e:  # noqa: BLE001 — replica refused
                rep.health.note_failure(time.monotonic(),
                                        f"submit: {type(e).__name__}")
                tried.add(rep)
                last_exc = e
                with self._lock:
                    self._retries += 1
                _m_retries.inc()
                continue
            with self._lock:
                sess.replica = rep
            if first:
                _m_requests.labels(replica=rep.name).inc()
            fut.add_done_callback(
                lambda f, s=sess, r=rep, g=epoch:
                self._on_replica_done(s, r, g, f))
            _tracing.event("fleet_place", request_id=sess.rid,
                           prefix_match=int(match),
                           resume=bool(sess.toks),
                           **sess._tr(replica=rep.name))
            return
        if sheds:
            # every candidate shed: propagate the largest retry hint
            err = max(sheds, key=lambda e: e.retry_after_s)
        else:
            err = ReplicaUnavailable(
                sess.rid,
                f"tried {len(tried)} replica(s); last error: "
                f"{last_exc!r}" if tried else "no routable replica")
        if first:
            raise err
        # a re-placement (failover) runs inside engine callbacks:
        # never raise — fail the session's client-facing future. The
        # JOURNAL entry deliberately stays live: a healed fleet's
        # recover_from_journal still completes it token-identically
        # (the ReplicaUnavailable contract).
        with self._lock:
            sess.done = True
        sess.future.set_exception(err)

    # ---- token + completion plumbing -----------------------------------
    def _make_token_cb(self, sess, epoch):
        def cb(tok, reason):
            first = False
            with self._lock:
                if sess.done or epoch != sess.epoch:
                    return  # stale replica still flushing: ignore
                sess.toks.append(int(tok))
                if sess.t_first is None:
                    sess.t_first = time.perf_counter()
                    self._ttft.append(sess.t_first - sess.t_submit)
                    first = True
                self._tokens_out += 1
                if reason is not None:
                    sess.stop_reason = reason
            if first and self._slo is not None:
                # router-observed TTFT: spans queueing, placement,
                # any failover requeue gap — the client's number
                self._slo_observe_latency(
                    "ttft", sess.t_first - sess.t_submit, sess)
            if self._journal is not None:
                self._journal.record_token(sess.rid, tok)
                if reason is not None:
                    self._journal.record_done(sess.rid, reason)
            fwd = sess.on_token
            if fwd is not None:
                fwd(tok, reason)
        return cb

    def _on_replica_done(self, sess, rep, epoch, fut):
        exc = fut.exception()
        with self._lock:
            if sess.done or epoch != sess.epoch:
                return
            if exc is None or isinstance(
                    exc, (QuarantinedRequest, RequestTimeout)):
                sess.done = True
                self._requests_done += 1
        now = time.monotonic()
        if exc is None:
            rep.health.note_ok(now)
            self._slo_observe_avail(sess, True, rep)
            if self._journal is not None and sess.stop_reason is None:
                # terminal token never streamed (e.g. an immediate
                # journal-terminal resolution): close the entry
                self._journal.record_done(sess.rid, "done")
            sess.future.set_result(fut.result())
            return
        if isinstance(exc, (QuarantinedRequest, RequestTimeout)):
            # the request's OWN failure — by design it costs exactly
            # itself, never a failover
            reason = ("quarantined"
                      if isinstance(exc, QuarantinedRequest)
                      else "timeout")
            if self._journal is not None:
                self._journal.record_done(sess.rid, reason)
            self._slo_observe_avail(sess, False, rep)
            sess.future.set_exception(exc)
            return
        if self._stop:
            with self._lock:
                sess.done = True
            sess.future.set_exception(exc)
            return
        # the replica gave up on the session (engine death, stop, an
        # unrecovered dispatch error): passive health signal + re-admit
        # on a survivor from the journaled state
        rep.health.note_failure(now, f"{type(exc).__name__}: {exc}")
        _logger.warning("replica %s failed session %s (%s); failing "
                        "over", rep.name, sess.rid, exc)
        self._failover_session(sess, exclude={rep})

    # ---- SLO burn-rate engine (ISSUE 14) -------------------------------
    def _slo_observe_latency(self, kind, value_s, sess):
        """Feed one router-observed latency (caller checked _slo)."""
        meta = sess.meta
        rep = sess.replica
        self._slo.observe(
            kind, value_s=value_s,
            lane=meta.lane if meta is not None else None,
            tenant=meta.tenant if meta is not None else None,
            replica=rep.name if rep is not None else None)

    def _slo_observe_avail(self, sess, ok, rep=None):
        """Feed one session outcome (finished vs terminally failed)."""
        if self._slo is None:
            return
        if rep is None:
            rep = sess.replica
        meta = sess.meta
        self._slo.observe(
            "availability", good=ok,
            lane=meta.lane if meta is not None else None,
            tenant=meta.tenant if meta is not None else None,
            replica=rep.name if rep is not None else None)

    def _slo_degrade_check(self, now):
        """The degrade hook: a replica-scoped SLO in SUSTAINED page
        burn (>= slo_degrade_sustain_s continuous) marks its replica
        not_ready in the r18 state machine — residents keep decoding,
        new placements go elsewhere until the burn clears."""
        if self._slo is None:
            return
        paging = self._slo.paging(now, self.slo_degrade_sustain_s)
        for rep in self.replicas:
            hit = sorted(n for n in paging
                         for s in self._slo.slos
                         if s.name == n and s.replica == rep.name)
            if not hit:
                self._slo_degraded.pop(rep.name, None)
                continue
            if rep.dead:
                continue
            if rep.name not in self._slo_degraded:
                self._slo_degraded[rep.name] = now
                _tracing.event("slo_degrade", replica=rep.name,
                               slos=hit)
                _logger.warning(
                    "replica %s degraded to not_ready: sustained SLO "
                    "page burn (%s)", rep.name, ", ".join(hit))
            rep.health.note_not_ready(
                now, f"slo page burn: {', '.join(hit)}")
            _m_state.labels(replica=rep.name).set(
                _STATE_CODE["not_ready"])

    def capacity(self):
        """The fleet /capacity endpoint payload (ISSUE 17): every
        replica's versioned pressure snapshot federated under its
        name, dead replicas contributing `{"error": ...}` instead of
        failing the page — the fleet-level ROADMAP-3 Autoscaler
        input."""
        from ..observability.capacity import federate_capacity

        return federate_capacity(
            {rep.name: rep.capacity for rep in self.replicas},
            timeout_s=self.capacity_timeout_s)

    def slo_report(self):
        """The fleet /slo endpoint payload."""
        if self._slo is None:
            return {"slos": [], "worst": "ok", "paging": []}
        report = self._slo.report()
        report["degraded_replicas"] = sorted(self._slo_degraded)
        return report

    # ---- timeline export (ISSUE 14) ------------------------------------
    def export_timeline(self, path):
        """Write the FLEET Chrome/Perfetto timeline: the shared span
        sink laid out per replica (events are stamped with `replica`
        by the engines) plus every replica's flight-recorder ring on
        its own track, and the router's own events on a `router`
        process. Open in chrome://tracing or ui.perfetto.dev. Returns
        the event count."""
        from ..observability import timeline as _timeline

        recorders = {}
        for rep in self.replicas:
            try:
                recorders[rep.name] = rep.server._recorder.events()
            except Exception:  # noqa: BLE001 — a dead replica's ring
                continue      # is best-effort
        return _timeline.write_chrome_trace(
            path, recorders=recorders, default_name="router")

    # ---- failover ------------------------------------------------------
    def _failover_session(self, sess, exclude=frozenset()):
        with self._lock:
            if sess.done:
                return
            sess.epoch += 1
            sess.failovers += 1
            self._failover_sessions += 1
            if sess.trace is not None:
                # causal tracing: the re-admission on a survivor is a
                # new hop of the same trace, cause "failover"
                sess.trace = sess.trace.child("failover")
        _m_failover_sessions.inc()
        _tracing.event("fleet_failover_session", request_id=sess.rid,
                       tokens_done=len(sess.toks), **sess._tr())
        self._dispatch(sess, first=False)

    def _failover_replica(self, rep, why=""):
        """Re-admit every unfinished session resident on `rep` onto
        survivors, in accept order. Idempotent: sessions already moved
        (or finished) are skipped."""
        with self._lock:
            victims = [s for s in self._sessions.values()
                       if s.replica is rep and not s.done]
            if victims:
                self._failovers += 1
        if not victims:
            return
        _m_failovers.inc()
        _logger.warning("failing over %d session(s) from replica %s "
                        "(%s)", len(victims), rep.name, why)
        for sess in victims:
            self._failover_session(sess, exclude={rep})

    # ---- planned migration ---------------------------------------------
    def migrate_session(self, rid, target=None):
        """Move one LIVE session to another replica with zero prefill
        recompute: the source preempt-publishes and exports its K/V
        chain, the payload crosses the wire as bytes, the target
        imports and warm-attaches, and the stream keeps delivering
        from the next token. Falls back to plain journal replay when
        the source is already dead or the target pool cannot hold the
        chain. Returns the target replica's name. Raises KeyError for
        an unknown/finished rid and ReplicaUnavailable when there is
        nowhere to move to."""
        with self._lock:
            sess = self._sessions.get(rid)
            if sess is None or sess.done:
                raise KeyError(f"unknown or finished session {rid!r}")
            source = sess.replica
        if isinstance(target, str):
            by_name = {r.name: r for r in self.replicas}
            if target not in by_name:
                raise KeyError(f"unknown replica {target!r}")
            target = by_name[target]
        if source is None or source.dead:
            # source already gone: the fallback IS the failover path
            with self._lock:
                self._migrations += 1
            _m_migrations.inc()
            self._failover_session(
                sess, exclude={source} if source else frozenset())
            with self._lock:
                moved = sess.replica
            if moved is None:
                raise ReplicaUnavailable(rid, "migration fallback "
                                              "found no survivor")
            return moved.name
        ent, payload = source.server.export_session(rid)
        with self._lock:
            sess.epoch += 1          # stale source callbacks no-op
            epoch = sess.epoch
            if sess.trace is not None:
                # causal tracing: the warm re-admission on the target
                # is a new hop, cause "migration" (the engine's
                # migrate_out event on the source closes the old hop)
                sess.trace = sess.trace.child("migration")
                ent["trace"] = sess.trace.to_dict()
        wire = serialize_kv_payload(payload)
        payload = deserialize_kv_payload(wire)  # the wire round-trip
        if target is None or target is source:
            resume = (np.asarray(ent["ids"] + ent["gen0"], np.int32)
                      if ent["gen0"] else np.asarray(ent["ids"],
                                                     np.int32))
            target, _ = self._place(resume, exclude={source})
        if target is None:
            target = source if not source.dead else None
        if target is None:
            with self._lock:
                sess.done = True
            err = ReplicaUnavailable(rid, "no migration target")
            sess.future.set_exception(err)
            raise err
        imported = 0
        if payload is not None:
            try:
                tenant = (ent.get("meta") or {}).get("tenant",
                                                     "default")
                imported = target.server.import_kv_payload(
                    payload, owner=(tenant, rid))
            except Exception as e:  # noqa: BLE001 — pool pressure on
                # the target: journal replay still completes the
                # session, just without the zero-recompute warm attach
                _logger.warning("migration of %s: target %s could not "
                                "import KV (%s); replaying", rid,
                                target.name, e)
                imported = 0
        cb = self._make_token_cb(sess, epoch)
        fut = target.server.admit_journal_entry(ent, on_token=cb)
        with self._lock:
            sess.replica = target
            self._migrations += 1
        _m_migrations.inc()
        fut.add_done_callback(
            lambda f, s=sess, r=target, g=epoch:
            self._on_replica_done(s, r, g, f))
        _tracing.event("fleet_migrate", request_id=rid,
                       source=source.name, to=target.name,
                       kv_tokens=int(imported),
                       wire_bytes=len(wire),
                       **sess._tr(replica=target.name))
        return target.name

    # ---- dynamic membership (ISSUE 20) ---------------------------------
    def add_replica(self, replica, *, require_warm=True):
        """Admit one replica into the live routing set (the
        autoscaler's scale-up actuation; callable directly). Accepts
        a `fleet.Replica` (incl. a spawned `RemoteReplica`) or a bare
        not-yet-started `PagedGenerationServer`.

        Readiness gate: with `require_warm=True` (default) the
        replica is only admitted once its engine PROVES
        `warm_buckets()` ran (the `warmed` readiness detail), so a
        fresh replica never pays an XLA compile inside a request
        window. A not-yet-started in-process engine that skipped the
        warm is warmed HERE (before start — the engine loop owns the
        cache arrays after); a remote replica must have been spawned
        warm (`warm_start`, the spawn default) or admission is
        refused. Returns the admitted `Replica`."""
        rep = (replica if isinstance(replica, Replica)
               else Replica(f"replica{next(self._rep_ids)}", replica))
        with self._lock:
            if self._stop:
                raise RuntimeError("router stopped")
            if any(r.name == rep.name for r in self.replicas):
                raise ValueError(f"duplicate replica name: "
                                 f"{rep.name!r}")
        if require_warm:
            srv = rep.server
            if (hasattr(srv, "warm_buckets")
                    and not getattr(srv, "_warm_ran", False)
                    and getattr(srv, "_thread", None) is None):
                srv.warm_buckets()
        if self._started:
            rep.start()
        if require_warm:
            _ready, detail = rep.readiness()
            if not detail.get("warmed", False):
                rep.stop()
                raise RuntimeError(
                    f"replica {rep.name} failed the warm readiness "
                    f"gate (no proof warm_buckets ran); spawn with "
                    f"warm_start=True or pass require_warm=False")
        with self._lock:
            # copy-on-write: placement/probe iterations hold a
            # consistent snapshot, never a half-mutated list
            self.replicas = self.replicas + [rep]
            self._replicas_added += 1
            total = len(self.replicas)
        _m_added.inc()
        _m_replicas.set(float(total))
        _tracing.event("fleet_add_replica", replica=rep.name,
                       total=total)
        _logger.info("replica %s admitted (fleet size %d)", rep.name,
                     total)
        return rep

    def remove_replica(self, name, *, force=False):
        """Remove one replica from the routing set and stop it.
        Refuses (unless `force=True`) while unfinished sessions are
        resident on a live replica — `retire_replica` runs the full
        drain-first state machine. Residents still present at removal
        (force, or a dead replica) fail over to survivors via the
        router journal. Returns the removed `Replica`."""
        with self._lock:
            rep = next((r for r in self.replicas if r.name == name),
                       None)
            if rep is None:
                raise KeyError(f"unknown replica {name!r}")
            if len(self.replicas) == 1:
                raise ValueError("cannot remove the last replica")
            residents = [s for s in self._sessions.values()
                         if s.replica is rep and not s.done]
            if residents and not force and not rep.dead:
                raise RuntimeError(
                    f"replica {name} has {len(residents)} resident "
                    f"session(s); retire_replica drains first "
                    f"(or pass force=True)")
            self.replicas = [r for r in self.replicas if r is not rep]
            self._replicas_removed += 1
            total = len(self.replicas)
        _m_removed.inc()
        _m_replicas.set(float(total))
        _tracing.event("fleet_remove_replica", replica=rep.name,
                       total=total)
        # anything still resident moves NOW, before the engine stops
        self._failover_replica(rep, why="removed from fleet")
        rep.stop()
        _logger.info("replica %s removed (fleet size %d)", name, total)
        return rep

    def retire_replica(self, name, *, now=None):
        """Scale-down actuation, as one deterministic state machine:

        1. DRAIN — `set_draining(True)` on the engine and not_ready
           in the health machine: residents keep decoding, placement
           weight drops to 0 immediately.
        2. MIGRATE — every resident session moves to best-prefix/
           least-loaded survivors over the migration wire in accept
           order (zero prefill recompute); a SIGKILL mid-drain
           degrades the remaining moves to the r18 journal failover,
           token-identically.
        3. RETIRE — `remove_replica` drops and stops the engine.

        Returns {"replica", "migrated", "failed_over"}."""
        now = time.monotonic() if now is None else now
        with self._lock:
            rep = next((r for r in self.replicas if r.name == name),
                       None)
            if rep is None:
                raise KeyError(f"unknown replica {name!r}")
            if len(self.replicas) == 1:
                raise ValueError("cannot retire the last replica")
        _tracing.event("fleet_retire_replica", replica=rep.name)
        try:
            rep.set_draining(True)
        except Exception:  # noqa: BLE001 — a replica dying mid-call
            pass           # drains by failover below
        rep.health.note_not_ready(now, "draining (retire)")
        _m_state.labels(replica=rep.name).set(
            _STATE_CODE["not_ready"])
        migrated = 0
        failed_over = 0
        # bounded sweep: weight 0 stops new placements, but a submit
        # racing the drain flip can land one more resident
        for _round in range(8):
            with self._lock:
                residents = sorted(
                    (s for s in self._sessions.values()
                     if s.replica is rep and not s.done),
                    key=lambda s: s.rid)
            if not residents:
                break
            progress = False
            for sess in residents:
                try:
                    was_dead = rep.dead
                    target = self.migrate_session(sess.rid)
                    if target != rep.name:
                        progress = True
                        if was_dead:
                            failed_over += 1
                        else:
                            migrated += 1
                except KeyError:
                    progress = True  # finished while we looked
                except Exception as e:  # noqa: BLE001 — no survivor
                    # for this session: leave it to remove_replica's
                    # failover (which fails the future if the fleet
                    # truly has nowhere to put it)
                    _logger.warning("retire %s: moving %s failed "
                                    "(%s)", rep.name, sess.rid, e)
            if not progress:
                break
        self.remove_replica(name, force=True)
        return {"replica": name, "migrated": migrated,
                "failed_over": failed_over}

    # ---- probes --------------------------------------------------------
    def _probe_loop(self):
        while not self._stop:
            try:
                self.check_replicas()
            except Exception:  # noqa: BLE001 — the probe loop must
                _logger.exception("fleet probe pass failed")
            self._probe_wake.wait(timeout=self.probe_interval_s)
            self._probe_wake.clear()

    def check_replicas(self, now=None):
        """One active probe pass (the probe thread calls this on the
        interval; tests call it directly with an explicit now).
        Liveness false => the replica is DEAD: mark it and fail its
        sessions over. Ready false => weight 0, sessions stay.
        Circuit-open replicas are only probed when their capped
        backoff has elapsed, and a healthy probe alone never closes
        an open circuit — only trial traffic does."""
        now = time.monotonic() if now is None else now
        for rep in self.replicas:
            if rep.dead:
                # externally killed/died: make sure nothing is left
                self._failover_replica(rep, why="dead replica")
                _m_state.labels(replica=rep.name).set(
                    _STATE_CODE["dead"])
                continue
            h = rep.health
            if not h.probe_due(now):
                continue
            live, _detail = rep.liveness()
            if not live and self._started:
                _m_probes.labels(replica=rep.name,
                                 outcome="dead").inc()
                h.mark_dead("liveness probe failed")
                self._failover_replica(rep, why="liveness probe "
                                                "failed")
                _m_state.labels(replica=rep.name).set(
                    _STATE_CODE["dead"])
                continue
            ready, _detail = rep.readiness()
            if ready:
                _m_probes.labels(replica=rep.name, outcome="ok").inc()
                if h.state in ("ok", "degraded", "not_ready"):
                    # a bare probe never closes an OPEN circuit: the
                    # failures were real traffic; only trial traffic
                    # (half-open weight) may close it
                    h.note_ok(now)
            else:
                _m_probes.labels(replica=rep.name,
                                 outcome="not_ready").inc()
                h.note_not_ready(now, "readiness probe false")
            _m_state.labels(replica=rep.name).set(
                _STATE_CODE.get(h.state, 4.0))
        # SLO degrade hook (ISSUE 14): AFTER the probes, so a healthy
        # readiness probe cannot mask a sustained page burn this pass
        self._slo_degrade_check(now)

    # ---- recovery ------------------------------------------------------
    def recover_from_journal(self, journal=None):
        """Re-admit every accepted-but-unfinished session in the
        ROUTER journal onto the current fleet — the router-crash half
        of the takeover story (replica failover replays the same
        entries while the router lives). Returns {rid: Future}."""
        j = journal if journal is not None else self._journal
        if j is None:
            raise ValueError("no journal: pass one or build the "
                             "router with journal=")
        out = {}
        for ent in j.interrupted():
            sampling = None
            if ent.get("sampling"):
                sampling = SamplingParams(
                    **{k: tuple(v) if isinstance(v, list) else v
                       for k, v in ent["sampling"].items()})
            meta = None
            if ent.get("meta"):
                from ..inference.serving import RequestMeta

                m = ent["meta"]
                meta = RequestMeta(
                    lane=m.get("lane", "interactive"),
                    tenant=m.get("tenant", "default"),
                    deadline_s=m.get("deadline_s"),
                    cost=int(m.get("cost", 0)))
            trace = (TraceContext.from_dict(ent["trace"])
                     .child("failover")
                     if ent.get("trace") else None)
            sess = _Session(ent["rid"],
                            np.asarray(ent["ids"], np.int32),
                            int(ent["budget"]), int(ent["seed"]),
                            sampling, meta, ent.get("timeout_s"),
                            None, trace=trace)
            sess.toks = [int(t) for t in ent.get("gen0", [])]
            with self._lock:
                self._sessions[sess.rid] = sess
            self._dispatch(sess, first=False)
            out[sess.rid] = sess.future
        return out

    # ---- introspection -------------------------------------------------
    def health(self):
        """(status, detail) for the fleet /healthz: ok = every replica
        routable, degraded = some are not but >= 1 is, stalled =
        nothing routable (503 — drain the fleet)."""
        now = time.monotonic()
        routable = self._routable(now)
        states = {r.name: r.health.state for r in self.replicas}
        detail = {"replicas": states,
                  "routable": len(routable),
                  "total": len(self.replicas)}
        if not routable:
            return "stalled", detail
        if len(routable) < len(self.replicas):
            return "degraded", detail
        return "ok", detail

    def statusz(self):
        with self._lock:
            live = [s.rid for s in self._sessions.values()
                    if not s.done]
        status, detail = self.health()
        return {
            "server": "fleet",
            "health": {"status": status, **detail},
            "replicas": [r.stats() for r in self.replicas],
            "live_sessions": live,
            "stats": self.stats(),
        }

    def metrics_text(self):
        """The federated /metrics page: every replica's exposition
        with a `replica` label injected, fleet-level `fleet_*` series
        appended once (fleet.federation)."""
        def _metric_of(line):
            s = line.strip()
            if s.startswith("# HELP ") or s.startswith("# TYPE "):
                parts = s.split(" ", 3)
                return parts[2] if len(parts) > 2 else ""
            if not s or s.startswith("#"):
                return ""
            cut = len(s)
            for ch in ("{", " "):
                i = s.find(ch)
                if i != -1:
                    cut = min(cut, i)
            return s[:cut]

        def _split(text):
            rep_lines, fleet_lines = [], []
            for line in text.splitlines():
                (fleet_lines if _metric_of(line).startswith("fleet_")
                 else rep_lines).append(line)
            return "\n".join(rep_lines), "\n".join(fleet_lines)

        sources = []
        fleet_extra = ""
        for rep in self.replicas:
            rep_text, fleet_text = _split(rep.metrics_text())
            sources.append((rep.name, rep_text))
            if fleet_text:
                fleet_extra = fleet_text  # same process registry:
                # fleet series are identical across in-process
                # replicas — keep one copy, unrelabeled
        return federate_metrics(sources, extra=fleet_extra)

    def reset_stats(self):
        with self._lock:
            self._ttft.clear()
            self._tokens_out = 0
            self._requests_done = 0
            self._failovers = 0
            self._failover_sessions = 0
            self._migrations = 0
            self._replica_kills = 0
            self._sheds = 0
            self._retries = 0
            self._prefix_routed = 0
            self._placements = 0
            self._replicas_added = 0
            self._replicas_removed = 0
            self._t0 = time.perf_counter()
        # reset-coherent with the attached autoscaler's window
        if self._autoscaler is not None:
            self._autoscaler.reset_stats()

    def stats(self):
        with self._lock:
            ttft = sorted(self._ttft)
            n = len(ttft)
            pct = (lambda p: ttft[min(n - 1, int(p * n))] * 1e3
                   if n else 0.0)
            dt = (time.perf_counter() - self._t0) if self._t0 else 0.0
            live = sum(1 for s in self._sessions.values()
                       if not s.done)
            return {
                "replicas": {r.name: r.stats() for r in self.replicas},
                "live_sessions": live,
                "requests_done": self._requests_done,
                "new_tokens": self._tokens_out,
                "tokens_per_sec": (self._tokens_out / dt
                                   if dt else 0.0),
                "ttft_p50_ms": pct(0.50),
                "ttft_p99_ms": pct(0.99),
                "placements": self._placements,
                "prefix_routed": self._prefix_routed,
                "failovers": self._failovers,
                "failover_sessions": self._failover_sessions,
                "migrations": self._migrations,
                "replicas_added": self._replicas_added,
                "replicas_removed": self._replicas_removed,
                "replica_kills": self._replica_kills,
                "sheds": self._sheds,
                "submit_retries": self._retries,
                "fault_plan": (self._faults.describe()
                               if self._faults is not None else None),
                "journal": (self._journal.stats()
                            if self._journal is not None else None),
                "wall_s": dt,
                "slo": {
                    "enabled": self._slo is not None,
                    "degraded_replicas": sorted(self._slo_degraded),
                },
                "autoscale": (self._autoscaler.stats_block()
                              if self._autoscaler is not None
                              else dict(AUTOSCALE_ZERO)),
            }
