"""Wire transport: subprocess replicas behind stdlib HTTP (r22).

The r18 fleet proved the control plane with every replica in ONE
process; this module cuts the replica boundary at a real wire so the
scaling numbers become real. The wire surface is deliberately the
surface the router already speaks:

  * admission  — `POST /submit` carries either a FRESH request (the
    router-resolved sampling/seed/meta/trace, exactly `submit()`'s
    arguments) or a journal-shape resume entry (the
    `SessionJournal.entry_for` dict `admit_journal_entry` consumes).
    The response is a newline-delimited JSON token stream — one
    `{"tok", "reason"}` line per generated token, then one terminal
    `{"result"}` or typed `{"error"}` line — so the router's
    journaling token callback fires exactly as it does in-process.
  * KV migration — `POST /export` ships the journal entry plus the
    session's published K/V as the r20 compressed wire bytes
    (`serialize_kv_payload`: int8 codes + scales); `POST /import`
    accepts the same bytes. Int8 KV pools ship bit-exactly, so a
    subprocess migration is byte-for-byte the in-process one.
  * probes — `/healthz/live`, `/healthz/ready`, `/load`,
    `/match_prefix`, `/capacity`, `/stats`, `/metrics`, `/events`
    mirror the `Replica` probe surface 1:1.

`RemoteReplica` adapts that wire back into the replica protocol, so
FleetRouter's journal/failover/migration logic runs UNCHANGED over OS
processes: a dead subprocess fails its streams and probes, and the
ordinary r18 failover re-admits its sessions token-identically from
the router journal.

Error mapping across the wire (the contract `_on_replica_done`
relies on): `AdmissionShed` -> HTTP 429 and re-raised typed (the
router retries another replica); eager validation errors -> HTTP 400
(`ValueError`/`TypeError`); per-request terminal failures
(`QuarantinedRequest`, `RequestTimeout`) ride the stream's terminal
line and are reconstructed typed (no failover — same as in-process);
any transport failure (connect refused, stream cut mid-request)
surfaces as `ReplicaUnavailable` on the future, which the router
treats as a replica failure and fails over.

Workers run `python -m paddle_tpu.fleet.transport --config <json>`:
the config rebuilds the model DETERMINISTICALLY (global seed + model
config — same recipe as the parent's in-process twin), so token
parity across the wire needs no weight shipping.
"""
from __future__ import annotations

import http.client
import json
import os
import queue
import struct
import subprocess
import sys
import tempfile
import threading
import time
from concurrent.futures import Future
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..observability import log as _obs_log
from ..observability import metrics as _metrics
from ..observability import tracing as _tracing
from ..reliability.errors import (AdmissionShed, QuarantinedRequest,
                                  ReplicaUnavailable, RequestTimeout)
from .migration import deserialize_kv_payload, serialize_kv_payload
from .replica import Replica

_logger = _obs_log.get_logger(__name__)

_m_wire_requests = _metrics.counter(
    "fleet_wire_requests_total",
    "Wire transport calls by verb (router side)", labelnames=("verb",))
_m_wire_tokens = _metrics.counter(
    "fleet_wire_tokens_total",
    "Tokens streamed over the wire transport (router side)")
_m_wire_bytes = _metrics.counter(
    "fleet_wire_bytes_total",
    "Wire transport payload bytes (router side)",
    labelnames=("direction",))
_m_wire_errors = _metrics.counter(
    "fleet_wire_errors_total",
    "Wire transport failures by kind (router side)", labelnames=("kind",))

#: handshake line a worker prints on stdout once its engine and HTTP
#: server are up — the parent parses `port=`/`pid=` from it.
HANDSHAKE_PREFIX = "PADDLE_TPU_WORKER"

#: worker-side stall guard: if the engine emits nothing on a stream
#: for this long the worker ends it with an error line (the client
#: maps that to ReplicaUnavailable -> router failover).
STREAM_IDLE_TIMEOUT_S = 600.0


# ---------------------------------------------------------------------------
# wire encoding helpers (shared by both ends)
# ---------------------------------------------------------------------------

def _sampling_to_wire(sampling):
    from dataclasses import asdict, is_dataclass

    if sampling is None:
        return None
    if is_dataclass(sampling):
        return asdict(sampling)
    raise TypeError(f"sampling must be a SamplingParams, "
                    f"got {type(sampling).__name__}")


def _meta_to_wire(meta):
    if meta is None:
        return None
    return {"lane": meta.lane, "tenant": meta.tenant,
            "deadline_s": meta.deadline_s, "cost": meta.cost}


def _exc_to_wire(exc):
    if isinstance(exc, QuarantinedRequest):
        return {"type": "QuarantinedRequest", "rid": exc.rid,
                "seam": exc.seam, "failures": exc.failures,
                "cause": f"{type(exc.cause).__name__}: {exc.cause}"}
    if isinstance(exc, RequestTimeout):
        return {"type": "RequestTimeout", "rid": exc.rid,
                "waited_s": exc.waited_s, "timeout_s": exc.timeout_s}
    return {"type": type(exc).__name__, "msg": str(exc)}


def _exc_from_wire(err, rid):
    t = err.get("type", "RuntimeError")
    if t == "QuarantinedRequest":
        return QuarantinedRequest(err.get("rid", rid), err.get("seam", "?"),
                                  int(err.get("failures", 1)),
                                  RuntimeError(err.get("cause", "")))
    if t == "RequestTimeout":
        return RequestTimeout(err.get("rid", rid),
                              float(err.get("waited_s", 0.0)),
                              float(err.get("timeout_s", 0.0)))
    return RuntimeError(f"remote {t}: {err.get('msg', '')}")


def _jsonable(obj):
    """Best-effort JSON coercion for stats/capacity payloads (numpy
    scalars and arrays appear in engine stats)."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (np.bool_,)):
        return bool(obj)
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return str(obj)


# ---------------------------------------------------------------------------
# worker side (runs in the subprocess)
# ---------------------------------------------------------------------------

def build_worker_server(config):
    """Rebuild the model deterministically and construct the engine.

    config["model"]: {"kind": "gpt2", "seed": int, "config": {...}} —
        the global RNG seed plus `GPT2Config` kwargs; the parent
        builds its in-process twin with the same recipe, so weights
        match bit-for-bit without shipping them.
    config["server"]: JSON-able `PagedGenerationServer` kwargs;
        "kv_tier" (dict) becomes a `HostKVTier`, "journal" (path str)
        a `SessionJournal`, "speculation" passes through (True or a
        SpecConfig dict).
    """
    import paddle_tpu as paddle
    from ..inference.serving import PagedGenerationServer
    from ..models.gpt2 import GPT2, GPT2Config

    spec = config.get("model", {})
    kind = spec.get("kind", "gpt2")
    if kind != "gpt2":
        raise ValueError(f"unknown worker model kind {kind!r}")
    paddle.seed(int(spec.get("seed", 0)))
    cfg = GPT2Config(**spec.get("config", {}))
    model = GPT2(cfg)
    model.eval()

    kw = dict(config.get("server", {}))
    tier = kw.pop("kv_tier", None)
    if tier:
        from ..inference.kv_tier import HostKVTier
        kw["kv_tier"] = HostKVTier(**tier)
    jr = kw.pop("journal", None)
    if jr:
        from ..reliability import SessionJournal
        kw["journal"] = SessionJournal(jr)
    return PagedGenerationServer(model, **kw)


class _WorkerState:
    """Everything the HTTP handlers touch: the engine plus a local
    `Replica` used purely as the probe-surface delegate (load, queue
    depth, prefix match, capacity — identical arithmetic to the
    in-process replica the router would otherwise wrap)."""

    def __init__(self, name, srv):
        self.name = name
        self.srv = srv
        self.probe = Replica(name, srv)
        self.probe._started = True  # started out-of-band below


class _WorkerHandler(BaseHTTPRequestHandler):
    # HTTP/1.0: every response is close-delimited, so the token
    # stream needs no chunked framing — the client reads lines until
    # EOF. One connection per call is fine at fleet probe rates.
    protocol_version = "HTTP/1.0"

    def log_message(self, fmt, *args):  # noqa: D102 — quiet worker
        pass

    # -- plumbing --------------------------------------------------------
    def _state(self):
        return self.server.worker_state

    def _read_body(self):
        n = int(self.headers.get("Content-Length", 0) or 0)
        return self.rfile.read(n) if n else b""

    def _send_json(self, code, obj):
        body = json.dumps(_jsonable(obj)).encode()
        self._send_raw(code, body, "application/json")

    def _send_raw(self, code, body, ctype):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _write_line(self, obj):
        self.wfile.write(json.dumps(obj).encode() + b"\n")
        self.wfile.flush()

    # -- routes ----------------------------------------------------------
    def do_GET(self):  # noqa: N802 — http.server API
        st = self._state()
        try:
            if self.path == "/info":
                srv = st.srv
                self._send_json(200, {
                    "name": st.name, "pid": os.getpid(),
                    "max_new": srv.max_new, "max_slots": srv.max_slots,
                    "enable_prefix_cache": srv.enable_prefix_cache,
                    "warmed": bool(getattr(srv, "_warm_ran", False))})
            elif self.path == "/healthz/live":
                live, detail = st.probe.liveness()
                self._send_json(200, {"live": bool(live),
                                      "detail": detail})
            elif self.path == "/healthz/ready":
                ready, detail = st.probe.readiness()
                self._send_json(200, {"ready": bool(ready),
                                      "detail": detail})
            elif self.path == "/load":
                self._send_json(200, {
                    "load": st.probe.load(),
                    "queue_depth": st.probe.queue_depth()})
            elif self.path == "/capacity":
                self._send_json(200, st.probe.capacity())
            elif self.path == "/stats":
                self._send_json(200, st.srv.stats())
            elif self.path == "/metrics":
                # a subprocess replica serves its OWN registry — the
                # parent's federation labels it by replica name
                self._send_raw(200, _metrics.REGISTRY.to_prometheus()
                               .encode(), "text/plain; version=0.0.4")
            elif self.path == "/events":
                try:
                    evs = list(st.srv._recorder.events())
                except Exception:  # noqa: BLE001 — recorder optional
                    evs = []
                self._send_json(200, evs)
            else:
                self._send_json(404, {"msg": f"no route {self.path}"})
        except Exception as e:  # noqa: BLE001 — worker must not die
            try:
                self._send_json(500, {"type": type(e).__name__,
                                      "msg": str(e)})
            except Exception:  # noqa: BLE001 — client already gone
                pass

    def do_POST(self):  # noqa: N802 — http.server API
        try:
            if self.path == "/submit":
                self._do_submit()
            elif self.path == "/export":
                self._do_export()
            elif self.path == "/import":
                self._do_import()
            elif self.path == "/match_prefix":
                body = json.loads(self._read_body() or b"{}")
                n = self._state().probe.prefix_match_len(
                    np.asarray(body.get("ids", []), np.int32))
                self._send_json(200, {"match_len": int(n)})
            elif self.path == "/drain":
                body = json.loads(self._read_body() or b"{}")
                self._state().srv.set_draining(
                    bool(body.get("draining", True)))
                self._send_json(200, {"ok": True})
            elif self.path == "/shutdown":
                self._send_json(200, {"ok": True})
                threading.Thread(target=self.server.initiate_shutdown,
                                 daemon=True).start()
            else:
                self._send_json(404, {"msg": f"no route {self.path}"})
        except Exception as e:  # noqa: BLE001 — worker must not die
            try:
                self._send_json(500, {"type": type(e).__name__,
                                      "msg": str(e)})
            except Exception:  # noqa: BLE001
                pass

    # -- admission + token stream ---------------------------------------
    def _do_submit(self):
        from ..inference.serving import RequestMeta
        from ..observability.trace_context import TraceContext
        from ..sampling import SamplingParams

        body = json.loads(self._read_body())
        srv = self._state().srv
        q = queue.Queue()

        def on_tok(tok, reason):
            q.put(("tok", int(tok),
                   None if reason is None else str(reason)))

        try:
            if body.get("fresh"):
                sampling = None
                if body.get("sampling"):
                    sampling = SamplingParams(
                        **{k: tuple(v) if isinstance(v, list) else v
                           for k, v in body["sampling"].items()})
                meta = None
                if body.get("meta"):
                    m = body["meta"]
                    meta = RequestMeta(
                        lane=m.get("lane", "interactive"),
                        tenant=m.get("tenant", "default"),
                        deadline_s=m.get("deadline_s"),
                        cost=int(m.get("cost", 0)))
                trace_ctx = (TraceContext.from_dict(body["trace"])
                             if body.get("trace") else None)
                fut = srv.submit(
                    np.asarray(body["ids"], np.int32),
                    max_new_tokens=body.get("max_new_tokens"),
                    sampling=sampling, meta=meta, on_token=on_tok,
                    timeout_s=body.get("timeout_s"),
                    rid=body.get("rid"), trace_ctx=trace_ctx)
            else:
                ent = {k: v for k, v in body.items() if k != "fresh"}
                fut = srv.admit_journal_entry(ent, on_token=on_tok)
        except AdmissionShed as e:
            self._send_json(429, {"type": "AdmissionShed",
                                  "depth": e.depth,
                                  "shed_depth": e.shed_depth,
                                  "retry_after_s": e.retry_after_s})
            return
        except (ValueError, TypeError) as e:
            self._send_json(400, {"type": type(e).__name__,
                                  "msg": str(e)})
            return
        except Exception as e:  # noqa: BLE001 — typed to the client
            self._send_json(500, {"type": type(e).__name__,
                                  "msg": str(e)})
            return

        fut.add_done_callback(lambda f: q.put(("done", f)))
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.end_headers()
        try:
            while True:
                try:
                    item = q.get(timeout=STREAM_IDLE_TIMEOUT_S)
                except queue.Empty:
                    self._write_line({"error": {
                        "type": "WireStreamStall",
                        "msg": f"no engine progress in "
                               f"{STREAM_IDLE_TIMEOUT_S:g}s"}})
                    return
                if item[0] == "tok":
                    self._write_line({"tok": item[1],
                                      "reason": item[2]})
                    continue
                f = item[1]
                exc = f.exception()
                if exc is None:
                    self._write_line({"result": [int(x)
                                                 for x in f.result()]})
                else:
                    self._write_line({"error": _exc_to_wire(exc)})
                return
        except (BrokenPipeError, ConnectionError, OSError):
            return  # client went away — engine keeps its own state

    # -- KV migration wire ----------------------------------------------
    def _do_export(self):
        body = json.loads(self._read_body())
        srv = self._state().srv
        try:
            ent, payload = srv.export_session(body["rid"])
        except KeyError as e:
            self._send_json(404, {"type": "KeyError", "msg": str(e)})
            return
        wire = serialize_kv_payload(payload)
        ent_b = json.dumps(_jsonable(ent)).encode()
        blob = struct.pack(">I", len(ent_b)) + ent_b + wire
        self._send_raw(200, blob, "application/octet-stream")

    def _do_import(self):
        srv = self._state().srv
        payload = deserialize_kv_payload(self._read_body())
        owner = None
        tenant = self.headers.get("X-Owner-Tenant")
        rid = self.headers.get("X-Owner-Rid")
        if tenant is not None and rid is not None:
            owner = (tenant, rid)
        tokens = (srv.import_kv_payload(payload, owner=owner)
                  if payload is not None else 0)
        self._send_json(200, {"tokens": int(tokens)})


class _WorkerHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, handler, state):
        super().__init__(addr, handler)
        self.worker_state = state
        self._shutdown_once = threading.Lock()
        self._shutting_down = False

    def initiate_shutdown(self):
        with self._shutdown_once:
            if self._shutting_down:
                return
            self._shutting_down = True
        try:
            self.worker_state.srv.stop()
        except Exception:  # noqa: BLE001 — exit anyway
            _logger.exception("worker engine stop failed")
        self.shutdown()


def serve_worker(config):
    """Worker entrypoint: build the engine, bind an ephemeral HTTP
    port, print the handshake line, and serve until shutdown."""
    import signal

    name = config.get("name", f"worker-{os.getpid()}")
    srv = build_worker_server(config)
    srv.trace_name = name
    # warm-start (ISSUE 20): pre-compile every reachable jit bucket
    # BEFORE the handshake, so a freshly spawned replica never pays an
    # XLA compile inside a request window — /healthz/ready is
    # unreachable (no HTTP server) and false (engine not started)
    # until the warm completes. Opt out with "warm_start": false.
    if config.get("warm_start", True):
        modes = config.get("warm_modes")
        if modes is not None:
            modes = [tuple(bool(x) for x in m) for m in modes]
            srv.warm_buckets(modes)
        else:
            srv.warm_buckets()
    srv.start()
    state = _WorkerState(name, srv)
    httpd = _WorkerHTTPServer(
        (config.get("host", "127.0.0.1"), int(config.get("port", 0))),
        _WorkerHandler, state)
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: threading.Thread(
            target=httpd.initiate_shutdown, daemon=True).start())
    print(f"{HANDSHAKE_PREFIX} ready "  # cli-print: stdout handshake
          f"port={httpd.server_address[1]} "  # the parent parses this
          f"pid={os.getpid()}", flush=True)
    try:
        httpd.serve_forever(poll_interval=0.2)
    finally:
        httpd.server_close()


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="paddle_tpu fleet worker (subprocess replica)")
    ap.add_argument("--config", required=True,
                    help="path to a JSON worker config, or '-' for "
                         "stdin")
    args = ap.parse_args(argv)
    raw = (sys.stdin.read() if args.config == "-"
           else open(args.config).read())
    serve_worker(json.loads(raw))


# ---------------------------------------------------------------------------
# client side (runs in the router process)
# ---------------------------------------------------------------------------

class _WireRecorder:
    """`server._recorder` shim: the router's timeline export reads
    `.events()` in a try/except — fetch the worker's flight-recorder
    ring over the wire, empty on any failure."""

    def __init__(self, engine):
        self._engine = engine

    def events(self):
        try:
            return self._engine._get_json("/events")
        except Exception:  # noqa: BLE001 — timeline is best-effort
            return []


class RemoteEngine:
    """HTTP proxy speaking the engine surface the router reads:
    `submit`, `admit_journal_entry`, `export_session`,
    `import_kv_payload`, `max_new`, `max_slots`, `stats`,
    `_recorder.events()`. Futures are fed by a per-request reader
    thread pumping the worker's token stream; a cut stream fails the
    future with `ReplicaUnavailable`, which the router treats as a
    replica failure (failover), exactly like an in-process crash."""

    def __init__(self, host, port, *, name="remote",
                 probe_timeout_s=2.0, read_timeout_s=None):
        self.host = host
        self.port = int(port)
        self.trace_name = name  # Replica.__init__ overwrites
        self.probe_timeout_s = float(probe_timeout_s)
        self.read_timeout_s = (STREAM_IDLE_TIMEOUT_S + 60.0
                               if read_timeout_s is None
                               else float(read_timeout_s))
        self._recorder = _WireRecorder(self)
        info = self._get_json("/info", timeout=30.0)
        self.info = dict(info)  # connect-time worker facts (warmed, pid)
        self.max_new = int(info["max_new"])
        self.max_slots = int(info["max_slots"])
        self.enable_prefix_cache = bool(
            info.get("enable_prefix_cache", False))

    # -- plumbing --------------------------------------------------------
    def _get_json(self, path, timeout=None):
        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=self.probe_timeout_s if timeout is None
            else timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise RuntimeError(
                    f"GET {path} -> {resp.status}: {data[:200]!r}")
            return json.loads(data)
        finally:
            conn.close()

    def _post_raw(self, path, body, *, headers=None, timeout=None):
        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=self.read_timeout_s if timeout is None
            else timeout)
        try:
            conn.request("POST", path, body=body,
                         headers=headers or {})
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    # -- admission + token stream ---------------------------------------
    def submit(self, ids, max_new_tokens=None, sampling=None, *,
               meta=None, on_token=None, timeout_s=None, rid=None,
               trace_ctx=None):
        body = {
            "fresh": True,
            "ids": [int(x) for x in np.asarray(ids).reshape(-1)],
            "max_new_tokens": max_new_tokens,
            "sampling": _sampling_to_wire(sampling),
            "meta": _meta_to_wire(meta),
            "timeout_s": timeout_s,
            "rid": rid,
            "trace": (trace_ctx.to_dict() if trace_ctx is not None
                      else None),
        }
        return self._stream_submit(body, on_token, verb="submit")

    def admit_journal_entry(self, ent, on_token=None):
        body = dict(ent)
        body["fresh"] = False
        return self._stream_submit(body, on_token, verb="admit")

    def _stream_submit(self, body, on_token, verb):
        rid = body.get("rid")
        data = json.dumps(body).encode()
        if _metrics.enabled():
            _m_wire_requests.labels(verb=verb).inc()
            _m_wire_bytes.labels(direction="sent").inc(len(data))
        conn = http.client.HTTPConnection(
            self.host, self.port, timeout=self.read_timeout_s)
        try:
            conn.request("POST", "/submit", body=data,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
        except Exception as e:
            conn.close()
            if _metrics.enabled():
                _m_wire_errors.labels(kind="connect").inc()
            raise ReplicaUnavailable(
                str(rid or "?"),
                f"wire connect to {self.trace_name}: "
                f"{type(e).__name__}: {e}") from e
        if resp.status != 200:
            payload = resp.read()
            conn.close()
            if _metrics.enabled():
                _m_wire_errors.labels(kind="status").inc()
            raise self._submit_error(resp.status, payload)
        _tracing.event("fleet_wire_submit", replica=self.trace_name,
                       request_id=rid, verb=verb)
        fut = Future()
        threading.Thread(
            target=self._pump, args=(conn, resp, fut, on_token, rid),
            daemon=True,
            name=f"wire-pump-{self.trace_name}").start()
        return fut

    @staticmethod
    def _submit_error(status, payload):
        try:
            err = json.loads(payload)
        except Exception:  # noqa: BLE001 — non-JSON error page
            err = {"type": "RuntimeError",
                   "msg": payload[:200].decode("utf-8", "replace")}
        if status == 429 and err.get("type") == "AdmissionShed":
            return AdmissionShed(int(err["depth"]),
                                 int(err["shed_depth"]),
                                 float(err["retry_after_s"]))
        if status == 400:
            cls = TypeError if err.get("type") == "TypeError" \
                else ValueError
            return cls(err.get("msg", "remote validation failed"))
        return RuntimeError(f"remote submit -> {status}: "
                            f"{err.get('type')}: {err.get('msg')}")

    def _pump(self, conn, resp, fut, on_token, rid):
        try:
            for raw in iter(resp.readline, b""):
                line = raw.strip()
                if not line:
                    continue
                if _metrics.enabled():
                    _m_wire_bytes.labels(direction="received").inc(
                        len(raw))
                msg = json.loads(line)
                if "tok" in msg:
                    if _metrics.enabled():
                        _m_wire_tokens.inc()
                    if on_token is not None:
                        try:
                            on_token(int(msg["tok"]),
                                     msg.get("reason"))
                        except Exception:  # noqa: BLE001
                            _logger.exception(
                                "wire on_token callback failed")
                elif "result" in msg:
                    fut.set_result(np.asarray(msg["result"],
                                              dtype=np.int32))
                    return
                elif "error" in msg:
                    fut.set_exception(
                        _exc_from_wire(msg["error"], rid))
                    return
        except Exception as e:  # noqa: BLE001 — cut stream
            if not fut.done():
                if _metrics.enabled():
                    _m_wire_errors.labels(kind="stream").inc()
                fut.set_exception(ReplicaUnavailable(
                    str(rid or "?"),
                    f"wire stream from {self.trace_name}: "
                    f"{type(e).__name__}: {e}"))
            return
        finally:
            conn.close()
        if not fut.done():
            # EOF without a terminal line: the worker died mid-stream
            if _metrics.enabled():
                _m_wire_errors.labels(kind="stream").inc()
            fut.set_exception(ReplicaUnavailable(
                str(rid or "?"),
                f"wire stream from {self.trace_name} closed "
                f"mid-request"))

    # -- KV migration wire ----------------------------------------------
    def export_session(self, rid):
        if _metrics.enabled():
            _m_wire_requests.labels(verb="export").inc()
        status, data = self._post_raw(
            "/export", json.dumps({"rid": rid}).encode(),
            headers={"Content-Type": "application/json"})
        if status == 404:
            raise KeyError(rid)
        if status != 200:
            raise RuntimeError(f"wire export {rid!r} -> {status}: "
                               f"{data[:200]!r}")
        if _metrics.enabled():
            _m_wire_bytes.labels(direction="received").inc(len(data))
        (n,) = struct.unpack(">I", data[:4])
        ent = json.loads(data[4:4 + n].decode())
        # int8 KV pools round-trip the r20 codec bit-exactly, so the
        # router's own serialize->deserialize pass reproduces these
        # bytes; dense pools re-quantize (tolerance-gated) — pair the
        # wire with kv_dtype="int8" when exact parity matters.
        return ent, deserialize_kv_payload(data[4 + n:])

    def import_kv_payload(self, payload, owner=None):
        wire = serialize_kv_payload(payload)
        headers = {"Content-Type": "application/octet-stream"}
        if owner is not None:
            headers["X-Owner-Tenant"] = str(owner[0])
            headers["X-Owner-Rid"] = str(owner[1])
        if _metrics.enabled():
            _m_wire_requests.labels(verb="import").inc()
            _m_wire_bytes.labels(direction="sent").inc(len(wire))
        status, data = self._post_raw("/import", wire,
                                      headers=headers)
        if status != 200:
            raise RuntimeError(f"wire import -> {status}: "
                               f"{data[:200]!r}")
        return int(json.loads(data)["tokens"])

    # -- misc engine surface ---------------------------------------------
    def set_draining(self, draining=True):
        status, data = self._post_raw(
            "/drain",
            json.dumps({"draining": bool(draining)}).encode(),
            headers={"Content-Type": "application/json"},
            timeout=self.probe_timeout_s)
        if status != 200:
            raise RuntimeError(f"wire drain -> {status}: "
                               f"{data[:200]!r}")
        return self

    def stats(self):
        return self._get_json("/stats", timeout=self.probe_timeout_s)

    def capacity_snapshot(self):
        return self._get_json("/capacity",
                              timeout=self.probe_timeout_s)


class RemoteReplica(Replica):
    """A fleet replica whose engine lives in ANOTHER OS process.

    Speaks the identical replica protocol (`Replica`), so the router
    does not know or care: probes are HTTP GETs with short timeouts
    (a hung or dead worker reads as not-live and the ordinary r18
    failover runs), placement signals (`load`, `prefix_match_len`)
    degrade safely on wire errors, and `kill()` is a real SIGKILL —
    the chaos gates exercise a true process death.
    """

    def __init__(self, name, engine, *, proc=None, health=None,
                 stderr_path=None, config_path=None,
                 keep_alive_on_stop=False):
        super().__init__(name, engine, health=health)
        self._proc = proc
        self._stderr_path = stderr_path
        self._config_path = config_path
        self._keep_alive_on_stop = bool(keep_alive_on_stop)

    # -- spawning --------------------------------------------------------
    @classmethod
    def spawn(cls, name, config, *, health=None,
              startup_timeout_s=180.0, python=None, env=None,
              keep_alive_on_stop=False):
        """Launch `python -m paddle_tpu.fleet.transport` with
        `config` (see `build_worker_server`), wait for the handshake
        line, and return a connected replica. The child inherits the
        parent environment (JAX_PLATFORMS, the persistent compile
        cache) plus `env` overrides; stderr goes to a temp log whose
        tail is surfaced on startup failure."""
        cfg = dict(config)
        cfg.setdefault("name", name)
        cf = tempfile.NamedTemporaryFile(
            "w", suffix=".json", prefix=f"ptpu-worker-{name}-",
            delete=False)
        json.dump(cfg, cf)
        cf.close()
        ef = tempfile.NamedTemporaryFile(
            "wb", suffix=".log", prefix=f"ptpu-worker-{name}-",
            delete=False)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        penv = dict(os.environ)
        penv["PYTHONPATH"] = os.pathsep.join(
            [repo_root] + ([penv["PYTHONPATH"]]
                           if penv.get("PYTHONPATH") else []))
        penv.setdefault("JAX_PLATFORMS", "cpu")
        if env:
            penv.update(env)
        proc = subprocess.Popen(
            [python or sys.executable, "-m",
             "paddle_tpu.fleet.transport", "--config", cf.name],
            stdout=subprocess.PIPE, stderr=ef, env=penv)
        ef.close()
        try:
            port = cls._await_handshake(proc, startup_timeout_s,
                                        ef.name)
            engine = RemoteEngine("127.0.0.1", port, name=name)
        except Exception:
            try:
                proc.kill()
            except Exception:  # noqa: BLE001
                pass
            raise
        rep = cls(name, engine, proc=proc, health=health,
                  stderr_path=ef.name, config_path=cf.name,
                  keep_alive_on_stop=keep_alive_on_stop)
        rep._started = True  # the worker engine is live from spawn
        return rep

    @staticmethod
    def _await_handshake(proc, timeout_s, stderr_path):
        lines = queue.Queue()

        def _reader():
            for raw in iter(proc.stdout.readline, b""):
                lines.put(raw)
            lines.put(None)

        threading.Thread(target=_reader, daemon=True,
                         name="wire-handshake").start()
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                raw = lines.get(timeout=max(
                    0.1, deadline - time.monotonic()))
            except queue.Empty:
                raise RuntimeError(
                    f"worker handshake timed out after {timeout_s:g}s"
                    f"; stderr tail: "
                    f"{_tail(stderr_path)!r}") from None
            if raw is None:
                raise RuntimeError(
                    f"worker exited before handshake (rc="
                    f"{proc.poll()}); stderr tail: "
                    f"{_tail(stderr_path)!r}")
            line = raw.decode("utf-8", "replace").strip()
            if line.startswith(HANDSHAKE_PREFIX):
                fields = dict(kv.split("=", 1)
                              for kv in line.split()[1:]
                              if "=" in kv)
                # keep draining stdout so the child never blocks on a
                # full pipe
                threading.Thread(
                    target=lambda: proc.stdout.read(),
                    daemon=True, name="wire-stdout-drain").start()
                return int(fields["port"])

    # -- lifecycle -------------------------------------------------------
    def start(self):
        with self._lock:
            self._started = True  # worker engine started at spawn
        return self

    def stop(self):
        with self._lock:
            if not self._started or self._killed:
                self._started = False
                return
            self._started = False
        if self._keep_alive_on_stop:
            return  # caller owns the process (call terminate())
        self.terminate()

    def terminate(self, timeout_s=20.0):
        """Full teardown: graceful /shutdown, then escalate."""
        if self._proc is None:
            return
        try:
            self.server._post_raw("/shutdown", b"", timeout=5.0)
        except Exception:  # noqa: BLE001 — escalate below
            pass
        try:
            self._proc.wait(timeout=timeout_s)
        except Exception:  # noqa: BLE001 — escalate
            try:
                self._proc.terminate()
                self._proc.wait(timeout=5.0)
            except Exception:  # noqa: BLE001 — last resort
                try:
                    self._proc.kill()
                    self._proc.wait(timeout=5.0)
                except Exception:  # noqa: BLE001
                    pass

    def kill(self):
        """Chaos hook: a REAL process death (SIGKILL) — in-flight
        streams cut mid-request, probes refuse, and the router's
        journaled failover re-admits the sessions elsewhere."""
        with self._lock:
            if self._killed:
                return
            self._killed = True
        self.health.mark_dead("killed")
        if self._proc is not None:
            try:
                self._proc.kill()
            except Exception:  # noqa: BLE001 — already gone
                pass
        _logger.warning("remote replica %s killed (pid %s)",
                        self.name,
                        getattr(self._proc, "pid", "?"))

    # -- probe surface ---------------------------------------------------
    def liveness(self):
        if self._killed:
            return False, {"engine_running": False, "killed": True}
        try:
            r = self.server._get_json("/healthz/live")
            return bool(r.get("live")), r.get("detail", {})
        except Exception as e:  # noqa: BLE001 — dead wire = not live
            return False, {"wire_error": f"{type(e).__name__}: {e}"}

    def readiness(self):
        if self._killed:
            return False, {"killed": True}
        try:
            r = self.server._get_json("/healthz/ready")
            return bool(r.get("ready")), r.get("detail", {})
        except Exception as e:  # noqa: BLE001
            return False, {"wire_error": f"{type(e).__name__}: {e}"}

    def load(self):
        try:
            return int(self.server._get_json("/load")["load"])
        except Exception:  # noqa: BLE001 — avoid placing on a replica
            return 1 << 30  # we cannot even probe

    def queue_depth(self):
        try:
            return int(self.server._get_json("/load")["queue_depth"])
        except Exception:  # noqa: BLE001
            return 0

    def prefix_match_len(self, ids):
        if self.dead or not self.server.enable_prefix_cache:
            return 0
        try:
            status, data = self.server._post_raw(
                "/match_prefix",
                json.dumps({"ids": [int(x) for x in
                                    np.asarray(ids).reshape(-1)]}
                           ).encode(),
                headers={"Content-Type": "application/json"},
                timeout=self.server.probe_timeout_s)
            if status != 200:
                return 0
            return int(json.loads(data)["match_len"])
        except Exception:  # noqa: BLE001 — placement is advisory
            return 0

    def capacity(self):
        if self.dead:
            raise RuntimeError(f"replica {self.name} is dead")
        # probe-timeout-bounded: a hung worker raises here and the
        # federation layer (with its own timeout guard) converts that
        # into the snapshot's error slot
        return self.server.capacity_snapshot()

    def metrics_text(self):
        try:
            conn = http.client.HTTPConnection(
                self.server.host, self.server.port,
                timeout=self.server.probe_timeout_s)
            try:
                conn.request("GET", "/metrics")
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    raise RuntimeError(f"/metrics -> {resp.status}")
                return body.decode("utf-8", "replace")
            finally:
                conn.close()
        except Exception as e:  # noqa: BLE001 — federation tolerates
            return (f"# replica {self.name} unreachable: "
                    f"{type(e).__name__}: {e}\n")


def _tail(path, n=800):
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - n))
            return f.read().decode("utf-8", "replace")
    except Exception:  # noqa: BLE001 — diagnostics only
        return ""


if __name__ == "__main__":
    main()
