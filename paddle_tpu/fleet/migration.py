"""KV-payload wire format for planned session migration (fleet round,
tentpole part c; wire compression added in the quantized-collectives
round).

`PagedKVCache.export_prefix` hands back a host-side payload (numpy
block contents — int8 codes + scales ride together under a quantized
pool — plus fills and the pool layout); this module is the WIRE half:
a self-describing bytes encoding (`serialize_kv_payload` /
`deserialize_kv_payload`) so a session's published K/V can cross a
process or host boundary and be re-published on the target pool via
`PagedKVCache.import_prefix`. In-process fleets round-trip through it
too — the router migrates through bytes on purpose, so the format
stays exercised.

Encoding: one uncompressed .npz (numpy's own container) holding a
JSON header under `__meta__` and each block leaf under a positional
key (`k{i}` / `v{i}` for a dense pool, `k{i}_codes` / `k{i}_scales`
etc. for int8 — the leaf structure is implied by kv_dtype, so no
pickling and no treedef on the wire).

Wire compression: a DENSE pool's blocks used to cross the wire at
full compute-dtype width — the one KV byte stream the r13 int8 pool
didn't cover. `serialize_kv_payload` now quantizes dense block
contents to int8 codes + per-vector f32 scales (the exact
`inference/kv_quant` scheme: symmetric absmax per (layer, row, head)
vector, |x - deq| <= absmax/254 per element) and
`deserialize_kv_payload` decompresses back to the pool dtype, so
`import_prefix` and everything behind it see a dense payload again.
The round trip is TOLERANCE-GATED at the sender: if any vector fails
the absmax/254 bound (non-finite values are the only way) the payload
ships raw, flagged by the absence of `wire_dtype` in the header — the
receiver never guesses. int8 pools already ship codes+scales
bit-exactly and are untouched, as is the dead-source journal-replay
fallback (no payload, b""). Wire bytes are counted by
`fleet_migration_bytes_total{direction}` at both ends.
"""
from __future__ import annotations

import io
import json

import numpy as np

from ..observability import metrics as _metrics

_META = "__meta__"
_FIELDS = ("tokens", "block_size", "kv_dtype", "num_layers",
           "num_heads", "head_dim", "fills")

# per-element round-trip bound of the symmetric int8 scheme, as a
# fraction of each vector's absmax (see inference/kv_quant.py)
_WIRE_BOUND = 1.0 / 254.0

_m_migration_bytes = _metrics.counter(
    "fleet_migration_bytes_total",
    "KV migration payload bytes crossing the wire, by direction "
    "(export = serialized at the source, import = deserialized at "
    "the target)",
    labelnames=("direction",))


def _leaves(kv_dtype, arr):
    """Positional leaf list of one block's K or V content."""
    if kv_dtype == "int8":
        return [("codes", np.asarray(arr.codes)),
                ("scales", np.asarray(arr.scales))]
    return [("", np.asarray(arr))]


def _unleaves(kv_dtype, parts):
    if kv_dtype == "int8":
        from ..inference.kv_quant import QuantizedKV

        return QuantizedKV(parts["codes"], parts["scales"])
    return parts[""]


def _encode_wire(arr):
    """Quantize one dense block [L, BS, H, Dh] to (int8 codes, f32
    per-vector scales) for the wire. Returns None when the block
    fails the tolerance gate (non-finite content) — the caller ships
    raw."""
    x = np.asarray(arr, dtype=np.float32)
    if not np.isfinite(x).all():
        return None
    amax = np.max(np.abs(x), axis=-1)
    sc = (np.maximum(amax, 1e-12) / 127.0).astype(np.float32)
    codes = np.clip(np.rint(x / sc[..., None]), -127,
                    127).astype(np.int8)
    # tolerance gate: the symmetric scheme guarantees
    # |x - deq| <= absmax/254 per element in exact arithmetic — verify
    # (with a one-ulp f32 allowance on the divide/multiply round trip)
    # rather than assume, so a numerics regression ships raw instead
    # of corrupt
    err = np.abs(x - codes.astype(np.float32) * sc[..., None])
    bound = amax[..., None] * (_WIRE_BOUND * (1.0 + 1e-4) + 1e-6)
    if not (err <= bound + 1e-12).all():
        return None
    return codes, sc


def _decode_wire(codes, scales, dtype_str):
    x = codes.astype(np.float32) * scales[..., None]
    try:
        return x.astype(np.dtype(dtype_str))
    except TypeError:  # unknown dtype string (no ml_dtypes): the pool
        return x       # write casts on set



def serialize_kv_payload(payload, wire_compress=True):
    """`export_prefix` payload -> bytes (None passes through as b"" —
    a session with nothing cached migrates by journal replay).

    Dense payloads compress to int8 codes + per-vector scales on the
    wire by default (`wire_compress=False` pins the raw pre-round
    format); int8-pool payloads already ARE codes+scales and ship
    bit-exactly either way."""
    if payload is None:
        return b""
    meta = {f: payload[f] for f in _FIELDS}
    compress = bool(wire_compress) and payload["kv_dtype"] is None
    arrays = {}
    encoded = {}
    if compress:
        for side in ("k", "v"):
            for i, block in enumerate(payload[side]):
                enc = _encode_wire(block)
                if enc is None:       # tolerance gate: ship raw
                    compress = False
                    encoded.clear()
                    break
                encoded[(side, i)] = enc
            if not compress:
                break
    if compress:
        meta["wire_dtype"] = "int8"
        meta["dtype"] = str(np.asarray(payload["k"][0]).dtype)
        for (side, i), (codes, sc) in encoded.items():
            arrays[f"{side}{i}_codes"] = codes
            arrays[f"{side}{i}_scales"] = sc
    else:
        for side in ("k", "v"):
            for i, block in enumerate(payload[side]):
                for suffix, arr in _leaves(payload["kv_dtype"], block):
                    key = f"{side}{i}" + (f"_{suffix}" if suffix
                                          else "")
                    arrays[key] = arr
    buf = io.BytesIO()
    np.savez(buf, **arrays,
             **{_META: np.frombuffer(
                 json.dumps(meta).encode("utf-8"), np.uint8)})
    data = buf.getvalue()
    if _metrics.enabled():
        _m_migration_bytes.labels(direction="export").inc(len(data))
    return data


def deserialize_kv_payload(data):
    """bytes -> `import_prefix` payload (b"" -> None). Wire-compressed
    dense payloads decompress back to the pool dtype here, so the
    import path is format-agnostic."""
    if not data:
        return None
    if _metrics.enabled():
        _m_migration_bytes.labels(direction="import").inc(len(data))
    with np.load(io.BytesIO(data)) as z:
        meta = json.loads(bytes(z[_META]).decode("utf-8"))
        kv_dtype = meta["kv_dtype"]
        wire_dtype = meta.pop("wire_dtype", None)
        dtype_str = meta.pop("dtype", None)
        n = len(meta["fills"])
        out = dict(meta)
        for side in ("k", "v"):
            blocks = []
            for i in range(n):
                if wire_dtype == "int8":
                    blocks.append(_decode_wire(z[f"{side}{i}_codes"],
                                               z[f"{side}{i}_scales"],
                                               dtype_str))
                elif kv_dtype == "int8":
                    blocks.append(_unleaves(kv_dtype, {
                        "codes": z[f"{side}{i}_codes"],
                        "scales": z[f"{side}{i}_scales"]}))
                else:
                    blocks.append(_unleaves(kv_dtype,
                                            {"": z[f"{side}{i}"]}))
            out[side] = blocks
    return out
