"""KV-payload wire format for planned session migration (fleet round,
tentpole part c).

`PagedKVCache.export_prefix` hands back a host-side payload (numpy
block contents — int8 codes + scales ride together under a quantized
pool — plus fills and the pool layout); this module is the WIRE half:
a self-describing bytes encoding (`serialize_kv_payload` /
`deserialize_kv_payload`) so a session's published K/V can cross a
process or host boundary and be re-published on the target pool via
`PagedKVCache.import_prefix`. In-process fleets round-trip through it
too — the router migrates through bytes on purpose, so the format
stays exercised.

Encoding: one uncompressed .npz (numpy's own container) holding a
JSON header under `__meta__` and each block leaf under a positional
key (`k{i}` / `v{i}` for a dense pool, `k{i}_codes` / `k{i}_scales`
etc. for int8 — the leaf structure is implied by kv_dtype, so no
pickling and no treedef on the wire).
"""
from __future__ import annotations

import io
import json

import numpy as np

_META = "__meta__"
_FIELDS = ("tokens", "block_size", "kv_dtype", "num_layers",
           "num_heads", "head_dim", "fills")


def _leaves(kv_dtype, arr):
    """Positional leaf list of one block's K or V content."""
    if kv_dtype == "int8":
        return [("codes", np.asarray(arr.codes)),
                ("scales", np.asarray(arr.scales))]
    return [("", np.asarray(arr))]


def _unleaves(kv_dtype, parts):
    if kv_dtype == "int8":
        from ..inference.kv_quant import QuantizedKV

        return QuantizedKV(parts["codes"], parts["scales"])
    return parts[""]


def serialize_kv_payload(payload):
    """`export_prefix` payload -> bytes (None passes through as b"" —
    a session with nothing cached migrates by journal replay)."""
    if payload is None:
        return b""
    meta = {f: payload[f] for f in _FIELDS}
    arrays = {}
    for side in ("k", "v"):
        for i, block in enumerate(payload[side]):
            for suffix, arr in _leaves(payload["kv_dtype"], block):
                key = f"{side}{i}" + (f"_{suffix}" if suffix else "")
                arrays[key] = arr
    buf = io.BytesIO()
    np.savez(buf, **arrays,
             **{_META: np.frombuffer(
                 json.dumps(meta).encode("utf-8"), np.uint8)})
    return buf.getvalue()


def deserialize_kv_payload(data):
    """bytes -> `import_prefix` payload (b"" -> None)."""
    if not data:
        return None
    with np.load(io.BytesIO(data)) as z:
        meta = json.loads(bytes(z[_META]).decode("utf-8"))
        kv_dtype = meta["kv_dtype"]
        n = len(meta["fills"])
        out = dict(meta)
        for side in ("k", "v"):
            blocks = []
            for i in range(n):
                if kv_dtype == "int8":
                    parts = {"codes": z[f"{side}{i}_codes"],
                             "scales": z[f"{side}{i}_scales"]}
                else:
                    parts = {"": z[f"{side}{i}"]}
                blocks.append(_unleaves(kv_dtype, parts))
            out[side] = blocks
    return out
