"""One fleet replica: a `PagedGenerationServer` plus its health state
and the probe surface the router reads (fleet round).

A replica owns its OWN engine, paged pool, optional journal and r15
ops plane — the router never reaches into engine internals except
through the replica-facing hooks (`submit`, `admit_journal_entry`,
`export_session`, `import_kv_payload`, `liveness`/`readiness`,
`cache.match_prefix_len`). Replicas here are in-process (each engine
already runs its own loop thread); the probe/dispatch surface is
deliberately the same one a subprocess replica would expose over
HTTP, so the router logic does not care which it is.
"""
from __future__ import annotations

import threading

from ..observability import log as _obs_log
from .health import ReplicaHealth

_logger = _obs_log.get_logger(__name__)


class Replica:
    """Router-facing wrapper of one serving engine.

    name: stable replica id — the `replica` label on federated
        metrics and the key in router stats.
    server: a NOT-yet-started `PagedGenerationServer` (the router
        starts and stops the fleet).
    health: a `ReplicaHealth` (default-constructed when omitted).
    """

    def __init__(self, name, server, health=None):
        self.name = str(name)
        self.server = server
        self.health = health if health is not None else ReplicaHealth()
        # causal tracing (ISSUE 14): the engine stamps its trace
        # events/spans/ring entries with this name, so the shared
        # in-process span sink still attributes per replica
        server.trace_name = self.name
        self._killed = False
        self._started = False
        self._lock = threading.Lock()

    # ---- lifecycle -----------------------------------------------------
    def start(self):
        with self._lock:
            if not self._started:
                self.server.start()
                self._started = True
        return self

    def stop(self):
        with self._lock:
            if self._started and not self._killed:
                self.server.stop()
            self._started = False

    def kill(self):
        """Crash-simulation: hard-stop the engine WITHOUT resolving
        its futures (`PagedGenerationServer.kill`) and mark the
        replica dead — the router's replica_kill seam and the chaos
        tests land here."""
        with self._lock:
            if self._killed:
                return
            self._killed = True
        self.health.mark_dead("killed")
        self.server.kill()
        _logger.warning("replica %s killed", self.name)

    @property
    def dead(self):
        return self._killed or self.health.state == "dead"

    # ---- probe surface -------------------------------------------------
    def liveness(self):
        if self._killed:
            return False, {"engine_running": False, "killed": True}
        return self.server.liveness()

    def readiness(self):
        if self._killed:
            return False, {"killed": True}
        return self.server.readiness()

    def set_draining(self, draining=True):
        """Drain toggle passthrough (the scale-down state machine's
        first step): readiness flips false while resident sessions
        keep decoding to completion."""
        self.server.set_draining(bool(draining))
        return self

    def load(self):
        """Instantaneous placement load: busy slots + queued requests
        (lock-free int reads — staleness only skews a tiebreak)."""
        srv = self.server
        busy = sum(1 for s in srv._slots if s is not None)
        sched = srv._sched
        try:
            depth = (sched.depth() if sched is not None
                     else len(srv._queue))
        except Exception:  # noqa: BLE001 — a torn-down scheduler
            depth = 0
        return busy + depth

    def queue_depth(self):
        srv = self.server
        try:
            return (srv._sched.depth() if srv._sched is not None
                    else len(srv._queue))
        except Exception:  # noqa: BLE001
            return 0

    def prefix_match_len(self, ids):
        """The placement signal: how many tokens of `ids` this
        replica's content-addressed cache already holds (0 when its
        prefix cache is off or it is dead)."""
        if self.dead or not self.server.enable_prefix_cache:
            return 0
        try:
            return self.server.cache.match_prefix_len(ids)
        except Exception:  # noqa: BLE001 — placement is advisory
            return 0

    def capacity(self):
        """This replica's versioned pressure snapshot (ISSUE 17) — the
        per-replica feed `FleetRouter.capacity()` federates. Raises on
        a dead replica; the federation layer converts that into the
        snapshot's `{"error": ...}` slot (dead-source tolerance)."""
        if self.dead:
            raise RuntimeError(f"replica {self.name} is dead")
        return self.server.capacity_snapshot()

    def metrics_text(self):
        """This replica's Prometheus page for federation. In-process
        replicas share the process registry (their per-pool series are
        disambiguated by the `pool` label); a subprocess replica would
        serve its own registry here — the federation layer treats both
        as opaque text."""
        from ..observability import metrics as _metrics

        return _metrics.REGISTRY.to_prometheus()

    def stats(self):
        live, _ = self.liveness()
        ready, _ = self.readiness()
        return {
            "name": self.name,
            "health": self.health.stats(),
            "live": live,
            "ready": ready,
            "load": 0 if self.dead else self.load(),
            "queue_depth": 0 if self.dead else self.queue_depth(),
        }
