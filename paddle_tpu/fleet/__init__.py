"""Resilient serving fleet (r18, ROADMAP item 4): replicated
`PagedGenerationServer` engines behind a failover `FleetRouter` with
journal-backed session takeover.

    from paddle_tpu.fleet import FleetRouter, Replica

    reps = [Replica(f"r{i}", PagedGenerationServer(
                model, enable_prefix_cache=True, ...))
            for i in range(4)]
    router = FleetRouter(reps, journal="fleet.journal").start()
    fut = router.submit(ids)                  # placed prefix-aware
    h = router.submit(ids, stream=True)       # survives replica death
    router.migrate_session(rid, target="r2")  # zero-recompute move
    router.stop()

A replica dying mid-stream is a recoverable, TESTED path: every
accepted request is journaled at the router (resolved seed, sampling,
every delivered token), the dead replica's sessions re-admit on
survivors via `PagedGenerationServer.admit_journal_entry`, and the
deterministic decode stack resumes them at PRNG step len(gen0) —
completed output is token-identical to a run that was never
interrupted. See docs/FLEET.md for the replica state machine, the
failover-vs-migration decision table, the parity guarantee and what
is NOT recoverable.
"""
from ..reliability import ReplicaUnavailable  # noqa: F401 (re-export)
from .autoscale import (Autoscaler, AutoscalePolicy,  # noqa: F401
                        ScaleDecision)
from .disagg import DisaggRouter, FleetLanes  # noqa: F401
from .federation import (add_label_to_prom_text,  # noqa: F401
                         federate_metrics, http_fetcher)
from .health import ReplicaHealth  # noqa: F401
from .migration import (deserialize_kv_payload,  # noqa: F401
                        serialize_kv_payload)
from .replica import Replica  # noqa: F401
from .router import FleetRouter  # noqa: F401
from .transport import RemoteEngine, RemoteReplica  # noqa: F401

__all__ = [
    "FleetRouter", "Replica", "ReplicaHealth", "ReplicaUnavailable",
    "RemoteEngine", "RemoteReplica", "DisaggRouter", "FleetLanes",
    "Autoscaler", "AutoscalePolicy", "ScaleDecision",
    "federate_metrics", "add_label_to_prom_text", "http_fetcher",
    "serialize_kv_payload", "deserialize_kv_payload",
]
