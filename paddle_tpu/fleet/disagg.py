"""Disaggregated prefill/decode pools + fleet-level SLO lanes (r22).

Real serving fleets burst on PREFILL (long prompts arriving together)
while decode throughput stays steady; one mixed pool lets a prefill
burst stall every resident decode stream. `DisaggRouter` splits the
fleet into a PREFILL pool and a DECODE pool so the two phases scale
independently:

  * fresh sessions place on the prefill pool (packed ragged prefill —
    the r8 chunk-plan seam — runs where prompts queue);
  * once a session's first token(s) stream, a dedicated handoff
    thread moves it to the least-loaded decode replica via the
    UNCHANGED r18 `migrate_session` — the session's published K/V
    chain crosses the wire as the r20 int8 codec bytes and
    warm-attaches on the decode side with ZERO prefill recompute;
  * failover, journaling and token parity are inherited untouched: a
    handoff IS a planned migration, so a crash at any point falls
    back to journal replay exactly like the r18 paths.

Placement steering happens entirely ABOVE the router's logic: the
subclass pins each `_dispatch`'s candidate set to the session's phase
pool (no tokens yet -> prefill, streaming -> decode) and degrades to
the whole fleet when the preferred pool has nothing routable — the
journal/failover/migration machinery is the base class's, unmodified.

`FleetLanes` composes the r12 `LaneScheduler` ABOVE placement:
fleet-wide tenant fairness / SLO lanes decide ADMISSION ORDER before
any replica is chosen, so an interactive request admits ahead of a
batch backlog regardless of which replica either would land on.
Requests wait in the lane queues until fleet slot capacity frees;
`AdmissionShed` from the router requeues (front) and retries.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future

import numpy as np

from ..observability import log as _obs_log
from ..observability import metrics as _metrics
from ..observability import tracing as _tracing
from ..reliability.errors import AdmissionShed
from .router import FleetRouter

_logger = _obs_log.get_logger(__name__)

_m_handoffs = _metrics.counter(
    "disagg_handoffs_total",
    "Prefill->decode session handoffs by outcome",
    labelnames=("outcome",))
_m_handoff_tokens = _metrics.counter(
    "disagg_handoff_kv_tokens_total",
    "KV-chain tokens moved prefill->decode over the migration wire")
_m_pool_size = _metrics.gauge(
    "disagg_pool_replicas", "Replicas per disaggregated pool",
    labelnames=("pool",))


class DisaggRouter(FleetRouter):
    """`FleetRouter` over two pools with phase-steered placement.

    prefill / decode: iterables of `Replica` (in-process or
        `RemoteReplica`) — names must be unique fleet-wide.
    handoff_after_tokens: tokens a session must have streamed before
        it moves to the decode pool (>= 1; the first token proves the
        prefill finished and the K/V chain is publishable).
    handoff_poll_s: handoff thread scan cadence.

    Every other kwarg is `FleetRouter`'s. The base class's journal,
    failover and migration logic run unchanged — this subclass only
    narrows placement candidates and drives planned migrations.
    """

    def __init__(self, prefill, decode, *, handoff_after_tokens=1,
                 handoff_poll_s=0.01, **kw):
        prefill = list(prefill)
        decode = list(decode)
        if not prefill or not decode:
            raise ValueError("DisaggRouter needs >= 1 prefill and "
                             ">= 1 decode replica")
        if int(handoff_after_tokens) < 1:
            raise ValueError(f"handoff_after_tokens must be >= 1, "
                             f"got {handoff_after_tokens}")
        super().__init__(prefill + decode, **kw)
        self.prefill_pool = frozenset(
            r.name for r in self.replicas[:len(prefill)])
        self.decode_pool = frozenset(
            r.name for r in self.replicas[len(prefill):])
        self.handoff_after_tokens = int(handoff_after_tokens)
        self.handoff_poll_s = float(handoff_poll_s)
        self._phase = threading.local()
        self._handed = set()          # rids already handed off
        self._handoffs_ok = 0
        self._handoffs_failed = 0
        self._handoffs_early = 0
        self._handoff_thread = None
        self._handoff_wake = threading.Event()
        if _metrics.enabled():
            _m_pool_size.labels(pool="prefill").set(len(prefill))
            _m_pool_size.labels(pool="decode").set(len(decode))

    # ---- lifecycle -----------------------------------------------------
    def start(self):
        super().start()
        # DEDICATED thread: a handoff exports K/V via run_host_op,
        # which deadlocks from an engine callback — never trigger a
        # migration from on_token
        self._handoff_thread = threading.Thread(
            target=self._handoff_loop, daemon=True,
            name="disagg-handoff")
        self._handoff_thread.start()
        return self

    def stop(self):
        self._stop = True
        self._handoff_wake.set()
        if self._handoff_thread is not None:
            self._handoff_thread.join(timeout=10)
            self._handoff_thread = None
        super().stop()

    # ---- phase-steered placement ---------------------------------------
    def _dispatch(self, sess, first=False):
        # a session with no tokens yet NEEDS a prefill wherever it
        # lands -> prefill pool; a streaming session is decode-phase
        # work (a failover re-prefills from the journal on the decode
        # side — availability over placement purity)
        self._phase.pool = (self.prefill_pool if not sess.toks
                            else self.decode_pool)
        try:
            return super()._dispatch(sess, first=first)
        finally:
            self._phase.pool = None

    def _place(self, ids, exclude=(), now=None):
        pool = getattr(self._phase, "pool", None)
        if pool:
            outside = {r for r in self.replicas if r.name not in pool}
            rep, match = super()._place(
                ids, exclude=set(exclude) | outside, now=now)
            if rep is not None:
                return rep, match
            # preferred pool has nothing routable: degrade to the
            # whole fleet rather than refuse (disagg is a perf
            # topology, not an availability boundary)
        return super()._place(ids, exclude=exclude, now=now)

    # ---- the prefill -> decode handoff ---------------------------------
    def _pick_decode(self, exclude=()):
        now = time.monotonic()
        pool = [r for r in self.replicas
                if r.name in self.decode_pool and r not in exclude
                and not r.dead
                and r.health.routing_weight(now) > 0.0]
        return min(pool, key=lambda r: r.load(), default=None)

    def _handoff_loop(self):
        while not self._stop:
            self._handoff_wake.wait(self.handoff_poll_s)
            self._handoff_wake.clear()
            if self._stop:
                return
            with self._lock:
                cands = [
                    s for s in self._sessions.values()
                    if not s.done and s.replica is not None
                    and s.replica.name in self.prefill_pool
                    and len(s.toks) >= self.handoff_after_tokens
                    and s.rid not in self._handed]
            for sess in cands:
                if self._stop:
                    return
                self._handoff(sess)

    def _handoff(self, sess):
        target = self._pick_decode(exclude={sess.replica})
        if target is None:
            return  # no decode capacity right now: retry next scan
        self._handed.add(sess.rid)
        with self._lock:
            source = sess.replica
            moved_tokens = len(sess.ids) + len(sess.toks)
        try:
            moved_to = self.migrate_session(sess.rid,
                                            target=target.name)
        except KeyError:
            # finished (or failed over) between the scan and now
            with self._lock:
                self._handoffs_early += 1
            if _metrics.enabled():
                _m_handoffs.labels(outcome="finished_early").inc()
            return
        except Exception as e:  # noqa: BLE001 — session still lives:
            # migrate_session's own fallbacks (journal replay,
            # failover) kept it running wherever it is
            with self._lock:
                self._handoffs_failed += 1
            if _metrics.enabled():
                _m_handoffs.labels(outcome="failed").inc()
            _logger.warning("disagg handoff of %s failed (%s)",
                            sess.rid, e)
            return
        with self._lock:
            self._handoffs_ok += 1
        if _metrics.enabled():
            _m_handoffs.labels(outcome="ok").inc()
            _m_handoff_tokens.inc(moved_tokens)
        _tracing.event(
            "disagg_handoff", request_id=sess.rid,
            source=source.name if source is not None else None,
            to=moved_to, kv_tokens=moved_tokens,
            **sess._tr(replica=moved_to))

    # ---- introspection -------------------------------------------------
    def stats(self):
        st = super().stats()
        with self._lock:
            st["disagg"] = {
                "prefill_pool": sorted(self.prefill_pool),
                "decode_pool": sorted(self.decode_pool),
                "handoffs": self._handoffs_ok,
                "handoffs_failed": self._handoffs_failed,
                "handoffs_finished_early": self._handoffs_early,
            }
        return st


class _LaneReq:
    """The light request shape `LaneScheduler` reads (meta, ids,
    budget, t_submit) plus what the dispatcher needs to forward it."""

    __slots__ = ("ids", "budget", "meta", "t_submit", "future",
                 "kwargs", "_fd_charged")

    def __init__(self, ids, budget, meta, kwargs):
        self.ids = ids
        self.budget = int(budget)
        self.meta = meta
        self.t_submit = time.perf_counter()
        self.future = Future()
        self.kwargs = kwargs
        self._fd_charged = False


class FleetLanes:
    """The r12 `LaneScheduler` composed ABOVE fleet placement.

    router: a started `FleetRouter` (or `DisaggRouter`).
    scheduler: a `frontend.LaneScheduler` (tenant configs, lane
        weights, rate buckets — all its policy knobs apply fleet-wide
        here).
    max_inflight: dispatched-but-unfinished cap; None = the fleet's
        total engine slots (sum of `max_slots`). Admission order is
        decided by the lanes while requests WAIT here — once
        dispatched, per-replica scheduling is the engine's own.

    `submit` returns a Future resolving exactly like
    `FleetRouter.submit`'s. Stop the composition (not the router)
    with `stop()`; queued-but-undispatched requests fail with
    RuntimeError.
    """

    def __init__(self, router, scheduler, *, max_inflight=None):
        self.router = router
        self.sched = scheduler
        self._max_inflight = (
            int(max_inflight) if max_inflight is not None
            else sum(r.server.max_slots for r in router.replicas))
        if self._max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._inflight = 0
        self._dispatched = 0
        self._shed_retries = 0
        self._stop = False
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="fleet-lanes")
        self._thread.start()
        return self

    def stop(self):
        self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        with self._lock:
            stranded = self.sched.drain()
        for req in stranded:
            req.future.set_exception(
                RuntimeError("fleet lanes stopped"))

    def submit(self, ids, max_new_tokens=None, sampling=None, *,
               meta=None, on_token=None, timeout_s=None,
               trace_ctx=None):
        if self._stop:
            raise RuntimeError("fleet lanes stopped")
        ids = np.asarray(ids, np.int32).reshape(-1)
        budget = max_new_tokens
        if budget is None and sampling is not None:
            budget = sampling.max_new_tokens
        if budget is None:
            budget = self.router.replicas[0].server.max_new
        req = _LaneReq(ids, budget, meta, {
            "max_new_tokens": max_new_tokens, "sampling": sampling,
            "on_token": on_token, "timeout_s": timeout_s,
            "trace_ctx": trace_ctx})
        with self._lock:
            # may raise QueueFull / unknown lane / unknown tenant —
            # eager, like the engine's own front door
            self.sched.on_submit(req, time.perf_counter())
        self._wake.set()
        return req.future

    # ---- dispatcher ----------------------------------------------------
    def _dispatch_loop(self):
        while not self._stop:
            self._wake.wait(0.02)  # rate buckets refill on wall time
            self._wake.clear()
            while not self._stop:
                now = time.perf_counter()
                with self._lock:
                    if self._inflight >= self._max_inflight:
                        break
                    req = self.sched.next_request(now)
                    if req is None:
                        break
                    self.sched.pop(req, now)
                    self._inflight += 1
                if not self._forward(req):
                    break

    def _forward(self, req):
        try:
            fut = self.router.submit(req.ids, meta=req.meta,
                                     **req.kwargs)
        except AdmissionShed:
            # the fleet itself is saturated: requeue at the FRONT
            # (its bucket charge is not repeated) and back off
            with self._lock:
                self._inflight -= 1
                self._shed_retries += 1
                self.sched.requeue(req, time.perf_counter())
            return False
        except BaseException as e:  # noqa: BLE001 — terminal reject
            with self._lock:
                self._inflight -= 1
            req.future.set_exception(e)
            return True
        with self._lock:
            self._dispatched += 1
        fut.add_done_callback(lambda f, r=req: self._done(r, f))
        return True

    def _done(self, req, fut):
        with self._lock:
            self._inflight -= 1
        self._wake.set()
        exc = fut.exception()
        if exc is not None:
            req.future.set_exception(exc)
        else:
            req.future.set_result(fut.result())

    def stats(self):
        with self._lock:
            return {
                "depth": self.sched.depth(),
                "lane_depths": self.sched.lane_depths(),
                "inflight": self._inflight,
                "max_inflight": self._max_inflight,
                "dispatched": self._dispatched,
                "shed_retries": self._shed_retries,
                "window": self.sched.window_stats(),
            }
