"""Deterministic SLO-driven autoscaler (ISSUE 20, ROADMAP item 3).

The actuation side of the r22 capacity bus: an `Autoscaler` control
loop on an EXPLICIT clock — the `TokenBucket`/`PressureSignals`
discipline — that each tick consumes ONE federated
`FleetRouter.capacity()` snapshot (pool headroom, blocks-exhaustion
ETA, queue depths, shed pressure, SLO burn rates, pre-aggregated in
the snapshot's `aggregate` block) and emits typed `ScaleDecision`s:

  * SCALE-UP   — spawn a replica (in-process or `RemoteReplica.spawn`)
    and `FleetRouter.add_replica()` it; the warm readiness gate means
    it is only routable once `warm_buckets()` provably ran, so a new
    replica never pays an XLA compile inside a request window.
  * SCALE-DOWN — pick the least-loaded replica FROM THE SNAPSHOT and
    `FleetRouter.retire_replica()` it: drain, migrate residents to
    best-prefix/least-loaded survivors over the existing migration
    wire (zero prefill recompute), retire. SIGKILL mid-drain degrades
    to the r18 journal failover token-identically.
  * REBALANCE  — KV/prefix-aware pressure relief: when a replica's
    blocks-exhaustion ETA (the r22 forecast) drops under the policy
    threshold, move up to `max_concurrent_migrations` of its resident
    sessions to the highest-headroom survivor BEFORE it sheds.

DETERMINISM is the load-bearing property: `decide()` is a pure
function of (policy, snapshot, internal hysteresis state) — it never
reads the router — so the same clock values + the same snapshots
reproduce the decision stream BYTE-IDENTICALLY (`Autoscaler.replay`
re-derives it from a recorded tick log with zero live engines). Every
tick records its `(now, snapshot)` input in `recorded` and every
decision appends one canonical JSON line to `decisions` (the decision
journal); actuation happens strictly AFTER journaling, so a crash
mid-tick loses at most actuations, never journal entries.

Policy is declarative (`AutoscalePolicy`): min/max replicas, headroom
and burn bands with separate up/down hysteresis tick counts and
cooldowns, a queue-per-slot trigger, and the rebalance ETA threshold.
What is NOT actuated here: per-lane admission (frontdoor), KV tier
demotion (kv_tier), disaggregated pool sizing — see docs/FLEET.md
"Elastic fleets".
"""
from __future__ import annotations

import dataclasses
import json
import threading
import time

from ..observability import log as _obs_log
from ..observability import metrics as _metrics
from ..observability import tracing as _tracing
from ..observability.capacity import fleet_aggregate
from .replica import Replica

_logger = _obs_log.get_logger(__name__)

_m_ticks = _metrics.counter(
    "autoscale_ticks_total",
    "autoscaler control-loop ticks (one capacity snapshot consumed "
    "per tick)")
_m_decisions = _metrics.counter(
    "autoscale_decisions_total",
    "autoscale decisions by action (hold included — the journal is "
    "the full stream)", labelnames=("action",))
_m_errors = _metrics.counter(
    "autoscale_errors_total",
    "decisions whose ACTUATION failed (the decision itself is "
    "journaled first and replays identically)")
_m_replicas = _metrics.gauge(
    "autoscale_replicas",
    "live replica count the last consumed snapshot reported")
_m_replica_seconds = _metrics.counter(
    "autoscale_replica_seconds_total",
    "replica-seconds metered from consumed snapshots (live replicas "
    "x tick interval — the bench's cost denominator)")
_m_migrations = _metrics.counter(
    "autoscale_migrations_total",
    "sessions moved by rebalance actuations (pressure-forecast "
    "relief, zero prefill recompute)")


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Declarative scaling policy. All thresholds read the snapshot's
    fleet `aggregate` block; hysteresis (`*_after` consecutive ticks)
    and per-direction cooldowns damp flapping.

    min_replicas / max_replicas: the fleet size band.
    up_headroom_frac: pressure when the worst replica's free-block
        fraction is <= this.
    up_burn: pressure when the worst SLO burn rate is >= this
        (budget-neutral burn is 1.0).
    up_queue_per_slot: pressure when summed queue depth / summed
        decode slots is >= this.
    down_headroom_frac / down_queue_per_slot: calm requires the worst
        headroom >= / queue pressure <= these (and no up-pressure).
    up_after / down_after: consecutive pressure/calm ticks before a
        scale decision fires.
    up_cooldown_s / down_cooldown_s: minimum spacing between same-
        direction decisions, on the loop's explicit clock.
    rebalance_eta_s: move sessions off a replica whose blocks-
        exhaustion ETA (r22 forecast) is <= this; None disables
        rebalancing.
    rebalance_headroom_frac: a rebalance target must have at least
        this free-block fraction.
    max_concurrent_migrations: session moves per rebalance actuation.
    """

    min_replicas: int = 1
    max_replicas: int = 4
    up_headroom_frac: float = 0.15
    up_burn: float = 2.0
    up_queue_per_slot: float = 1.0
    down_headroom_frac: float = 0.5
    down_queue_per_slot: float = 0.1
    up_after: int = 2
    down_after: int = 5
    up_cooldown_s: float = 10.0
    down_cooldown_s: float = 30.0
    rebalance_eta_s: float | None = None
    rebalance_headroom_frac: float = 0.3
    max_concurrent_migrations: int = 2

    def __post_init__(self):
        if int(self.min_replicas) < 1:
            raise ValueError(f"min_replicas must be >= 1, got "
                             f"{self.min_replicas}")
        if int(self.max_replicas) < int(self.min_replicas):
            raise ValueError(
                f"max_replicas must be >= min_replicas "
                f"({self.min_replicas}), got {self.max_replicas}")
        for fld in ("up_headroom_frac", "down_headroom_frac",
                    "rebalance_headroom_frac"):
            v = getattr(self, fld)
            if not 0.0 <= float(v) <= 1.0:
                raise ValueError(f"{fld} must be in [0, 1], got {v}")
        if float(self.down_headroom_frac) \
                < float(self.up_headroom_frac):
            raise ValueError(
                f"down_headroom_frac ({self.down_headroom_frac}) must "
                f"be >= up_headroom_frac ({self.up_headroom_frac}) — "
                f"the calm band may not overlap the pressure band")
        for fld in ("up_burn", "up_queue_per_slot",
                    "down_queue_per_slot"):
            if float(getattr(self, fld)) < 0.0:
                raise ValueError(f"{fld} must be >= 0, got "
                                 f"{getattr(self, fld)}")
        for fld in ("up_after", "down_after"):
            if int(getattr(self, fld)) < 1:
                raise ValueError(f"{fld} must be >= 1, got "
                                 f"{getattr(self, fld)}")
        for fld in ("up_cooldown_s", "down_cooldown_s"):
            if float(getattr(self, fld)) < 0.0:
                raise ValueError(f"{fld} must be >= 0, got "
                                 f"{getattr(self, fld)}")
        if self.rebalance_eta_s is not None \
                and float(self.rebalance_eta_s) <= 0.0:
            raise ValueError(f"rebalance_eta_s must be > 0 or None, "
                             f"got {self.rebalance_eta_s}")
        if int(self.max_concurrent_migrations) < 1:
            raise ValueError(
                f"max_concurrent_migrations must be >= 1, got "
                f"{self.max_concurrent_migrations}")


@dataclasses.dataclass(frozen=True)
class ScaleDecision:
    """One typed autoscale decision. `to_line()` is the CANONICAL
    journal encoding (sorted keys, fixed separators) — byte equality
    of lines is the replay-identity contract."""

    tick: int
    now: float
    action: str            # scale_up | scale_down | rebalance | hold
    replica: str | None    # spawned name / retire victim / source
    target: str | None     # rebalance destination
    reason: str

    def to_dict(self):
        return {"tick": self.tick, "now": self.now,
                "action": self.action, "replica": self.replica,
                "target": self.target, "reason": self.reason}

    def to_line(self):
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":"))


class Autoscaler:
    """The control loop. `router` may be None for pure replay (no
    actuation possible then).

    policy: an `AutoscalePolicy`.
    spawn: `spawn(name) -> Replica | engine` — builds the replica a
        scale-up admits (e.g. a `RemoteReplica.spawn` closure, or a
        fresh warmed in-process engine). None journals scale-up
        decisions but fails their actuation.
    clock: explicit injectable clock (default `time.monotonic`) —
        feed a fake clock for deterministic tests/replay.
    interval_s: the background thread's tick cadence (`start()`);
        `tick()` is the direct drive the benches/tests use.
    journal_path: optional file; every decision line is appended
        (the in-memory `decisions` list is always kept).
    """

    def __init__(self, router, policy=None, *, spawn=None, clock=None,
                 interval_s=1.0, journal_path=None):
        if policy is None:
            policy = AutoscalePolicy()
        if not isinstance(policy, AutoscalePolicy):
            raise TypeError(f"policy must be an AutoscalePolicy, got "
                            f"{type(policy).__name__}")
        if float(interval_s) <= 0.0:
            raise ValueError(f"interval_s must be > 0, "
                             f"got {interval_s}")
        self.router = router
        self.policy = policy
        self._spawn = spawn
        self._clock = clock or time.monotonic
        self.interval_s = float(interval_s)
        self._journal_path = journal_path
        self._lock = threading.RLock()
        # decision/control state (decide() is a pure function of this
        # + policy + snapshot; survives reset_stats)
        self._tick = 0
        self._up_ticks = 0
        self._down_ticks = 0
        self._last_up_t = None
        self._last_down_t = None
        self._last_rebalance_t = None
        self._auto_ids = 0           # deterministic spawned names
        self._last_now = None        # replica-seconds integration
        #: recorded (now, snapshot) tick inputs — the replay feed
        self.recorded: list = []
        #: canonical decision journal lines, in emission order
        self.decisions: list = []
        # window counters (reset_stats-coherent)
        self._w_ticks = 0
        self._w_decisions = 0
        self._scale_ups = 0
        self._scale_downs = 0
        self._rebalances = 0
        self._holds = 0
        self._errors = 0
        self._migrations = 0
        self._replica_seconds = 0.0
        self._last_decision = None
        # test seam: called between journal append and actuation (the
        # chaos gate kills the loop here — journaled, not actuated)
        self._seam_after_journal = None
        self._thread = None
        self._stop = False
        self._wake = threading.Event()
        if router is not None:
            router._autoscaler = self  # stats()["autoscale"] goes live

    # ---- control loop ---------------------------------------------------
    def start(self):
        """Run ticks on a background thread every `interval_s` (real
        deployments; tests and benches drive `tick()` explicitly)."""
        with self._lock:
            if self._thread is not None:
                return self
            if self._stop:
                raise RuntimeError("autoscaler stopped; build a new "
                                   "one")
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="paddle-tpu-autoscale")
            self._thread.start()
        return self

    def stop(self):
        self._stop = True
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=30)
            self._thread = None

    def _loop(self):
        while not self._stop:
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                _logger.exception("autoscale tick failed")
            self._wake.wait(timeout=self.interval_s)
            self._wake.clear()

    # ---- one tick --------------------------------------------------------
    def tick(self, now=None, snapshot=None):
        """Consume one capacity snapshot, journal the decisions, then
        actuate them. Returns the list of `ScaleDecision`s."""
        if self.router is None and snapshot is None:
            raise RuntimeError("no router: pass snapshot= explicitly")
        now = self._clock() if now is None else float(now)
        if snapshot is None:
            snapshot = self.router.capacity()
        with self._lock:
            self.recorded.append((now, snapshot))
            decisions = self._decide_locked(snapshot, now)
            for d in decisions:
                self.decisions.append(d.to_line())
            self._last_decision = decisions[-1].to_dict() \
                if decisions else None
        if self._journal_path is not None:
            with open(self._journal_path, "a") as f:
                for d in decisions:
                    f.write(d.to_line() + "\n")
        if _metrics.enabled():
            _m_ticks.inc()
            for d in decisions:
                _m_decisions.labels(action=d.action).inc()
        for d in decisions:
            _tracing.event("autoscale_decision", tick=d.tick,
                           action=d.action, replica=d.replica,
                           target=d.target, reason=d.reason)
        seam = self._seam_after_journal
        if seam is not None:
            seam(decisions)
        for d in decisions:
            if d.action == "hold":
                continue
            try:
                self.apply(d)
            except Exception as e:  # noqa: BLE001 — actuation failure
                # must not kill the loop; the journal already has the
                # decision and the next snapshot reflects reality
                with self._lock:
                    self._errors += 1
                if _metrics.enabled():
                    _m_errors.inc()
                _logger.warning("autoscale actuation %s failed: %s",
                                d.action, e)
        return decisions

    # ---- pure decision function ------------------------------------------
    def _decide_locked(self, snapshot, now):
        """Pure: (policy, snapshot, hysteresis state) -> decisions.
        Never reads the router — the replay-identity contract."""
        p = self.policy
        self._tick += 1
        self._w_ticks += 1
        replicas = snapshot.get("replicas") or {}
        agg = snapshot.get("aggregate")
        if agg is None:  # old-shape (schema v1) snapshot tolerance
            agg = fleet_aggregate(replicas)
        n = int(agg.get("replicas_ok") or 0)
        # replica-seconds metering: live replicas x elapsed
        if self._last_now is not None and now > self._last_now:
            dt = now - self._last_now
            self._replica_seconds += n * dt
            if _metrics.enabled():
                _m_replica_seconds.inc(n * dt)
        self._last_now = now
        if _metrics.enabled():
            _m_replicas.set(float(n))

        headroom = agg.get("min_headroom_frac")
        burn = agg.get("max_burn")
        q = int(agg.get("queue_depth_total") or 0)
        slots = int(agg.get("max_slots_total") or 0)
        qps = (q / slots) if slots > 0 else 0.0
        reasons = []
        if headroom is not None and headroom <= p.up_headroom_frac:
            reasons.append(f"headroom {headroom:.3f} "
                           f"<= {p.up_headroom_frac:g}")
        if burn is not None and burn >= p.up_burn:
            reasons.append(f"burn {burn:.3f} >= {p.up_burn:g}")
        if slots > 0 and qps >= p.up_queue_per_slot:
            reasons.append(f"queue/slot {qps:.3f} "
                           f">= {p.up_queue_per_slot:g}")
        pressure = bool(reasons)
        calm = (not pressure
                and (headroom is None
                     or headroom >= p.down_headroom_frac)
                and qps <= p.down_queue_per_slot)
        if pressure:
            self._up_ticks += 1
            self._down_ticks = 0
        elif calm:
            self._down_ticks += 1
            self._up_ticks = 0
        else:
            self._up_ticks = 0
            self._down_ticks = 0

        mk = lambda **kw: ScaleDecision(tick=self._tick, now=now, **kw)  # noqa: E731
        if (pressure and self._up_ticks >= p.up_after
                and n < p.max_replicas
                and (self._last_up_t is None
                     or now - self._last_up_t >= p.up_cooldown_s)):
            self._auto_ids += 1
            self._last_up_t = now
            self._up_ticks = 0
            d = mk(action="scale_up",
                   replica=f"auto{self._auto_ids}", target=None,
                   reason="; ".join(reasons))
            self._count_locked(d)
            return [d]
        if (calm and self._down_ticks >= p.down_after
                and n > p.min_replicas
                and (self._last_down_t is None
                     or now - self._last_down_t >= p.down_cooldown_s)):
            victim = self._pick_victim(replicas)
            if victim is not None:
                self._last_down_t = now
                self._down_ticks = 0
                d = mk(action="scale_down", replica=victim,
                       target=None,
                       reason=f"calm x{p.down_after}; headroom="
                              f"{'-' if headroom is None else round(headroom, 3)}"
                              f" queue/slot={round(qps, 3)}")
                self._count_locked(d)
                return [d]
        if p.rebalance_eta_s is not None:
            d = self._maybe_rebalance(replicas, now, mk)
            if d is not None:
                self._count_locked(d)
                return [d]
        d = mk(action="hold", replica=None, target=None,
               reason=(f"pressure x{self._up_ticks}" if pressure else
                       f"calm x{self._down_ticks}" if calm
                       else "neutral"))
        self._count_locked(d)
        return [d]

    @staticmethod
    def _snap_load(snap):
        """A replica's load as the SNAPSHOT reports it (busy slots +
        queue depth) — the victim/target ordering key."""
        queues = snap.get("queues")
        if not isinstance(queues, dict):
            return 0
        load = 0
        for k in ("busy_slots", "queue_depth"):
            v = queues.get(k)
            if isinstance(v, (int, float)):
                load += int(v)
        return load

    @staticmethod
    def _snap_headroom(snap):
        pool = snap.get("pool")
        if not isinstance(pool, dict):
            return None
        free, num = pool.get("free_blocks"), pool.get("num_blocks")
        if isinstance(free, (int, float)) \
                and isinstance(num, (int, float)) and num > 0:
            return free / num
        return None

    def _pick_victim(self, replicas):
        """Deterministic scale-down victim: the least-loaded live
        replica, name-ordered tiebreak — all from the snapshot."""
        live = [(self._snap_load(s), name)
                for name, s in sorted(replicas.items())
                if isinstance(s, dict) and "error" not in s]
        if not live:
            return None
        return min(live)[1]

    def _maybe_rebalance(self, replicas, now, mk):
        """KV/prefix-aware pressure relief: the live replica with the
        SOONEST blocks-exhaustion ETA under the threshold sheds
        sessions to the highest-headroom survivor."""
        p = self.policy
        if (self._last_rebalance_t is not None
                and now - self._last_rebalance_t < p.up_cooldown_s):
            return None
        worst = None  # (eta, name)
        for name, s in sorted(replicas.items()):
            if not isinstance(s, dict) or "error" in s:
                continue
            fc = s.get("forecast")
            eta = fc.get("exhaustion_eta_s") \
                if isinstance(fc, dict) else None
            if isinstance(eta, (int, float)) \
                    and eta <= p.rebalance_eta_s:
                if worst is None or (eta, name) < worst:
                    worst = (eta, name)
        if worst is None:
            return None
        source = worst[1]
        best = None  # (-headroom, load, name)
        for name, s in sorted(replicas.items()):
            if name == source or not isinstance(s, dict) \
                    or "error" in s:
                continue
            h = self._snap_headroom(s)
            if h is None or h < p.rebalance_headroom_frac:
                continue
            key = (-h, self._snap_load(s), name)
            if best is None or key < best:
                best = key
        if best is None:
            return None
        self._last_rebalance_t = now
        return mk(action="rebalance", replica=source, target=best[2],
                  reason=f"exhaustion eta {worst[0]:.3f}s "
                         f"<= {p.rebalance_eta_s:g}s")

    def _count_locked(self, d):
        self._w_decisions += 1
        if d.action == "scale_up":
            self._scale_ups += 1
        elif d.action == "scale_down":
            self._scale_downs += 1
        elif d.action == "rebalance":
            self._rebalances += 1
        else:
            self._holds += 1

    # ---- actuation --------------------------------------------------------
    def apply(self, decision):
        """Actuate one decision against the live router. Raises on
        failure (tick() converts that into the error counter)."""
        if self.router is None:
            raise RuntimeError("no router attached (replay-only "
                               "autoscaler)")
        act = decision.action
        if act == "scale_up":
            if self._spawn is None:
                raise RuntimeError("no spawn= callable: cannot "
                                   "actuate scale_up")
            built = self._spawn(decision.replica)
            rep = (built if isinstance(built, Replica)
                   else Replica(decision.replica, built))
            self.router.add_replica(rep)
            return rep
        if act == "scale_down":
            return self.router.retire_replica(decision.replica)
        if act == "rebalance":
            moved = 0
            with self.router._lock:
                residents = sorted(
                    s.rid for s in self.router._sessions.values()
                    if s.replica is not None
                    and s.replica.name == decision.replica
                    and not s.done)
            for rid in residents[:self.policy
                                 .max_concurrent_migrations]:
                try:
                    self.router.migrate_session(
                        rid, target=decision.target)
                    moved += 1
                except KeyError:
                    continue  # finished while we looked
            with self._lock:
                self._migrations += moved
            if _metrics.enabled() and moved:
                _m_migrations.inc(moved)
            return moved
        if act == "hold":
            return None
        raise ValueError(f"unknown decision action {act!r}")

    # ---- replay ----------------------------------------------------------
    @classmethod
    def replay(cls, policy, ticks):
        """Re-derive the decision stream from recorded `(now,
        snapshot)` tick inputs with ZERO live engines. Returns the
        canonical journal lines — byte-equal to the live run's
        `decisions` when the inputs match."""
        a = cls(None, policy)
        for now, snap in ticks:
            with a._lock:
                a.recorded.append((now, snap))
                for d in a._decide_locked(snap, now):
                    a.decisions.append(d.to_line())
        return list(a.decisions)

    # ---- introspection ---------------------------------------------------
    def reset_stats(self):
        """Zero the METERING window (stats_block). Control state —
        hysteresis counters, cooldown marks, the tick index, the
        journal — is deliberately kept: resetting stats must not
        change the decision stream."""
        with self._lock:
            self._w_ticks = 0
            self._w_decisions = 0
            self._scale_ups = 0
            self._scale_downs = 0
            self._rebalances = 0
            self._holds = 0
            self._errors = 0
            self._migrations = 0
            self._replica_seconds = 0.0
            self._last_decision = None

    def stats_block(self):
        """The router's `stats()["autoscale"]` block (keys mirror
        `router.AUTOSCALE_ZERO`, the zeroed-when-disabled shape)."""
        with self._lock:
            return {
                "enabled": True,
                "ticks": self._w_ticks,
                "decisions": self._w_decisions,
                "scale_ups": self._scale_ups,
                "scale_downs": self._scale_downs,
                "rebalances": self._rebalances,
                "holds": self._holds,
                "errors": self._errors,
                "migrations": self._migrations,
                "replica_seconds": self._replica_seconds,
                "last_decision": (dict(self._last_decision)
                                  if self._last_decision else None),
            }
