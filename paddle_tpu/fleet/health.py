"""Replica health state machine (fleet round, tentpole part a).

One `ReplicaHealth` per replica, driven by two signal classes:

  * ACTIVE probes — the router's probe loop calls the replica's split
    health surface (`liveness()` / `readiness()`, the r18 /healthz
    satellite) on an interval and feeds the outcome in;
  * PASSIVE dispatch outcomes — every routed request's completion or
    failure (`note_ok` / `note_failure`) updates the same state, so a
    replica that probes healthy but fails real traffic still opens.

States and routing weight:

    ok         weight 1.0      route normally
    degraded   weight w_d      >= 1 recent failure, not yet open —
                               route, but deprioritized
    open       weight 0.0      `open_after` consecutive failures —
                               circuit OPEN; after a capped-exponential
                               backoff the next `routable()` read
                               half-opens it
    half_open  weight eps      exactly ONE trial placement (or probe)
                               is allowed through; success -> ok,
                               failure -> open with doubled backoff
    not_ready  weight 0.0      the replica is alive but draining or
                               stalled (readiness false): route
                               nothing NEW, fail nothing over
    dead       weight 0.0      liveness failed / killed — terminal;
                               the router fails its sessions over

All transitions take an explicit `now` so the machine is deterministic
and unit-testable without sleeping; the router passes
`time.monotonic()`.
"""
from __future__ import annotations

import threading

STATES = ("ok", "degraded", "open", "half_open", "not_ready", "dead")


class ReplicaHealth:
    """Per-replica circuit breaker + routing weight.

    open_after: consecutive failures (probe or dispatch) that OPEN the
        circuit (>= 1).
    backoff_base_s / backoff_cap_s: capped exponential half-open probe
        schedule — open episode k waits min(cap, base * 2**(k-1))
        before allowing one trial.
    degraded_weight: routing weight while degraded (failures seen but
        the circuit has not opened).
    """

    def __init__(self, *, open_after=3, backoff_base_s=0.5,
                 backoff_cap_s=30.0, degraded_weight=0.25):
        if int(open_after) < 1:
            raise ValueError(f"open_after must be >= 1, "
                             f"got {open_after}")
        if float(backoff_base_s) <= 0:
            raise ValueError(f"backoff_base_s must be > 0, "
                             f"got {backoff_base_s}")
        if float(backoff_cap_s) < float(backoff_base_s):
            raise ValueError(
                f"backoff_cap_s ({backoff_cap_s}) must be >= "
                f"backoff_base_s ({backoff_base_s})")
        if not 0.0 < float(degraded_weight) <= 1.0:
            raise ValueError(f"degraded_weight must be in (0, 1], "
                             f"got {degraded_weight}")
        self.open_after = int(open_after)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.degraded_weight = float(degraded_weight)
        self._lock = threading.Lock()
        self._state = "ok"
        self._consecutive_failures = 0
        self._open_episodes = 0   # total times the circuit opened
        self._opened_at = None    # when the current open began
        self._trial_inflight = False
        self._transitions = 0
        self._last_failure = None  # short reason string

    # ---- introspection -------------------------------------------------
    @property
    def state(self):
        return self._state

    @property
    def consecutive_failures(self):
        return self._consecutive_failures

    @property
    def open_episodes(self):
        return self._open_episodes

    def _set(self, state):
        if state != self._state:
            self._state = state
            self._transitions += 1

    def backoff_s(self):
        """The current open episode's half-open wait."""
        k = max(1, self._open_episodes)
        return min(self.backoff_cap_s,
                   self.backoff_base_s * 2.0 ** (k - 1))

    # ---- signals -------------------------------------------------------
    def note_ok(self, now):
        """A successful dispatch or healthy+ready probe."""
        with self._lock:
            if self._state == "dead":
                return
            if self._state in ("half_open", "open"):
                # the trial (or a late success) closes the circuit
                self._open_episodes = 0
                self._opened_at = None
            self._trial_inflight = False
            self._consecutive_failures = 0
            self._last_failure = None
            self._set("ok")

    def note_failure(self, now, reason=""):
        """A failed dispatch, unreachable probe, or trial failure."""
        with self._lock:
            if self._state == "dead":
                return
            self._consecutive_failures += 1
            self._last_failure = str(reason) or None
            self._trial_inflight = False
            if self._state == "half_open" \
                    or self._consecutive_failures >= self.open_after:
                # re-open (doubling the backoff) or first open
                self._open_episodes += 1
                self._opened_at = float(now)
                self._set("open")
            else:
                self._set("degraded")

    def note_not_ready(self, now, reason=""):
        """An alive-but-not-accepting probe (draining / stalled):
        weight 0 without touching the failure streak or the circuit —
        when readiness returns, the prior state resumes via the next
        ok/failure signal."""
        with self._lock:
            if self._state in ("dead", "open", "half_open"):
                return
            self._last_failure = str(reason) or None
            self._set("not_ready")

    def mark_dead(self, reason=""):
        with self._lock:
            self._last_failure = str(reason) or self._last_failure
            self._set("dead")

    # ---- routing -------------------------------------------------------
    def routing_weight(self, now):
        """The router's placement weight RIGHT NOW. Reading this can
        half-open an open circuit whose backoff has elapsed: the next
        read returns a small trial weight exactly once — the single
        in-flight trial the half-open contract allows."""
        with self._lock:
            if self._state in ("dead", "not_ready"):
                return 0.0
            if self._state == "ok":
                return 1.0
            if self._state == "degraded":
                return self.degraded_weight
            if self._state == "open":
                if float(now) - self._opened_at >= self.backoff_s():
                    self._set("half_open")
                else:
                    return 0.0
            # half_open: one trial at a time
            if self._trial_inflight:
                return 0.0
            self._trial_inflight = True
            return 1e-3

    def probe_due(self, now):
        """Whether an ACTIVE probe should run now: always, except
        while the circuit is open and the backoff has not elapsed
        (capped-backoff half-open probing — the router's probe loop
        asks this before touching an open replica)."""
        with self._lock:
            if self._state == "dead":
                return False
            if self._state == "open":
                return float(now) - self._opened_at >= self.backoff_s()
            return True

    def stats(self):
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "open_episodes": self._open_episodes,
                "backoff_s": (self.backoff_s()
                              if self._open_episodes else 0.0),
                "transitions": self._transitions,
                "last_failure": self._last_failure,
            }
