"""/metrics federation over per-replica exporters (fleet round,
tentpole part d).

Each replica runs the r15 ops plane and exposes its own Prometheus
text at /metrics; the fleet front door serves ONE merged page where
every per-replica sample carries a `replica="<name>"` label — the
standard federation shape, so one scrape of the router sees the whole
fleet. `# HELP` / `# TYPE` comment lines are deduplicated (first
source wins); fleet-level series (`fleet_*`, already labeled where it
matters) are appended once, unrelabeled.

The rewriting is textual on the exposition format — it works over any
source (an in-process registry snapshot or an HTTP fetch from a
subprocess replica) without importing its registry.
"""
from __future__ import annotations

import urllib.request

_ESC = {"\\": "\\\\", '"': '\\"', "\n": "\\n"}


def _escape(value):
    return "".join(_ESC.get(c, c) for c in str(value))


def add_label_to_prom_text(text, label, value):
    """Inject `label="value"` into every SAMPLE line of a Prometheus
    text page (comments and blank lines pass through untouched).
    Handles both bare (`name 1.0`) and labeled
    (`name{a="b"} 1.0`) samples, including histogram `_bucket`
    series."""
    lv = f'{label}="{_escape(value)}"'
    out = []
    for line in text.splitlines():
        s = line.strip()
        if not s or s.startswith("#"):
            out.append(line)
            continue
        brace = line.find("{")
        space = line.find(" ")
        if brace != -1 and (space == -1 or brace < space):
            out.append(line[:brace + 1] + lv + ","
                       + line[brace + 1:])
        elif space != -1:
            out.append(line[:space] + "{" + lv + "}" + line[space:])
        else:  # not a sample line; pass through
            out.append(line)
    return "\n".join(out)


def federate_metrics(sources, extra=""):
    """Merge per-replica Prometheus pages into one federated page.

    sources: iterable of (replica_name, text_or_fetcher) — a str of
        Prometheus text, or a zero-arg callable returning one (an
        unreachable source contributes a comment line instead of
        failing the whole page).
    extra: fleet-level text appended verbatim at the end (the
        router's own `fleet_*` series).
    """
    out = []
    seen_comments = set()
    for name, src in sources:
        try:
            text = src() if callable(src) else str(src)
        except Exception as e:  # noqa: BLE001 — one dead replica must
            # not take down the whole federated page
            out.append(f"# replica {name}: unreachable "
                       f"({type(e).__name__}: {e})")
            continue
        labeled = add_label_to_prom_text(text, "replica", name)
        for line in labeled.splitlines():
            if line.startswith("#"):
                if line in seen_comments:
                    continue
                seen_comments.add(line)
            out.append(line)
    if extra:
        for line in str(extra).splitlines():
            if line.startswith("#") and line in seen_comments:
                continue
            out.append(line)
    return "\n".join(out) + "\n"


def http_fetcher(url, timeout=2.0):
    """A zero-arg /metrics fetcher for a subprocess/remote replica's
    exporter URL (the in-process default reads the registry
    directly)."""
    def fetch():
        with urllib.request.urlopen(f"{url}/metrics",
                                    timeout=timeout) as r:
            return r.read().decode("utf-8")
    return fetch
