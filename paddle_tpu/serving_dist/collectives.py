"""Quantized TP collectives for the sharded decode hot path.

Sharded serving (serving_dist round) pays one compute-dtype all-reduce
per half-block (row-split out_proj / fc2), one for the vocab-parallel
embedding gather, and a vocab-parallel f32 all-gather of the head
logits per sampled token — at tp degrees worth running, inter-chip
bytes are the dominant un-optimized cost of the decode loop (EQuARX,
PAPERS.md: XLA-level quantized all-reduce reaches ~2x collective
speedup at negligible quality loss; the training side already ships
`distributed.collective.quantized_all_reduce` for DCN gradient rings —
this module is the serving analogue, inside the jitted decode programs).

Mechanism: every quantized collective is an explicit `shard_map` seam
over the mesh's `mp` axis, so the SPMD partitioner has zero freedom
inside it (the r14 lesson — the pinned toolchain miscompiles when the
sort/argmax pipeline is left shardable; an explicit per-device body
cannot be re-partitioned):

  * `matmul_psum` — the row-split projections' reduction. Each shard
    computes its partial [rows, E] product, quantizes it with
    PER-CHUNK symmetric absmax scales (chunk = the E/tp slice that
    all_to_all routes to its owning shard; int4-group mode additionally
    groups scales every `int4_group` lanes and packs two codes per
    byte), ships codes+scales via all_to_all, dequantizes and SUMS IN
    f32 on the owner (one quantization error per value, not log(n)),
    re-quantizes the reduced chunk once, and all_gathers codes+scales
    back. Wire bytes: 2*(n-1)/n * rows*E at 1 (int8) or 0.5 (int4)
    byte/element + scales, vs 2*(n-1)/n * rows*E * 2 (bf16) — ~0.5x /
    ~0.25x plus a few percent of scales.
  * `embed_psum` — the vocab-parallel embedding's psum, same wire
    format: each shard gathers the token rows its vocab slice holds
    (others contribute zeros) and the partials reduce quantized.
  * `greedy_tokens` — the all-greedy fast path never ships logits at
    all: each shard argmaxes its OWN vocab slice and the shards
    exchange (max, global index) pairs — 8 bytes per row per peer
    instead of 4*V/tp; the combine reproduces `jnp.argmax`'s
    first-index tie-break exactly, so this seam is LOSSLESS (the
    greedy token equals the one computed from gathered f32 logits).
  * `gather_logits` — sampled/penalty modes and return_logits
    dispatches need the full [rows, V] row; the codes+scales
    all-gather ships 1 (0.5) byte/element instead of f32's 4.

What is NOT quantized: the dp-axis traffic (pure placement — bitwise,
no values cross a reduction), the block-table/host-input broadcasts,
and any collective XLA inserts outside these seams. A mesh whose tp
does not divide the vocab keeps its logits replicated (plan._fit
dropped the wte sharding) — the logits seams then trace to the
identity and account zero bytes, exactly like the baseline.

Byte accounting is HOST-SIDE and analytic: the wire formulas below
mirror the seam implementations element-for-element, and the decoder
increments `serving_collective_bytes_total{collective,dtype}` per
dispatch for BOTH the path actually traced and the bf16 baseline the
same dispatch would have shipped, so a bench record's bytes ratio
needs no device instrumentation.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

from ..observability import metrics as _metrics

MODES = ("int8", "int4g")

# collective names the byte accounting + metrics label with
ROW_PSUM = "row_psum"
EMBED_PSUM = "embed_psum"
LOGITS_GATHER = "logits_gather"
LOGITS_ARGMAX = "logits_argmax"

_SCALE_BYTES = 4  # scales ship f32

_m_collective_bytes = _metrics.counter(
    "serving_collective_bytes_total",
    "analytic per-device wire bytes of the sharded decode collectives "
    "(dtype=baseline is what the unquantized collectives would ship "
    "for the same dispatches)",
    labelnames=("collective", "dtype"))


def record_wire_bytes(bytes_by_key):
    """Emit one dispatch's {(collective, dtype): bytes} accounting to
    the process-wide metrics registry (one bool check when telemetry
    is off — the PagedDecoder keeps its own window dict regardless)."""
    if not _metrics.enabled():
        return
    for (name, dtype), nbytes in bytes_by_key.items():
        _m_collective_bytes.labels(collective=name, dtype=dtype).inc(
            nbytes)


def _require(cond, msg):
    if not cond:
        raise ValueError(msg)


def normalize_collective_quant(mode):
    """Eager validation of the `collective_quant` config value (None
    passes through: the exact pre-round program)."""
    if mode is not None and mode not in MODES:
        raise ValueError(
            f"ShardedEngineConfig.collective_quant={mode!r} must be one "
            f"of {(None,) + MODES}")
    return mode


# ---------------------------------------------------------------------------
# quantize / dequantize primitives (pure jnp; shard_map bodies call these)
# ---------------------------------------------------------------------------

def group_size(width, group):
    """Effective scale-group width: the configured group snapped to a
    divisor of `width` (gcd — worst case per-element scales, never a
    ragged tail)."""
    return math.gcd(int(width), int(group)) or 1


def encode_int8(x, group=None):
    """[..., C] -> (int8 codes [..., C], f32 scales [..., C/g]).
    Symmetric absmax per scale group; group=None means ONE scale per
    trailing vector (the per-chunk layout of the psum wire)."""
    import jax.numpy as jnp

    C = x.shape[-1]
    g = C if group is None else group_size(C, group)
    xg = x.astype(jnp.float32).reshape(x.shape[:-1] + (C // g, g))
    amax = jnp.max(jnp.abs(xg), axis=-1, keepdims=True)
    sc = jnp.maximum(amax, 1e-12) / 127.0
    codes = jnp.clip(jnp.round(xg / sc), -127, 127).astype(jnp.int8)
    return codes.reshape(x.shape), sc.squeeze(-1)


def decode_int8(codes, scales, group=None):
    """Inverse of encode_int8 -> f32."""
    import jax.numpy as jnp

    C = codes.shape[-1]
    g = C if group is None else group_size(C, group)
    cg = codes.reshape(codes.shape[:-1] + (C // g, g))
    return (cg.astype(jnp.float32)
            * scales[..., None]).reshape(codes.shape)


def encode_int4(x, group):
    """[..., C] -> (packed uint8 codes [..., C/2], f32 scales
    [..., C/g]). Two's-complement nibbles in [-7, 7], two per byte
    (even lane low nibble); C must be even (every seam width here is a
    multiple of tp and of 2)."""
    import jax.numpy as jnp

    C = x.shape[-1]
    _require(C % 2 == 0, f"int4 packing needs an even width, got {C}")
    g = group_size(C, group)
    xg = x.astype(jnp.float32).reshape(x.shape[:-1] + (C // g, g))
    amax = jnp.max(jnp.abs(xg), axis=-1, keepdims=True)
    sc = jnp.maximum(amax, 1e-12) / 7.0
    q = jnp.clip(jnp.round(xg / sc), -7, 7).astype(
        jnp.int8).reshape(x.shape)
    packed = ((q[..., 0::2] & 0xF)
              | ((q[..., 1::2] & 0xF) << 4)).astype(jnp.uint8)
    return packed, sc.squeeze(-1)


def decode_int4(packed, scales, group, width):
    """Inverse of encode_int4 -> f32 [..., width]."""
    import jax.numpy as jnp

    lo = (packed & 0xF).astype(jnp.int8)
    hi = ((packed >> 4) & 0xF).astype(jnp.int8)
    lo = jnp.where(lo > 7, lo - 16, lo)
    hi = jnp.where(hi > 7, hi - 16, hi)
    q = jnp.stack([lo, hi], axis=-1).reshape(
        packed.shape[:-1] + (width,))
    g = group_size(width, group)
    qg = q.reshape(q.shape[:-1] + (width // g, g))
    return (qg.astype(jnp.float32)
            * scales[..., None]).reshape(q.shape)


def _wire_encode(x, mode, group):
    """(codes, scales) for one wire hop. int8 ships one scale per
    trailing vector (per-chunk); int4g ships group scales and packed
    nibbles."""
    if mode == "int8":
        return encode_int8(x)
    return encode_int4(x, group)


def _wire_decode(codes, scales, mode, group, width):
    if mode == "int8":
        return decode_int8(codes, scales)
    return decode_int4(codes, scales, group, width)


# ---------------------------------------------------------------------------
# wire-byte formulas (host-side accounting — mirror the seams exactly)
# ---------------------------------------------------------------------------

def _hop_bytes(nvec, width, mode, group):
    """Bytes of codes+scales for `nvec` vectors of `width` lanes on ONE
    wire hop (before the (n-1)/n routing fraction)."""
    if mode == "int8":
        return nvec * width + nvec * _SCALE_BYTES
    g = group_size(width, group)
    return nvec * width // 2 + nvec * (width // g) * _SCALE_BYTES


def psum_wire_bytes(nrows, width, tp, mode, group, base_itemsize):
    """(actual, baseline) per-device wire bytes of ONE all-reduce over
    a [nrows, width] partial. Baseline = the ring all-reduce XLA
    emits: 2*(n-1)/n * data. Quantized = all_to_all (codes+scales of
    tp chunks) + all_gather of the re-quantized owned chunk."""
    if tp <= 1:
        return 0, 0
    base = int(2 * (tp - 1) * nrows * width * base_itemsize // tp)
    if mode is None:
        return base, base
    chunk = width // tp
    # phase 1: all_to_all routes (tp-1)/tp of the [nrows, tp, chunk]
    # code+scale set; phase 2: each shard sends its reduced chunk's
    # codes+scales to tp-1 peers
    p1 = _hop_bytes(nrows * tp, chunk, mode, group) * (tp - 1) // tp
    p2 = _hop_bytes(nrows, chunk, mode, group) * (tp - 1)
    return int(p1 + p2), base


def gather_wire_bytes(nrows, vocab, tp, mode, group):
    """(actual, baseline) per-device wire bytes of the vocab-parallel
    logits all-gather ([nrows, vocab] f32 baseline; codes+scales of
    the local [nrows, vocab/tp] slice quantized)."""
    if tp <= 1 or vocab % tp:
        return 0, 0
    base = int((tp - 1) * nrows * vocab * 4 // tp)
    if mode is None:
        return base, base
    return int(_hop_bytes(nrows, vocab // tp, mode, group)
               * (tp - 1)), base


def argmax_wire_bytes(nrows, vocab, tp):
    """(actual, baseline) per-device wire bytes of the greedy
    fast path: each row ships one (f32 max, int32 global index) pair
    per peer instead of the f32 logits row."""
    if tp <= 1 or vocab % tp:
        return 0, 0
    base = int((tp - 1) * nrows * vocab * 4 // tp)
    return int((tp - 1) * nrows * 8), base


# ---------------------------------------------------------------------------
# the CollectiveQuant bundle (static, hashable — part of every builder key)
# ---------------------------------------------------------------------------

@dataclass(frozen=True, eq=False)
class CollectiveQuant:
    """The static quantized-collectives spec one sharded PagedDecoder
    traces with. Hashable (jax Mesh hashes structurally), so the
    lru-cached program builders key on it like on `mode`/`kv_quant`;
    `None` stays the exact pre-round program."""

    mode: str            # "int8" | "int4g"
    tp: int
    mesh: object         # jax Mesh
    group: int = 32      # int4-group scale width
    axis: str = "mp"

    def __post_init__(self):
        _require(self.mode in MODES,
                 f"CollectiveQuant.mode={self.mode!r} must be one of "
                 f"{MODES}")
        _require(isinstance(self.tp, int) and self.tp > 1,
                 f"CollectiveQuant.tp={self.tp!r} must be an int > 1 "
                 f"(tp=1 has no wire — pass collective_quant=None)")
        _require(isinstance(self.group, int) and self.group >= 1,
                 f"CollectiveQuant.group={self.group!r} must be a "
                 f"positive int")

    # Mesh objects compare by devices+axes; include shape in the hash
    # but not the device list (two servers on equal meshes share jits
    # via DecodeShardings equality anyway — this only needs to be
    # stable and hashable)
    def __hash__(self):
        return hash((self.mode, self.tp, self.group, self.axis,
                     tuple(dict(self.mesh.shape).items())))

    def __eq__(self, other):
        return (isinstance(other, CollectiveQuant)
                and self.mode == other.mode and self.tp == other.tp
                and self.group == other.group and self.axis == other.axis
                and self.mesh == other.mesh)

    # -- traced seams ---------------------------------------------------

    def _shard_map(self, body, in_specs, out_specs):
        from jax.experimental.shard_map import shard_map

        return shard_map(body, mesh=self.mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)

    def _quantized_psum(self, x):
        """shard_map BODY helper: all_to_all + dequant-sum + all_gather
        of one per-shard partial [..., width]; returns the reduced
        array in x.dtype."""
        import jax
        import jax.numpy as jnp

        n, ax = self.tp, self.axis
        width = x.shape[-1]
        lead = x.shape[:-1]
        xr = jnp.moveaxis(x.reshape(lead + (n, width // n)), -2, 0)
        codes, sc = _wire_encode(xr, self.mode, self.group)
        codes = jax.lax.all_to_all(codes, ax, split_axis=0,
                                   concat_axis=0)
        sc = jax.lax.all_to_all(sc, ax, split_axis=0, concat_axis=0)
        part = _wire_decode(codes, sc, self.mode, self.group,
                            width // n).sum(axis=0)
        codes2, sc2 = _wire_encode(part, self.mode, self.group)
        codes2 = jax.lax.all_gather(codes2, ax)
        sc2 = jax.lax.all_gather(sc2, ax)
        full = _wire_decode(codes2, sc2, self.mode, self.group,
                            width // n)
        return jnp.moveaxis(full, 0, -2).reshape(
            lead + (width,)).astype(x.dtype)

    def _specs(self, ndim_x, P):
        """(x_spec, w_spec, out_spec) for a row-split matmul seam over
        an [..., K] activation and a [K, N] weight."""
        x_spec = P(*([None] * (ndim_x - 1) + [self.axis]))
        w_spec = P(self.axis, None)
        out_spec = P(*([None] * ndim_x))
        return x_spec, w_spec, out_spec

    def matmul_psum(self, x, w, cast=None):
        """Row-split projection with a quantized reduction: x [..., K]
        (K sharded over mp), w [K, N] (row-sharded) -> replicated
        [..., N]. `cast` applies to the weight INSIDE the body (the
        W8A16 codes->compute-dtype cast of `matw`); the per-output-
        column scale epilogue stays outside (it applies after the
        reduction — replicated, free)."""
        from jax.sharding import PartitionSpec as P

        x_spec, w_spec, out_spec = self._specs(x.ndim, P)

        def body(x_loc, w_loc):
            if cast is not None:
                w_loc = w_loc.astype(cast)
            return self._quantized_psum(x_loc @ w_loc)

        return self._shard_map(body, (x_spec, w_spec), out_spec)(x, w)

    def embed_psum(self, ids, table, scales=None, dt=None):
        """Vocab-parallel embedding with a quantized psum: ids [...]
        int32, table [V, E] row-sharded over mp (W8A16: int8 codes plus
        per-row `scales` [V]). Each shard contributes the rows its
        vocab slice holds; the partials reduce through the quantized
        wire. Returns [..., E] replicated in `dt` (or table dtype)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        id_spec = P(*([None] * ids.ndim))
        tab_spec = P(self.axis, None)
        out_spec = P(*([None] * (ids.ndim + 1)))
        args = (ids, table) + ((scales,) if scales is not None else ())
        in_specs = (id_spec, tab_spec) + (
            (P(self.axis),) if scales is not None else ())

        def body(ids_loc, tab_loc, *rest):
            vs = tab_loc.shape[0]
            off = jax.lax.axis_index(self.axis) * vs
            loc = ids_loc - off
            ok = (loc >= 0) & (loc < vs)
            rows = tab_loc[jnp.clip(loc, 0, vs - 1)]
            if rest:  # W8A16 codes: dequant the gathered rows
                rows = rows.astype(dt) \
                    * rest[0][jnp.clip(loc, 0, vs - 1)][..., None] \
                    .astype(dt)
            elif dt is not None:
                rows = rows.astype(dt)
            part = jnp.where(ok[..., None], rows, 0)
            return self._quantized_psum(part)

        return self._shard_map(body, in_specs, out_spec)(*args)

    def greedy_tokens(self, logits):
        """LOSSLESS vocab-parallel argmax over mp-sharded [R, V] f32
        logits: per-shard (max, first-index) pairs exchanged instead of
        logits rows. Reproduces `jnp.argmax`'s first-index tie-break
        (global max, then smallest global index). Caller guarantees
        V % tp == 0 (checked at trace time by `vocab_sharded`)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        V = logits.shape[-1]

        def body(lg):
            vs = lg.shape[-1]
            gi = (jnp.argmax(lg, axis=-1)
                  + jax.lax.axis_index(self.axis) * vs)
            vals = jax.lax.all_gather(jnp.max(lg, axis=-1), self.axis)
            idxs = jax.lax.all_gather(gi, self.axis)        # [n, R]
            gmax = vals.max(axis=0)
            cand = jnp.where(vals >= gmax[None], idxs, V)
            return cand.min(axis=0).astype(jnp.int32)

        return self._shard_map(body, (P(None, self.axis),),
                               P(None))(logits)

    def gather_logits(self, logits):
        """Quantized vocab-parallel all-gather: mp-sharded [R, V] f32
        -> replicated f32 through the codes+scales wire (per-row
        scales under int8, per-group under int4g). Caller guarantees
        V % tp == 0."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        n = self.tp
        V = logits.shape[-1]

        def body(lg):
            codes, sc = _wire_encode(lg, self.mode, self.group)
            codes = jax.lax.all_gather(codes, self.axis)
            sc = jax.lax.all_gather(sc, self.axis)
            full = _wire_decode(codes, sc, self.mode, self.group,
                                V // n)
            return jnp.concatenate([full[i] for i in range(n)],
                                   axis=-1)

        return self._shard_map(body, (P(None, self.axis),),
                               P(None, None))(logits)

    def vocab_sharded(self, vocab):
        """Whether the plan actually shards this vocab (plan._fit drops
        indivisible dims to replicated — then there is no logits
        collective to quantize OR to count)."""
        return int(vocab) % self.tp == 0


def build_collective_quant(cfg, mesh):
    """The engine-side constructor: a ShardedEngineConfig whose
    `collective_quant` is set and whose tp > 1 yields a CollectiveQuant
    over the server's mesh; anything else yields None (tp=1 has no
    inter-chip wire — quantizing it would only perturb numerics)."""
    mode = normalize_collective_quant(
        getattr(cfg, "collective_quant", None))
    if mode is None or cfg.tp <= 1:
        return None
    return CollectiveQuant(mode=mode, tp=cfg.tp, mesh=mesh,
                           group=getattr(cfg, "int4_group", 32))


# ---------------------------------------------------------------------------
# per-dispatch accounting (host side)
# ---------------------------------------------------------------------------

def dispatch_wire_bytes(*, spec, vocab, tp, mode, group, trunk_rows,
                        logit_rows, greedy_fast, base_itemsize):
    """{(collective, dtype): bytes} one decode dispatch ships, for the
    ACTUAL path (`mode` None = unquantized) alongside the bf16
    baseline under the "baseline" dtype key. trunk_rows = token rows
    through the transformer trunk (2L row psums of [rows, E] plus one
    embed psum); logit_rows = head readout rows; greedy_fast = the
    all-greedy argmax seam replaced the logits gather."""
    L, _H, _Dh, E, _eps, _tied = spec
    out = {}
    dtype = mode or "base"

    def add(name, actual, baseline):
        if baseline or actual:
            out[(name, dtype)] = out.get((name, dtype), 0) + actual
            out[(name, "baseline")] = (out.get((name, "baseline"), 0)
                                       + baseline)

    a, b = psum_wire_bytes(trunk_rows, E, tp, mode, group,
                           base_itemsize)
    add(ROW_PSUM, a * 2 * L, b * 2 * L)
    if int(vocab) % tp == 0:
        a, b = psum_wire_bytes(trunk_rows, E, tp, mode, group,
                               base_itemsize)
        add(EMBED_PSUM, a, b)
        if greedy_fast and mode is not None:
            a, b = argmax_wire_bytes(logit_rows, vocab, tp)
            add(LOGITS_ARGMAX, a, b)
        else:
            a, b = gather_wire_bytes(logit_rows, vocab, tp, mode, group)
            add(LOGITS_GATHER, a, b)
    return out
