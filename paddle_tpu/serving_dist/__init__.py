"""Sharded serving: the paged engine tensor-parallel on the mesh.

ROADMAP item 1 — the serving engine of PRs 1-8 (paged KV + continuous
batching, packed chunked prefill, prefix cache, per-request sampling,
speculation, front door, W8A16/int8-KV) runs on ONE device; the
training stack already proves 4D dp/pp/mp/sp parallelism with loss
parity.  This subsystem closes that gap for the DECODE side: it shards
the existing engine's weights and KV block pool over a
`jax.sharding.Mesh` (built by `parallel/mesh.py`, the canonical
dp/pp/mp/sp axes — serving uses `mp` for tensor parallel and `dp` for
the pool's block axis) and jits the UNCHANGED decode programs
(`nn/decode.py` prefill / step / packed_prefill / packed_verify) with
explicit in/out shardings, so XLA inserts exactly the two TP
collectives per layer family the training TP path already schedules
(all-reduce after the row-split out_proj/fc2 contractions, all-gather
of the vocab-sharded logits at the head).

The design invariant: sharding is a PLACEMENT property, not an engine
property.

  * Block tables, sequence lengths, refcounts, the prefix-cache index,
    admission reservations — every piece of host bookkeeping in
    `PagedKVCache` — stay replicated host state, untouched.  The pool's
    DEVICE arrays shard over the head axis (tp) and optionally the
    block axis (dp), so prefix publish/attach, copy-on-write, swap-out,
    truncate and the int8 scale buffers all keep working: they only
    ever name block INDICES, and every shard holds its head-slice of
    every block.
  * The decode programs are the same traced functions; a 1-device mesh
    compiles the identical program and is bitwise-identical to the
    unsharded engine (tested).
  * Composition is free: quantization (w8a16 + int8 KV), speculation,
    per-request sampling invariance and the FrontDoor run unchanged on
    the sharded engine — their state is host-side or replicated.

Use:

    from paddle_tpu.serving_dist import ShardedEngineConfig
    server = PagedGenerationServer(model,
                                   sharding=ShardedEngineConfig(tp=4))

Development and CPU validation run on forced host devices
(`XLA_FLAGS=--xla_force_host_platform_device_count=N`, the multichip
dryrun trick; `scripts/run_mesh_tests.sh` wraps it).  Importing this
package pulls nothing heavy — `inference/serving.py` imports it lazily
and only when `sharding=` is actually given.
"""
from __future__ import annotations

from .config import (ShardedEngineConfig, disabled_stats_block,
                     normalize_sharding)
from .plan import (DecodeShardings, build_decode_shardings,
                   decode_spec_for, kv_pool_specs, place_decode_params,
                   place_kv_pool)
from .engine import (apply_sharding, max_slots_for_budget,
                     pool_blocks_for_budget)
from .collectives import (CollectiveQuant, build_collective_quant,
                          normalize_collective_quant)
from .config import SP_ATTENTION_MODES
from .sp_attention import (build_sp_fresh_attention,
                           sp_attention_flat_bound,
                           sp_attention_peak_bytes)

__all__ = [
    "ShardedEngineConfig", "normalize_sharding", "disabled_stats_block", "DecodeShardings", "decode_spec_for",
    "kv_pool_specs", "build_decode_shardings", "place_decode_params",
    "place_kv_pool", "apply_sharding", "pool_blocks_for_budget",
    "max_slots_for_budget", "CollectiveQuant", "build_collective_quant",
    "normalize_collective_quant", "SP_ATTENTION_MODES",
    "build_sp_fresh_attention", "sp_attention_peak_bytes",
    "sp_attention_flat_bound",
]
