"""ShardedEngineConfig — mesh/axis plumbing for the sharded paged engine.

Reuses the canonical mesh builder (`parallel/mesh.py`, axes dp/pp/mp/sp)
so serving and training agree on axis names: serving tensor parallel IS
the training `mp` axis (column/row-split weights, vocab-parallel head)
and the optional slot/data axis is `dp` (the KV pool's block dimension
shards over it).  pp stays 1 — pipeline parallel is a training-side
schedule with no decode analogue here.  `sp` (long-context round) is
the SEQUENCE-PARALLEL axis of the packed PREFILL stream: one huge
prompt's chunk stream shards its token axis over sp, multiplying the
per-dispatch chunk budget by sp, while decode stays pure TP and the KV
pool stays replicated over sp (every shard owns the writes of its own
stream slice and the seams re-replicate them — see nn/decode.py).
"""
from __future__ import annotations

import logging
from dataclasses import dataclass

_log = logging.getLogger(__name__)

#: Attention strategies for the sequence-parallel packed-prefill trunk.
#: "allgather" is the r21 seam (each shard all-gathers the full K/V
#: stream — linear memory in chunk length); "ring" rotates fixed-size
#: K/V sub-blocks around the sp axis with online-softmax accumulation;
#: "ulysses" all-to-alls heads<->sequence so each shard attends its own
#: head slice over the full stream block-by-block.  Both keep peak live
#: K/V bytes per shard O(block), flat in chunk length.
SP_ATTENTION_MODES = ("allgather", "ring", "ulysses")


@dataclass(frozen=True)
class ShardedEngineConfig:
    """How to shard one `PagedGenerationServer` across devices.

    tp: tensor-parallel degree — attention/MLP weights column/row-split
        and the LM head vocab-sharded over the mesh `mp` axis; the KV
        pool's HEAD axis shards with them, so each device holds
        1/tp of every block's bytes.
    dp: optional data/slot degree — the KV pool's BLOCK axis
        additionally shards over the mesh `dp` axis (per-device pool
        bytes divide by tp*dp).  Weights are replicated over dp.
    sp: sequence-parallel degree for the PACKED PREFILL stream (long-
        context round): the engine's per-dispatch chunk budget becomes
        `prefill_chunk_tokens * sp` and the packed-prefill program
        shards its token axis over the mesh `sp` axis, so ONE huge
        prompt stops serializing through a single replica's budget.
        Decode/verify/unified programs are untouched (decode stays
        TP), the KV pool is replicated over sp, and sp=1 traces the
        exact pre-round programs bitwise.  sp>1 requires dp==1 — sp
        shards one stream; dp replicates independent pools, and the
        composed layout is future work (ROADMAP).
    devices: explicit device list (tests / subsets); None = the first
        tp*dp*sp of `jax.devices()`.
    collective_quant: None (default — the exact pre-round bf16
        collectives) | "int8" | "int4g": quantize the decode hot
        path's mp-axis collectives (row-split psums, embed psum,
        vocab-parallel logits) through the serving_dist.collectives
        shard_map seams.  Static — flipping it re-traces the decode
        programs.  tp=1 meshes ignore it (no inter-chip wire).
    int4_group: scale-group width of the "int4g" wire (snapped to a
        divisor of each chunk; ignored by "int8").
    sp_attention: how the sp>1 packed-prefill trunk attends across
        shards — one of SP_ATTENTION_MODES.  "allgather" (default) is
        the exact r21 path; "ring"/"ulysses" are memory-flat (peak live
        K/V bytes per shard stay O(block) instead of O(chunk)) and
        token-parity-tested against it.  sp=1 normalizes ring/ulysses
        back to "allgather" (degenerate mesh — nothing to rotate).
    """

    tp: int = 1
    dp: int = 1
    sp: int = 1
    devices: tuple = None
    collective_quant: str = None
    int4_group: int = 32
    sp_attention: str = "allgather"

    def __post_init__(self):
        for field_name in ("tp", "dp", "sp", "int4_group"):
            v = getattr(self, field_name)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise ValueError(
                    f"ShardedEngineConfig.{field_name}={v!r} must be a "
                    f"positive int")
        if self.sp > 1 and self.dp > 1:
            raise ValueError(
                f"ShardedEngineConfig(sp={self.sp}, dp={self.dp}): "
                f"sp>1 requires dp==1 — sequence parallel shards ONE "
                f"packed prefill stream while dp shards the pool's "
                f"block axis across replicas; the composed layout is "
                f"not implemented")
        from .collectives import normalize_collective_quant

        normalize_collective_quant(self.collective_quant)
        if self.sp_attention not in SP_ATTENTION_MODES:
            raise ValueError(
                f"ShardedEngineConfig.sp_attention="
                f"{self.sp_attention!r} must be one of "
                f"{SP_ATTENTION_MODES}")
        if self.sp == 1 and self.sp_attention != "allgather":
            _log.debug(
                "ShardedEngineConfig(sp=1, sp_attention=%r): degenerate "
                "sp mesh has nothing to rotate; normalizing to "
                "'allgather' (bitwise-identical programs)",
                self.sp_attention)
            object.__setattr__(self, "sp_attention", "allgather")
        if self.devices is not None:
            object.__setattr__(self, "devices", tuple(self.devices))

    @property
    def total(self):
        return self.tp * self.dp * self.sp

    def build_mesh(self):
        """Build the (dp, pp, mp, sp) mesh this config shards over —
        pp = 1, mp = tp, sp = sp.  Raises naming the shortfall when
        the backend has fewer devices than tp*dp*sp (the forced-host
        CPU flag or a real slice provides them)."""
        import jax

        from ..parallel.mesh import make_mesh

        devices = self.devices
        if devices is None:
            avail = jax.devices()
            if len(avail) < self.total:
                raise ValueError(
                    f"ShardedEngineConfig(tp={self.tp}, dp={self.dp}, "
                    f"sp={self.sp}) "
                    f"needs {self.total} devices, backend has "
                    f"{len(avail)} (on CPU set XLA_FLAGS="
                    f"--xla_force_host_platform_device_count="
                    f"{self.total} before importing jax, or use "
                    f"scripts/run_mesh_tests.sh)")
            devices = avail[:self.total]
        elif len(devices) != self.total:
            raise ValueError(
                f"ShardedEngineConfig(tp={self.tp}, dp={self.dp}, "
                f"sp={self.sp}) needs "
                f"exactly {self.total} devices, got {len(devices)}")
        return make_mesh(dp=self.dp, mp=self.tp, pp=1, sp=self.sp,
                         devices=list(devices))

    def stats_block(self):
        """The `stats()["sharding"]` dict for an ENABLED server (the
        disabled form is zeroed by the engine — schema-congruent)."""
        return {
            "enabled": True,
            "mesh_shape": {"dp": self.dp, "mp": self.tp, "sp": self.sp},
            "tp_degree": self.tp,
            "dp_degree": self.dp,
            "sp_degree": self.sp,
            "collective_quant": self.collective_quant or "none",
            "sp_attention": self.sp_attention,
        }


def normalize_sharding(sharding, num_heads):
    """Normalize the server's `sharding=` ctor value (True -> default
    config) and check the ONE hard divisibility requirement eagerly:
    tp must divide the head count, because the KV pool shards its head
    axis over mp (a fractional head slice has no block layout).  Param
    dims that an axis happens not to divide (GPT-2's 50257 vocab, say)
    just fall back to replicated placement per-leaf in plan.py — only
    the pool layout is load-bearing."""
    if sharding is True:
        sharding = ShardedEngineConfig()
    if not isinstance(sharding, ShardedEngineConfig):
        raise TypeError(f"sharding must be a ShardedEngineConfig, True "
                        f"or None, got {type(sharding).__name__}")
    if num_heads % sharding.tp:
        raise ValueError(
            f"ShardedEngineConfig.tp={sharding.tp} must divide the "
            f"model's num_heads={num_heads}: the KV pool shards its "
            f"head axis over the mp mesh axis")
    if sharding.sp_attention == "ulysses":
        local_heads = num_heads // sharding.tp
        if local_heads % sharding.sp:
            raise ValueError(
                f"ShardedEngineConfig(sp_attention='ulysses', "
                f"sp={sharding.sp}, tp={sharding.tp}): ulysses needs "
                f"the mp-local head count ({local_heads}) divisible by "
                f"sp ({sharding.sp}); use ring attention for "
                f"head-count-agnostic sequence parallelism")
    return sharding


def disabled_stats_block():
    """The zeroed, schema-congruent `stats()["sharding"]` block an
    unsharded server reports (the speculation/frontdoor convention:
    dashboards and bench records need no gating)."""
    return {
        "enabled": False,
        "mesh_shape": {},
        "tp_degree": 0,
        "dp_degree": 0,
        "sp_degree": 0,
        "collective_quant": "none",
        "sp_attention": "none",
    }
