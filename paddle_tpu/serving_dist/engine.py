"""Server-side glue: apply a ShardedEngineConfig to a live engine, and
the capacity arithmetic the bench/tests reason with.

`apply_sharding` is called from the `PagedGenerationServer` constructor
(lazily — an unsharded server never imports this package) after the
weights are snapshotted/quantized and the pool is built, and BEFORE the
PagedDecoder exists: it places the params and pool arrays on the mesh
and returns the DecodeShardings bundle the decoder jits with.
"""
from __future__ import annotations

import numpy as np

from .config import ShardedEngineConfig
from .plan import build_decode_shardings, place_decode_params, place_kv_pool


def apply_sharding(server, cfg):
    """Shard `server`'s weights + KV pool per `cfg`; returns the
    DecodeShardings for the server's PagedDecoder.  Mutates only
    placements (device arrays move onto the mesh) — values, host
    bookkeeping and the engine loop are untouched."""
    if not isinstance(cfg, ShardedEngineConfig):
        raise TypeError(f"sharding must be a ShardedEngineConfig, got "
                        f"{type(cfg).__name__} (the server ctor "
                        f"normalizes True via normalize_sharding)")
    mesh = cfg.build_mesh()
    server._params = place_decode_params(mesh, server._params)
    place_kv_pool(mesh, server.cache)
    # per-shard byte accounting divides by the axes that actually SPLIT
    # the pool (heads over mp, blocks over dp); sp replicates the pool,
    # so each sp shard holds a full tp*dp-divided copy
    server.cache.set_shard_count(cfg.tp * cfg.dp)
    server.sharding = cfg
    server._mesh = mesh
    return build_decode_shardings(mesh, server._params,
                                  server.kv_dtype)


def _block_bytes(num_layers, num_heads, head_dim, block_size,
                 dtype=np.float32, kv_dtype=None):
    """Device bytes ONE pool block costs across all layers, K + V
    (codes + per-vector scales under int8)."""
    vecs = num_layers * 2 * block_size * num_heads  # K and V
    if kv_dtype == "int8":
        return vecs * (head_dim * 1 + np.dtype(dtype).itemsize)
    return vecs * head_dim * np.dtype(dtype).itemsize


def pool_blocks_for_budget(cfg_model, block_size, per_device_bytes,
                           tp=1, dp=1, dtype=np.float32, kv_dtype=None):
    """Largest `num_blocks` (INCLUDING trash block 0) whose per-device
    pool share fits `per_device_bytes`.  The pool shards its head axis
    over tp and its block axis over dp, so per-device bytes =
    total / (tp * dp): at FIXED per-device budget the pool holds
    tp*dp times the blocks — the capacity lever the sharded bench axis
    measures."""
    bb = _block_bytes(cfg_model.num_layers, cfg_model.num_heads,
                      cfg_model.hidden_size // cfg_model.num_heads,
                      block_size, dtype, kv_dtype)
    return max(2, int(per_device_bytes * tp * dp // bb))


def max_slots_for_budget(cfg_model, block_size, per_device_bytes,
                         tokens_per_request, tp=1, dp=1,
                         dtype=np.float32, kv_dtype=None,
                         spare_blocks=0):
    """Concurrent slots the admission reservation can back at a fixed
    per-device pool budget: usable blocks // worst-case blocks per
    request (`tokens_per_request` = prompt + budget + overrun slack;
    `spare_blocks` = the +1 CoW spare when prefix caching is on)."""
    from ..inference.kv_cache import blocks_for

    nb = pool_blocks_for_budget(cfg_model, block_size, per_device_bytes,
                                tp=tp, dp=dp, dtype=dtype,
                                kv_dtype=kv_dtype)
    per_req = blocks_for(tokens_per_request, block_size) + spare_blocks
    return (nb - 1) // max(per_req, 1)
