"""Memory-flat sequence-parallel attention for the sp packed-prefill trunk.

The r21 sp trunk made the packed prefill stream sequence-parallel but
kept one memory cliff: `nn/decode._sp_kv_gather` all-gathers the FULL
freshly-projected K/V stream onto every sp shard before the pool
scatter and attention, so peak live fresh-K/V bytes per shard are
O(chunk) — linear in chunk length, exactly the regime ring attention
exists for.  This module ports the two multichip training primitives
(`parallel/ring_attention.py`, `parallel/ulysses.py`) into the serving
trunk's RAGGED, PAGED contract:

* ring — each shard's fresh K/V stream slice is cut into fixed
  `block_tokens`-row sub-blocks that rotate around the `sp` axis via
  ppermute; every shard scatters each visiting block into its replica
  of the paged pool (so the sp-replicated pool converges bitwise, the
  r21 invariant) and folds it into an online-softmax accumulator.
  Peak cross-shard fresh-K/V per shard = held block + in-flight
  ppermute buffer = O(block_tokens), CONSTANT in chunk length.

* ulysses — one all-to-all per sub-block swaps sequence<->head
  sharding: each shard attends its own head slice over the full
  gathered sub-block (global row order reconstructed by index math).
  The pool scatter still rides the ring rotation (the replicated pool
  needs ALL mp-local heads per shard), fused into the same scan.
  Requires the mp-local head count divisible by sp.

Masking contract (the `ops/pallas/unified_attention.py` segment-causal
contract, which must survive rotation): every row of the packed stream
carries (seg, pos) metadata; a query at (qseg, qpos) attends exactly
keys with kseg == qseg and 0 <= kpos <= qpos.  Because seg/pos enter
the seam REPLICATED (specs P(None)), a visiting block's metadata is
recovered exactly from its origin shard index — global row r of ring
step s on shard j is (j - s) % n * T_local + c * block + r — so
cross-shard causality is exact, not approximate.  The fresh pass
covers positions [start_seg, qpos] (start_seg = the segment's first
position written THIS dispatch, computed by `segment_starts`); the
pool pass covers columns < start_seg against the already-resident
paged blocks with the same numerics as `ops.attention`'s XLA fallback
(scores f32, weights cast to model dtype, int8 scales folded
post-contraction).  The union is exactly [0, qpos] — the same key set
the all-gather path masks — so parity is token-for-token (the online
softmax reassociates the reduction; parity is asserted empirically on
the composed stack, the established sp policy).

Pad rows (pos == -1) are excluded from attention by the mask and their
K/V payload is ZEROED before rotation: all pads scatter into the
reserved trash block (0, 0), and different shards apply those writes
in different rotation orders — identical zero payloads keep the sp
pool replicas bitwise convergent regardless of order (the all-gather
path gets this for free because every shard applies the one gathered
stream in one order).
"""
from __future__ import annotations

import functools

from .config import SP_ATTENTION_MODES  # noqa: F401  (re-export)

#: Rotation sub-block length (tokens).  Fixed — NOT a function of chunk
#: length — so ring/ulysses peak cross-shard fresh-K/V bytes per shard
#: are constant across any chunk sweep (the memory-flatness bar).
#: Matches parallel/ring_attention._CHUNK.
DEFAULT_BLOCK_TOKENS = 512

NEG_INF = -1e30


def _sub_block(local_tokens, block_tokens):
    """Static sub-block length: `block_tokens` shrunk (power-of-two
    steps) until it divides the shard-local stream length."""
    bc = max(1, min(int(block_tokens), int(local_tokens)))
    while local_tokens % bc:
        bc //= 2
    return bc


def sp_attention_peak_bytes(mode, chunk_tokens, sp, tp, num_heads,
                            head_dim, kv_quant=False, itemsize=4,
                            scale_itemsize=4,
                            block_tokens=DEFAULT_BLOCK_TOKENS):
    """Peak CROSS-SHARD fresh-K/V bytes one sp shard materializes to
    attend a packed stream of `chunk_tokens` — the analytic accounting
    the flat-memory assertion and the `serving_sp_attention_bytes_peak`
    gauge report (host-side arithmetic, the r20 `dispatch_wire_bytes`
    discipline: CPU-degraded runs can't measure HBM, the formula is
    exact on any backend).

    Counted: bytes the attention MODE materializes beyond the shard's
    own T/sp stream slice — the all-gather output (full stream, k+v),
    or ring's held + in-flight rotating sub-blocks, or ulysses' a2a
    in/out buffers + the rotation-scatter window.  Not counted: the
    shard-local q/k/v projections and the paged pool itself, identical
    across modes (O(chunk/sp) and O(pool) respectively).

    allgather: 2 * chunk * (H/tp) * Dh * eff     (linear in chunk)
    ring:      4 * block * (H/tp) * Dh * eff     (constant)
    ulysses:   8 * block * (H/tp) * Dh * eff     (constant)
    eff = itemsize, or for int8 KV 1 + scale_itemsize/Dh.
    """
    if mode not in SP_ATTENTION_MODES:
        raise ValueError(f"sp_attention={mode!r} must be one of "
                         f"{SP_ATTENTION_MODES}")
    t = int(chunk_tokens)
    local_heads = max(1, int(num_heads) // max(1, int(tp)))
    eff = (1.0 + float(scale_itemsize) / float(head_dim)) if kv_quant \
        else float(itemsize)
    per_tok = local_heads * int(head_dim) * eff
    if mode == "allgather" or int(sp) <= 1:
        return int(round(2 * t * per_tok))
    bc = _sub_block(max(1, t // int(sp)), block_tokens)
    ring = 4 * bc * per_tok          # k+v, held + in-flight ppermute
    if mode == "ring":
        return int(round(ring))
    return int(round(2 * ring))      # ulysses: + a2a in/out buffers


def sp_attention_flat_bound(mode, tp, num_heads, head_dim,
                            kv_quant=False, itemsize=4,
                            scale_itemsize=4,
                            block_tokens=DEFAULT_BLOCK_TOKENS):
    """The chunk-length-INDEPENDENT ceiling on ring/ulysses peak bytes
    (the sub-block never exceeds `block_tokens` rows) — what the
    serving loop asserts every ring/ulysses dispatch stays under."""
    eff = (1.0 + float(scale_itemsize) / float(head_dim)) if kv_quant \
        else float(itemsize)
    per_tok = max(1, int(num_heads) // max(1, int(tp))) * int(head_dim) \
        * eff
    mult = 4 if mode == "ring" else 8
    return int(round(mult * int(block_tokens) * per_tok))


def segment_starts(seg, pos, num_segments):
    """Per-segment first position written THIS dispatch: starts[b] =
    min over the stream's valid rows of segment b of pos (a huge
    sentinel when a segment feeds no rows — its queries don't exist
    either).  Splits each query's key range exactly: pool columns
    < starts[qseg] (earlier dispatches), fresh rows in
    [starts[qseg], qpos].  Computed OUTSIDE the shard_map seam from the
    replicated stream metadata, so every shard agrees bitwise."""
    import jax.numpy as jnp

    big = jnp.int32(2 ** 30)
    p = jnp.where(pos >= 0, pos.astype(jnp.int32), big)
    return jnp.full((num_segments,), big, jnp.int32).at[seg].min(p)


def kv_set_layer(cache, i, new, kv_quant):
    """Functional single-layer write-back into the full pool stack —
    the inverse of `nn.decode._kv_io`'s `layer` accessor, for trunks
    whose attention seam updates a whole layer slice at once."""
    if kv_quant:
        from ..inference.kv_quant import QuantizedKV

        return QuantizedKV(cache.codes.at[i].set(new.codes),
                           cache.scales.at[i].set(new.scales))
    return cache.at[i].set(new)


@functools.lru_cache(maxsize=16)
def build_sp_fresh_attention(mesh, mode, kv_quant, block_size, scale,
                             block_tokens=DEFAULT_BLOCK_TOKENS):
    """Build the shard_map seam that replaces `_sp_kv_gather` + the
    sp trunk's per-layer pool scatter + `ragged_prefill_attention`:

        attend(q, k, v, kc_i, vc_i, tables, seg, pos, starts)
            -> (o, kc_i, vc_i)

    q/k/v: [T, H_mp, Dh] fresh projections, token axis sp-sharded and
    head axis mp-sharded (the trunk's layout).  kc_i/vc_i: ONE layer's
    pool arrays ([N, BS, H_mp, Dh] dense, or int8 QuantizedKV),
    sp-replicated / mp-head-sharded, returned with this stream's rows
    scattered in on every sp replica.  tables/seg/pos/starts:
    replicated ragged metadata ([B, M], [T], [T], [B]).  o: [T, H_mp,
    Dh] attention output, token-sharded like q.

    Static args (cache key): mesh, mode ("ring"|"ulysses"), kv_quant,
    pool block_size, softmax scale, rotation sub-block length.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import axis_size as _axis_size
    from ..parallel.mesh import pvary as _pvary

    if mode not in ("ring", "ulysses"):
        raise ValueError(f"build_sp_fresh_attention: mode={mode!r} "
                         f"(allgather keeps the r21 seam)")
    BS = int(block_size)
    quant = bool(kv_quant)
    if quant:
        from ..inference.kv_quant import QuantizedKV, kv_encode

    def _vary(t):
        return jax.tree_util.tree_map(lambda x: _pvary(x, "sp"), t)

    # -- shared pieces (shapes generic over the head count Hq) --------

    def _pool_partial(qh, qseg, qpos, qcap, kc_i, vc_i, tables):
        """Unnormalized (o, m, l) of the queries against the
        ALREADY-RESIDENT pool columns (< qcap per query) — the exact
        numerics of ops.attention's XLA fallback (scores f32, weights
        cast to model dtype, int8 scales folded post-contraction),
        minus the final normalization, which happens after the fresh
        blocks merge in.  qh: [Hq, Tq, Dh]."""
        hq, tq, dh = qh.shape
        b, mmax = tables.shape
        c = mmax * BS
        if quant:
            k = kc_i.codes[tables].reshape(b, c, hq, dh)
            v = vc_i.codes[tables].reshape(b, c, hq, dh)
            ks = kc_i.scales[tables].reshape(b, c, hq).transpose(2, 0, 1)
            vs = vc_i.scales[tables].reshape(b, c, hq).transpose(2, 0, 1)
        else:
            k = kc_i[tables].reshape(b, c, hq, dh)
            v = vc_i[tables].reshape(b, c, hq, dh)
        k = k.transpose(2, 0, 1, 3).astype(qh.dtype)   # [Hq, B, C, Dh]
        v = v.transpose(2, 0, 1, 3).astype(qh.dtype)
        s = jnp.einsum("htd,hbcd->htbc", qh, k).astype(jnp.float32) \
            * scale
        if quant:
            s = s * ks[:, None].astype(jnp.float32)
        own = qseg[:, None] == jnp.arange(b)[None, :]          # [Tq, B]
        ok = jnp.arange(c)[None, :] < qcap[:, None]            # [Tq, C]
        mask = own[:, :, None] & ok[:, None, :]
        s = jnp.where(mask[None], s, NEG_INF)
        sf = s.reshape(hq, tq, b * c)
        m = sf.max(-1)                                         # [Hq, Tq]
        p = jnp.exp(sf - m[..., None])                         # f32
        l = p.sum(-1)
        w = p.reshape(hq, tq, b, c).astype(qh.dtype)
        if quant:
            w = w * vs[:, None].astype(qh.dtype)
        o = jnp.einsum("htbc,hbcd->htd", w, v).astype(jnp.float32)
        return o, m, l

    def _attend_block(qh, qseg, qpos, kb, vb, kseg, kpos, acc):
        """Fold one visiting fresh sub-block into the online-softmax
        accumulator (ring_attention's merge rule).  kb/vb: [Bc, Hq,
        Dh] (or int8 (codes, scales)); kseg/kpos: the block's global
        row metadata, recovered outside."""
        o, m, l = acc
        if quant:
            kcodes, ksc = kb
            vcodes, vsc = vb
            k = kcodes.transpose(1, 0, 2).astype(qh.dtype)
            v = vcodes.transpose(1, 0, 2).astype(qh.dtype)
            ksh = ksc.transpose(1, 0)                          # [Hq, Bc]
            vsh = vsc.transpose(1, 0)
        else:
            k = kb.transpose(1, 0, 2)                     # [Hq, Bc, Dh]
            v = vb.transpose(1, 0, 2)
        s = jnp.einsum("htd,hcd->htc", qh, k).astype(jnp.float32) \
            * scale
        if quant:
            s = s * ksh[:, None].astype(jnp.float32)
        mask = (qseg[:, None] == kseg[None, :]) \
            & (kpos[None, :] >= 0) \
            & (kpos[None, :] <= qpos[:, None])                # [Tq, Bc]
        s = jnp.where(mask[None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        alpha = jnp.exp(m - m_new)       # exp(-1e30 - finite) == 0.0:
        p = jnp.exp(s - m_new[..., None])  # empty partials annihilate
        w = p.astype(qh.dtype)
        if quant:
            w = w * vsh[:, None].astype(qh.dtype)
        pv = jnp.einsum("htc,hcd->htd", w, v).astype(jnp.float32)
        o = o * alpha[..., None] + pv
        l = l * alpha + p.sum(-1)
        return o, m_new, l

    def _scatter(kc_i, vc_i, kb, vb, kseg, kpos, tables):
        """Scatter one visiting sub-block's rows into this shard's
        pool replica — the same (blk, off) arithmetic as the trunk's
        `kv_write`, pads routed to the reserved trash block 0 (their
        payload is pre-zeroed, so every rotation order converges)."""
        valid = kpos >= 0
        p0 = jnp.where(valid, kpos, 0)
        blk = jnp.where(valid, tables[kseg, p0 // BS], 0)
        off = p0 % BS
        if quant:
            kc_i = QuantizedKV(kc_i.codes.at[blk, off].set(kb[0]),
                               kc_i.scales.at[blk, off].set(kb[1]))
            vc_i = QuantizedKV(vc_i.codes.at[blk, off].set(vb[0]),
                               vc_i.scales.at[blk, off].set(vb[1]))
        else:
            kc_i = kc_i.at[blk, off].set(kb)
            vc_i = vc_i.at[blk, off].set(vb)
        return kc_i, vc_i

    def _fresh_payload(k, v, valid, scales_dtype):
        """Zero pad rows, encode once when quantized (per-row absmax —
        bit-identical to `kv_write`'s append encoding no matter how
        rows are batched or routed), cut into rotation sub-blocks."""
        kz = jnp.where(valid[:, None, None], k, 0)
        vz = jnp.where(valid[:, None, None], v, 0)
        if quant:
            kz = kv_encode(kz, scales_dtype)       # (codes, scales)
            vz = kv_encode(vz, scales_dtype)
        return kz, vz

    def _chunks(t, n_blocks, bc):
        return jax.tree_util.tree_map(
            lambda x: x.reshape((n_blocks, bc) + x.shape[1:]), t)

    def _rotate(t, n):
        perm = [(d, (d + 1) % n) for d in range(n)]
        return jax.tree_util.tree_map(
            lambda x: jax.lax.ppermute(x, "sp", perm), t)

    # -- mode bodies (run per-shard inside shard_map) ------------------

    def ring_body(q, k, v, kc_i, vc_i, tables, seg, pos, starts):
        n = _axis_size("sp")
        j = jax.lax.axis_index("sp")
        tl = q.shape[0]
        bc = _sub_block(tl, block_tokens)
        nb = tl // bc
        kc_i, vc_i, tables, seg, pos, starts = _vary(
            (kc_i, vc_i, tables, seg, pos, starts))
        qseg = jax.lax.dynamic_slice_in_dim(seg, j * tl, tl)
        qpos = jax.lax.dynamic_slice_in_dim(pos, j * tl, tl)
        qh = q.transpose(1, 0, 2)                      # [Hl, Tl, Dh]
        qcap = jnp.where(qpos >= 0,
                         jnp.minimum(starts[qseg], qpos + 1), 0)
        o, m, l = _pool_partial(qh, qseg, qpos, qcap, kc_i, vc_i,
                                tables)
        sdt = kc_i.scales.dtype if quant else None
        kz, vz = _fresh_payload(k, v, qpos >= 0, sdt)

        def outer(carry, xs):
            kc_i, vc_i, o, m, l = carry
            kb0, vb0, c = xs

            def inner(icarry, s):
                kc_i, vc_i, o, m, l, kb, vb = icarry
                src = (j - s) % n
                base = src * tl + c * bc
                kseg = jax.lax.dynamic_slice_in_dim(seg, base, bc)
                kpos = jax.lax.dynamic_slice_in_dim(pos, base, bc)
                kc_i, vc_i = _scatter(kc_i, vc_i, kb, vb, kseg, kpos,
                                      tables)
                o, m, l = _attend_block(qh, qseg, qpos, kb, vb, kseg,
                                        kpos, (o, m, l))
                kb, vb = _rotate((kb, vb), n)
                return (kc_i, vc_i, o, m, l, kb, vb), None

            (kc_i, vc_i, o, m, l, _, _), _ = jax.lax.scan(
                inner, (kc_i, vc_i, o, m, l, kb0, vb0),
                jnp.arange(n))
            return (kc_i, vc_i, o, m, l), None

        (kc_i, vc_i, o, m, l), _ = jax.lax.scan(
            outer, (kc_i, vc_i, o, m, l),
            (_chunks(kz, nb, bc), _chunks(vz, nb, bc), jnp.arange(nb)))
        out = (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)
        return out.transpose(1, 0, 2), kc_i, vc_i

    def ulysses_body(q, k, v, kc_i, vc_i, tables, seg, pos, starts):
        n = _axis_size("sp")
        j = jax.lax.axis_index("sp")
        tl, hl, dh = q.shape
        hu = hl // n
        bc = _sub_block(tl, block_tokens)
        nb = tl // bc
        kc_i, vc_i, tables, seg, pos, starts = _vary(
            (kc_i, vc_i, tables, seg, pos, starts))
        # seq -> head: my head slice over the FULL packed stream, rows
        # in global order (sources concatenate in ring order)
        qg = jax.lax.all_to_all(q, "sp", split_axis=1, concat_axis=0,
                                tiled=True)               # [T, Hu, Dh]
        qh = qg.transpose(1, 0, 2)
        qcap = jnp.where(pos >= 0,
                         jnp.minimum(starts[seg], pos + 1), 0)
        h0 = j * hu
        if quant:
            kc_h = QuantizedKV(
                jax.lax.dynamic_slice_in_dim(kc_i.codes, h0, hu, 2),
                jax.lax.dynamic_slice_in_dim(kc_i.scales, h0, hu, 2))
            vc_h = QuantizedKV(
                jax.lax.dynamic_slice_in_dim(vc_i.codes, h0, hu, 2),
                jax.lax.dynamic_slice_in_dim(vc_i.scales, h0, hu, 2))
        else:
            kc_h = jax.lax.dynamic_slice_in_dim(kc_i, h0, hu, 2)
            vc_h = jax.lax.dynamic_slice_in_dim(vc_i, h0, hu, 2)
        o, m, l = _pool_partial(qh, seg, pos, qcap, kc_h, vc_h, tables)
        sdt = kc_i.scales.dtype if quant else None
        qpos_loc = jax.lax.dynamic_slice_in_dim(pos, j * tl, tl)
        kz, vz = _fresh_payload(k, v, qpos_loc >= 0, sdt)
        # global row index of gathered-sub-block row r: source shard
        # r // bc contributed its rows [c*bc, c*bc+bc)
        gbase = (jnp.arange(n)[:, None] * tl
                 + jnp.arange(bc)[None, :]).reshape(-1)

        def a2a(t):
            return jax.tree_util.tree_map(
                lambda x: jax.lax.all_to_all(
                    x, "sp", split_axis=1, concat_axis=0, tiled=True),
                t)

        def outer(carry, xs):
            kc_i, vc_i, o, m, l = carry
            kb0, vb0, c = xs
            gidx = gbase + c * bc
            o, m, l = _attend_block(qh, seg, pos, a2a(kb0), a2a(vb0),
                                    seg[gidx], pos[gidx], (o, m, l))

            # the sp-replicated pool needs ALL mp-local heads on every
            # shard, which the head-sharded a2a view can't provide —
            # the scatter rides the ring rotation instead
            def inner(icarry, s):
                kc_i, vc_i, kb, vb = icarry
                src = (j - s) % n
                base = src * tl + c * bc
                kseg = jax.lax.dynamic_slice_in_dim(seg, base, bc)
                kpos = jax.lax.dynamic_slice_in_dim(pos, base, bc)
                kc_i, vc_i = _scatter(kc_i, vc_i, kb, vb, kseg, kpos,
                                      tables)
                kb, vb = _rotate((kb, vb), n)
                return (kc_i, vc_i, kb, vb), None

            (kc_i, vc_i, _, _), _ = jax.lax.scan(
                inner, (kc_i, vc_i, kb0, vb0), jnp.arange(n))
            return (kc_i, vc_i, o, m, l), None

        (kc_i, vc_i, o, m, l), _ = jax.lax.scan(
            outer, (kc_i, vc_i, o, m, l),
            (_chunks(kz, nb, bc), _chunks(vz, nb, bc), jnp.arange(nb)))
        on = (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)
        # head -> seq: normalize FIRST so only o crosses back
        out = jax.lax.all_to_all(on.transpose(1, 0, 2), "sp",
                                 split_axis=0, concat_axis=1,
                                 tiled=True)              # [Tl, Hl, Dh]
        return out, kc_i, vc_i

    body = ring_body if mode == "ring" else ulysses_body
    stream = P("sp", "mp", None)
    if quant:
        from ..inference.kv_quant import QuantizedKV as _QKV

        pool = _QKV(P(None, None, "mp", None), P(None, None, "mp"))
    else:
        pool = P(None, None, "mp", None)
    return shard_map(
        body, mesh=mesh,
        in_specs=(stream, stream, stream, pool, pool, P(None, None),
                  P(None), P(None), P(None)),
        out_specs=(stream, pool, pool),
        check_rep=False)
