"""The GPT-2 decode sharding plan: flat param names -> PartitionSpec.

The placement is the PROVEN training TP plan (models/gpt2_hybrid.py /
parallel/api.py Megatron rules) transcribed onto the decode programs'
flat naming ("h.{i}.qkv_proj.weight", ...):

  * column-split (output dim over mp): qkv_proj, fc1 — their biases and
    per-output-column int8 scales shard with the columns;
  * row-split (contraction dim over mp): out_proj, fc2 — XLA inserts
    the ONE all-reduce per half-block after each, exactly the psum the
    training `_stage_fn` places; their biases/scales are replicated
    (they apply after the reduction);
  * vocab-parallel embedding + tied head: wte rows over mp — the embed
    is a sharded gather, the head's [B, V]-sharded logits are
    all-gathered before the sampling pipeline (argmax/top-k need the
    full vocab row; the training path keeps them sharded because CE
    only needs psum'd softmax statistics — serving pays the gather, the
    placement the ISSUE names);
  * everything else (wpe, layer norms, row-split biases) replicated.

The W8A16 key convention is honored: "name::w8c" codes shard like
"name", "name::w8s" per-output-column scales shard like the weight's
LAST dim.  The KV pool shards its HEAD axis over mp (each device holds
its heads' slice of every block — block tables stay replicated host
state) and optionally its BLOCK axis over dp; int8 pools shard codes
and per-vector scales in lockstep.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.api import tp_spec_for

W8_CODES, W8_SCALES = "::w8c", "::w8s"


def _base_weight_spec(name, ndim):
    """Spec of a base (non-suffixed) decode param name."""
    if name == "wte.weight":
        return P("mp", *([None] * (ndim - 1)))  # vocab-parallel
    if name == "wpe.weight" or ".ln_" in name or name.startswith("ln_f"):
        return P()
    if name.endswith(".bias"):
        # biases follow their weight's output columns: column-split
        # projections get sharded biases, row-split ones replicated
        w = tp_spec_for(name[:-len(".bias")] + ".weight", 2)
        return P("mp") if tuple(w) and tuple(w)[-1] == "mp" else P()
    return tp_spec_for(name, ndim)  # Megatron column/row rules


def decode_spec_for(name, ndim):
    """PartitionSpec for one flat decode param (handles the int8 key
    convention: codes shard like the weight, per-output-column scales
    like its last dim)."""
    if name.endswith(W8_CODES):
        return _base_weight_spec(name[:-len(W8_CODES)], ndim)
    if name.endswith(W8_SCALES):
        base = name[:-len(W8_SCALES)]
        if base == "wte.weight":
            # embedding scales are per VOCAB ROW (the quantization
            # channel), not per column — they shard with the rows
            return P("mp", *([None] * (ndim - 1)))
        w = _base_weight_spec(base, max(ndim + 1, 2))
        last = tuple(w)[-1] if tuple(w) else None
        return P(*([None] * (ndim - 1) + [last]))
    return _base_weight_spec(name, ndim)


def _fit(mesh, spec, shape):
    """Drop spec axes whose mesh size doesn't divide the dim (explicit
    NamedSharding placement requires divisibility; GPT-2's 50257 vocab
    is the canonical offender).  The leaf just stays replicated on that
    dim — correctness is placement-independent, and XLA may still
    shard the computation internally."""
    entries = tuple(spec) + (None,) * (len(shape) - len(tuple(spec)))
    return P(*[ax if ax is not None and shape[i] % mesh.shape[ax] == 0
               else None for i, ax in enumerate(entries)])


def param_shardings(mesh, params):
    """dict name -> NamedSharding for one decode param dict (base or
    W8A16-quantized keys alike); indivisible dims fall back to
    replicated per-leaf."""
    return {name: NamedSharding(mesh, _fit(
        mesh, decode_spec_for(name, v.ndim), v.shape))
        for name, v in params.items()}


def kv_pool_specs(kv_dtype=None):
    """(k_blocks, v_blocks) sharding-spec pytrees for the pool arrays:
    [L, num_blocks, block_size, H, Dh] with heads over mp and blocks
    over dp.  For an int8 pool the per-vector scale buffer
    [L, num_blocks, block_size, H] shards identically minus Dh, so
    codes and scales stay in lockstep under every block operation."""
    codes = P(None, "dp", None, "mp", None)
    if kv_dtype == "int8":
        from ..inference.kv_quant import QuantizedKV

        spec = QuantizedKV(codes, P(None, "dp", None, "mp"))
    elif kv_dtype is None:
        spec = codes
    else:
        raise ValueError(f"unknown kv_dtype {kv_dtype!r} "
                         "(supported: None, 'int8')")
    return spec, spec


class DecodeShardings:
    """The sharding bundle one sharded PagedDecoder jits with: per-name
    param shardings, the kc/vc pool sharding pytree, and the replicated
    sharding every host-side dispatch input/output is pinned to.

    HASHABLE (param shardings held as a sorted item tuple; Mesh and
    NamedSharding hash structurally), so the explicit-sharding jits in
    nn/decode are cached process-wide per bundle — two servers on
    equal meshes share compiled programs instead of re-jitting."""

    __slots__ = ("mesh", "_params_items", "kv", "rep")

    def __init__(self, mesh, params, kv, rep):
        self.mesh = mesh
        self._params_items = tuple(sorted(params.items()))
        self.kv = kv
        self.rep = rep

    @property
    def params(self):
        return dict(self._params_items)

    @property
    def shard_label(self):
        """The `shard` label the ops plane's compile metrics carry
        (serving_xla_compiles_total{..., shard=}): the mesh shape in
        axis=size form, e.g. "mp2xdp1" — so a fleet scraping several
        mesh configs can tell whose jit cache went cold.  A sequence-
        parallel mesh (long-context round) appends "xsp{n}"; sp=1
        keeps the exact pre-round label so existing dashboards and
        the r14 gauge assertions never see a rename."""
        shape = dict(self.mesh.shape)
        label = f"mp{shape.get('mp', 1)}xdp{shape.get('dp', 1)}"
        if shape.get("sp", 1) > 1:
            label += f"xsp{shape['sp']}"
        return label

    def _key(self):
        return (self.mesh, self._params_items, self.kv, self.rep)

    def __hash__(self):
        return hash(self._key())

    def __eq__(self, other):
        return (isinstance(other, DecodeShardings)
                and self._key() == other._key())


def build_decode_shardings(mesh, params, kv_dtype=None):
    """Assemble the DecodeShardings bundle for one server's param dict
    (call AFTER quantize_weights so the ::w8c/::w8s keys are in)."""
    k_spec, _ = kv_pool_specs(kv_dtype)
    kv = jax.tree.map(lambda sp: NamedSharding(mesh, sp), k_spec,
                      is_leaf=lambda x: isinstance(x, P))
    return DecodeShardings(mesh, param_shardings(mesh, params), kv,
                           NamedSharding(mesh, P()))


def place_decode_params(mesh, params):
    """device_put the param dict with the plan's shardings (the
    explicit placement half; the jit's in_shardings re-assert it)."""
    sh = param_shardings(mesh, params)
    return {name: jax.device_put(v, sh[name])
            for name, v in params.items()}


def place_kv_pool(mesh, cache):
    """device_put the cache's K/V pool arrays with the per-shard block
    layout (heads over mp, blocks over dp).  Host bookkeeping — block
    tables, refcounts, the prefix index, retention — is untouched: the
    whole point is that every shard holds its slice of every block, so
    block INDICES mean the same thing on every device."""
    k_spec, v_spec = kv_pool_specs(cache.kv_dtype)
    as_sh = (lambda spec: jax.tree.map(
        lambda sp: NamedSharding(mesh, sp), spec,
        is_leaf=lambda x: isinstance(x, P)))
    cache.swap_arrays(jax.device_put(cache.k_blocks, as_sh(k_spec)),
                      jax.device_put(cache.v_blocks, as_sh(v_spec)))
