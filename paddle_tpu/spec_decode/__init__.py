"""Speculative decoding for the paged serving engine (round 11).

Decode is one dispatch per token per round and bandwidth-bound, not
FLOP-bound (PERF.md: the per-token parameter stream is the roofline), so
the chip can VERIFY K proposed tokens for nearly the price of decoding
one. Three layers:

* `config` — `SpecConfig`, the eagerly-validated speculation knob
  bundle (draft budget K, drafter choice, n-gram match window);
* `drafter` — the `Drafter` protocol plus the self-drafting
  `NgramDrafter` (per-slot suffix lookup over the request's own
  prompt + generated tokens — no second model) and the
  `DraftModelDrafter` seam for a small draft model sharing the target
  tokenizer;
* `verifier` — host-side assembly of the packed verification plan (the
  rejection-sampling half runs on device: `nn.decode.packed_verify`
  scores every slot's drafts in ONE ragged dispatch, reusing the PR 3
  packed-prefill kernel shape, and decides acceptance with the exact
  per-slot sampling pipeline plain decode would run).

Because the PR 5 PRNG is counter-based (`fold_in(seed, step)`), the
target's token at every position is deterministic given its logits, so
rejection sampling reduces to exact match and fixed-seed output is
token-identical to non-speculative decode regardless of how many
tokens were accepted (greedy degenerates to argmax match). Rejected
draft positions roll the paged cache back via
`PagedKVCache.truncate_seq`. See docs/SERVING.md ("Speculative
decoding").
"""
from .config import SpecConfig  # noqa: F401
from .drafter import Drafter, DraftModelDrafter, NgramDrafter  # noqa: F401
from .verifier import VerifyPlan, build_verify_plan  # noqa: F401

__all__ = ["SpecConfig", "Drafter", "NgramDrafter", "DraftModelDrafter",
           "VerifyPlan", "build_verify_plan"]
