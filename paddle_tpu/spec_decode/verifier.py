"""Host-side verification planning: pack per-slot draft proposals into
the ONE ragged dispatch `nn.decode.packed_verify` scores.

The plan layout deliberately mirrors the PR 3 packed-prefill contract
(inference/serving.py `_prefill_packed`): each speculating slot
contributes a region `[last_token, draft_1 .. draft_k]` aligned to
`pack_align` (128 on TPU — the Pallas ragged-prefill kernel's
query-tile contract — 8 elsewhere), the packed length T buckets to a
power of two and the plan row count P likewise, so the compile count
stays logarithmic in the speculation budget exactly as it is for
prefill chunks. `sample_idx` is the per-row [K1] readout matrix —
"per-row sample indices" over the packed stream — and `dlen` both
carries each row's draft count and marks padding rows (dlen == 0).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np


def _pow2(n):
    p = 1
    while p < n:
        p *= 2
    return p


@dataclass
class VerifyPlan:
    """One round's packed verification plan.

    slots: plan row -> server slot index (real rows only; the device
        arrays are padded to `P` rows).
    drafts: per real row, the proposed tokens (1..K each).
    write_pos: per real row, the cache position its last emitted token
        is written at — drafts occupy write_pos+1 .. write_pos+k, and
        rollback truncates the sequence to write_pos + accepted + 1.
    toks/seg/pos: the packed [T] stream (pos -1 marks packing pad).
    sample_idx: [P, K1] int32 — packed index of each row's verify
        position (clamped to the region end past the row's drafts).
    dlen: [P] int32 draft counts; 0 is a real row with no drafts this
        round (one verify position = one decode step, so draft-free
        decode slots ride the same dispatch instead of forcing a
        second, plain dispatch into the round); -1 marks a padding row.
    steps: [P] int32 base PRNG step per row (generated-token count).
    """

    slots: list
    drafts: list
    write_pos: list
    toks: np.ndarray
    seg: np.ndarray
    pos: np.ndarray
    sample_idx: np.ndarray
    dlen: np.ndarray
    steps: np.ndarray

    @property
    def rows(self):
        return len(self.slots)

    def grow_updates(self, seqs):
        """(seq, new_len) pairs covering every row's speculative write
        horizon, for one atomic `PagedKVCache.ensure_many`."""
        return [(seqs[r], self.write_pos[r] + len(self.drafts[r]) + 1)
                for r in range(len(self.slots))]


def build_verify_plan(entries, max_draft_tokens, pack_align,
                      min_rows=None):
    """Assemble a `VerifyPlan` from per-slot proposals.

    entries: list of (slot_idx, last_token, write_pos, base_step,
        drafts) — drafts a 1-D int array of <= K proposals (empty =
        the slot rides along draft-free and emits its one plain-decode
        token from the shared dispatch).
    max_draft_tokens: K — fixes the readout width K1 = K + 1 so the
        verify program never specializes per draft-count combination.
    pack_align: the packed-region alignment (the serving engine's
        `_pack_align`).
    min_rows: pad the plan to at least this many rows (the server
        passes max_slots).

    The plan shape is PINNED, not content-sized: every region spans
    `align * ceil(K1 / align)` tokens and the row count buckets to
    pow2(max(rows, min_rows)), so a server compiles ONE verify variant
    per sampling mode — verification runs every scheduler round, and
    per-round shape churn would turn into a compile storm (the prefill
    chunk path tolerates log-many buckets because each request
    prefills once; verify cannot).

    Returns None when `entries` is empty.
    """
    if not entries:
        return None
    align = int(pack_align)
    K1 = int(max_draft_tokens) + 1
    region = -(-K1 // align) * align
    offsets = [r * region for r in range(len(entries))]
    P = _pow2(max(len(entries), int(min_rows or 1)))
    T = P * region
    toks = np.zeros((T,), np.int32)
    seg = np.zeros((T,), np.int32)
    pos = np.full((T,), -1, np.int32)
    sample_idx = np.zeros((P, K1), np.int32)
    dlen = np.full((P,), -1, np.int32)      # -1 = padding row
    steps = np.zeros((P,), np.int32)
    slots, all_drafts, write_pos = [], [], []
    for r, (slot, last, wpos, step, drafts) in enumerate(entries):
        drafts = np.asarray(drafts, np.int32).reshape(-1)
        k = int(drafts.size)
        o = offsets[r]
        toks[o] = int(last)
        toks[o + 1:o + 1 + k] = drafts
        seg[o:o + 1 + k] = r
        pos[o:o + 1 + k] = np.arange(wpos, wpos + 1 + k, dtype=np.int32)
        # readout j for j <= k; clamped past the region so the gather
        # stays in-bounds (the device masks those positions via dlen)
        sample_idx[r] = o + np.minimum(np.arange(K1), k)
        dlen[r] = k
        steps[r] = int(step)
        slots.append(slot)
        all_drafts.append(drafts)
        write_pos.append(int(wpos))
    return VerifyPlan(slots=slots, drafts=all_drafts,
                      write_pos=write_pos, toks=toks, seg=seg, pos=pos,
                      sample_idx=sample_idx, dlen=dlen, steps=steps)
