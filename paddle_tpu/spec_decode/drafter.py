"""Drafters: cheap per-slot token proposers for speculative decoding.

A drafter sees ONE slot's full context (prompt + every token generated
so far) each scheduler round and proposes up to `max_tokens` likely next
tokens. Proposals are free to be wrong — the packed verification
dispatch accepts exactly the prefix the target model would have emitted
and the paged cache rolls the rest back — so a drafter's only job is to
be cheap and right often enough to pay for the verify dispatch.

`NgramDrafter` is the self-drafting baseline (prompt-lookup decoding):
no second model, no device work — the proposal is a suffix-match lookup
over the slot's own token history, which is exactly right for the
repetitive/agentic traffic speculation targets (code, tool-call loops,
quote-heavy chat, structured output).

`DraftModelDrafter` is the seam for a real draft model: any model
sharing the target's tokenizer whose `generate(ids, n)` returns a
greedy continuation can propose. The reference implementation here runs
a dense B=1 generate per slot per round — correct but dispatch-heavy;
a production drafter would keep its own paged cache and batch its
proposals (that engine plugs in through the same one-method protocol).
"""
from __future__ import annotations

from typing import Protocol, runtime_checkable

import numpy as np


@runtime_checkable
class Drafter(Protocol):
    """One method: propose up to `max_tokens` continuations of
    `token_ids` (the slot's prompt + generated tokens, 1-D int array).
    Return a 1-D int array of 0..max_tokens proposals — an empty return
    means "no idea", and the slot takes plain decode this round."""

    def propose(self, token_ids, max_tokens: int): ...


class NgramDrafter:
    """Self-drafting n-gram / prompt-lookup drafter.

    Finds the longest suffix of the context (between `min_match` and
    `max_match` tokens, longest first) that also occurs EARLIER in the
    context, and proposes the tokens that followed that most recent
    earlier occurrence. O(context · max_match) numpy compares per call —
    microseconds at serving context lengths, no device work.
    """

    def __init__(self, max_match=3, min_match=1):
        self.max_match = int(max_match)
        self.min_match = int(min_match)
        if not 1 <= self.min_match <= self.max_match:
            raise ValueError(
                f"need 1 <= min_match <= max_match, got "
                f"min_match={min_match!r} max_match={max_match!r}")

    def propose(self, token_ids, max_tokens):
        ctx = np.asarray(token_ids).reshape(-1)
        n = int(ctx.size)
        max_tokens = int(max_tokens)
        if max_tokens < 1 or n < self.min_match + 1:
            return np.empty((0,), np.int32)
        for m in range(min(self.max_match, n - 1), self.min_match - 1,
                       -1):
            pattern = ctx[n - m:]
            # candidate starts i < n - m (a PROPER earlier occurrence,
            # so at least one follow token exists at i + m <= n - 1)
            windows = np.lib.stride_tricks.sliding_window_view(
                ctx[:n - 1], m)                  # starts 0 .. n-m-1
            hits = np.flatnonzero((windows == pattern).all(axis=1))
            if hits.size == 0:
                continue
            # PERIODIC EXTENSION off the most recent occurrence: the
            # suffix recurring d = (n-m) - i tokens before itself is
            # evidence of a period-d pattern, so extrapolate the d
            # tokens after the occurrence cyclically. This always
            # fills max_tokens (index i+m+(j mod d) <= n-1 by
            # construction) — without it, a fresh token run could
            # never be proposed further than it has already repeated,
            # capping every early proposal at 1-2 tokens.
            i = int(hits[-1])
            d = (n - m) - i
            idx = i + m + (np.arange(max_tokens) % d)
            return ctx[idx].astype(np.int32)
        return np.empty((0,), np.int32)


class DraftModelDrafter:
    """Model-based drafting seam: greedy-continue the context with a
    small causal LM sharing the target tokenizer. `model` is anything
    with `generate(ids[1, S], n) -> [1, S + n]` (a `models.gpt2.GPT2`
    qualifies). Note the cost model in the module docstring — this
    reference implementation is one dense generate per slot per round."""

    def __init__(self, model):
        self._model = model

    def propose(self, token_ids, max_tokens):
        ctx = np.asarray(token_ids, np.int32).reshape(-1)
        max_tokens = int(max_tokens)
        if max_tokens < 1 or ctx.size == 0:
            return np.empty((0,), np.int32)
        out = self._model.generate(ctx[None], max_tokens)
        out = np.asarray(getattr(out, "numpy", lambda: out)())[0]
        return out[ctx.size:].astype(np.int32)
