"""Speculation configuration with EAGER validation.

`SpecConfig` follows the `SamplingParams` house rule: a bad value raises
a ValueError that NAMES the offending field and value at construction
time, never as a jit-time shape failure inside a compiled verify
dispatch.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class SpecConfig:
    """Speculative-decoding knobs for `PagedGenerationServer`.

    max_draft_tokens: the draft budget K — each eligible slot proposes
        up to K tokens per scheduler round; one packed verification
        dispatch scores all proposals and emits between 1 and K+1
        tokens per slot (1 = first draft rejected, exactly what plain
        decode would have emitted; K+1 = all accepted plus the bonus
        token).
    drafter: "ngram" (the default self-drafting prompt-lookup drafter —
        no second model) or any object implementing the
        `drafter.Drafter` protocol (e.g. a `DraftModelDrafter` wrapping
        a small model that shares the target tokenizer).
    ngram_max_match / ngram_min_match: the n-gram drafter's suffix
        match window — it tries the longest suffix first and falls back
        down to min_match before giving up (no proposal = the slot
        takes plain decode this round).
    """

    max_draft_tokens: int = 4
    drafter: object = "ngram"
    ngram_max_match: int = 3
    ngram_min_match: int = 1

    def __post_init__(self):
        for name in ("max_draft_tokens", "ngram_max_match",
                     "ngram_min_match"):
            v = getattr(self, name)
            try:
                iv = int(v)
                if iv != v or iv < 1:
                    raise ValueError
            except (TypeError, ValueError):
                raise ValueError(
                    f"{name} must be an int >= 1, got {v!r}") from None
            object.__setattr__(self, name, iv)
        if self.ngram_min_match > self.ngram_max_match:
            raise ValueError(
                f"ngram_min_match ({self.ngram_min_match}) must be <= "
                f"ngram_max_match ({self.ngram_max_match})")
        if isinstance(self.drafter, str):
            if self.drafter != "ngram":
                raise ValueError(
                    f"drafter must be 'ngram' or a Drafter instance, "
                    f"got {self.drafter!r}")
        elif not callable(getattr(self.drafter, "propose", None)):
            raise ValueError(
                f"drafter must be 'ngram' or implement propose(); "
                f"got {self.drafter!r}")

    def make_drafter(self):
        """Instantiate the configured drafter (a fresh NgramDrafter for
        the string form; the instance itself otherwise)."""
        if isinstance(self.drafter, str):
            from .drafter import NgramDrafter

            return NgramDrafter(max_match=self.ngram_max_match,
                                min_match=self.ngram_min_match)
        return self.drafter
