"""paddle.jit — dygraph→static compilation.

Reference: python/paddle/fluid/dygraph/jit.py + dygraph_to_static/. TPU-first
rework: instead of AST transpilation to ProgramDesc, `to_static` functionalizes
the layer (params become pytree inputs) and hands the SAME eager code to
`jax.jit` — XLA compiles the whole forward (or train step) into one fused TPU
computation, cached per input shape.
"""
from __future__ import annotations

import functools

import jax
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._value
    return x


def _wrap(x):
    if hasattr(x, "shape") and hasattr(x, "dtype") and not isinstance(x, Tensor):
        return Tensor(x)
    return x


class StaticFunction:
    """Compiled callable. Parameters and buffers of every Layer touched are
    passed functionally so weight updates between calls don't retrigger
    compilation (they're inputs, not constants)."""

    def __init__(self, fn, layer=None, input_spec=None, donate_params=False):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        self._compiled = None
        self._training = None

    def _get_layer(self, args):
        if self._layer is not None:
            return self._layer, args
        if args and isinstance(args[0], Layer):
            return args[0], args[1:]
        return None, args

    def __call__(self, *args, **kwargs):
        from ..core import rng
        layer, call_args = self._get_layer(args)

        if layer is None:
            if self._compiled is None:
                self._compiled = jax.jit(
                    lambda a, k: jax.tree_util.tree_map(
                        _unwrap, self._fn(*a, **k),
                        is_leaf=lambda x: isinstance(x, Tensor)))
            raw_args = jax.tree_util.tree_map(
                _unwrap, call_args, is_leaf=lambda x: isinstance(x, Tensor))
            raw_kw = jax.tree_util.tree_map(
                _unwrap, kwargs, is_leaf=lambda x: isinstance(x, Tensor))
            out = self._compiled(raw_args, raw_kw)
            return jax.tree_util.tree_map(_wrap, out)

        # layer path: functionalize params/buffers
        if self._compiled is None or self._training != layer.training:
            self._training = layer.training
            fn = self._fn

            def pure(params, buffers, a, k, key):
                rng_saved = rng._default_generator._key, rng._default_generator._count
                rng._default_generator._key = key
                rng._default_generator._count = 0
                saved_p, saved_b = layer.functional_state()
                layer.load_functional_state(params, buffers)
                try:
                    out = fn(layer, *a, **k) if not hasattr(fn, "__self__") \
                        else fn(*a, **k)
                    out_raw = jax.tree_util.tree_map(
                        _unwrap, out, is_leaf=lambda x: isinstance(x, Tensor))
                    _, new_bufs = layer.functional_state()
                    return out_raw, new_bufs
                finally:
                    # restore concrete values so the live layer never holds
                    # trace-time tracers after compilation
                    layer.load_functional_state(saved_p, saved_b)
                    (rng._default_generator._key,
                     rng._default_generator._count) = rng_saved
            self._compiled = jax.jit(pure)

        params, buffers = layer.functional_state()
        raw_args = jax.tree_util.tree_map(
            _unwrap, call_args, is_leaf=lambda x: isinstance(x, Tensor))
        raw_kw = jax.tree_util.tree_map(
            _unwrap, kwargs, is_leaf=lambda x: isinstance(x, Tensor))
        out, new_bufs = self._compiled(params, buffers, raw_args, raw_kw,
                                       rng.next_key())
        layer.load_functional_state(None, new_bufs)
        return jax.tree_util.tree_map(_wrap, out)

    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(self._fn)
        except OSError:
            return "<compiled>"


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """@paddle.jit.to_static — compile a function or Layer.forward with XLA."""
    def deco(fn):
        if isinstance(fn, Layer):
            layer = fn
            sf = StaticFunction(type(layer).forward, layer=layer,
                                input_spec=input_spec)
            layer.forward = sf
            return layer
        wrapped = StaticFunction(fn, input_spec=input_spec)
        functools.update_wrapper(wrapped, fn, updated=[])
        return wrapped
    if function is not None:
        return deco(function)
    return deco


class TranslatedLayer(Layer):
    """Inference-loaded model (ref: fluid/dygraph/io.py TranslatedLayer)."""

    def __init__(self, state, forward_fn):
        super().__init__()
        self._state = state
        self._forward_fn = forward_fn

    def forward(self, *args):
        return self._forward_fn(self._state, *args)


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save — params + a spec of the forward for later load."""
    from ..framework.io import save as fsave
    state = {k: v for k, v in layer.state_dict().items()}
    fsave({"state_dict": state,
           "class_name": type(layer).__name__}, path + ".pdparams")


def load(path, **configs):
    from ..framework.io import load as fload
    payload = fload(path + ".pdparams")
    return payload


def not_to_static(fn):
    fn.__not_to_static__ = True
    return fn


def ignore_module(modules):
    pass


class ProgramTranslator:
    """API-parity shim (ref: dygraph_to_static/program_translator.py)."""

    _instance = None
    enable_to_static = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, flag):
        ProgramTranslator.enable_to_static = flag


def enable_to_static(flag=True):
    ProgramTranslator.get_instance().enable(flag)


declarative = to_static  # 1.x decorator name (ref: fluid/dygraph/jit.py)
print_function = None


def set_verbosity(level=0, also_to_stdout=False):
    """Dygraph-to-static logging verbosity (ref: dygraph_to_static/logging_utils)."""
    _dy2static_state["verbosity"] = level


def set_code_level(level=100, also_to_stdout=False):
    _dy2static_state["code_level"] = level


_dy2static_state = {"verbosity": 0, "code_level": 0}


class _Dy2StaticModule:
    """Namespace shim for paddle.jit.dy2static (program translator info)."""
    set_verbosity = staticmethod(set_verbosity)
    set_code_level = staticmethod(set_code_level)


dy2static = _Dy2StaticModule()


class TracedLayer:
    """Trace a dygraph Layer into a static callable (ref: fluid/dygraph/jit.py
    TracedLayer). On the XLA backend tracing IS jit: the layer's forward is
    wrapped by to_static and the in/out specs recorded from the example."""

    def __init__(self, layer, inputs):
        self._layer = layer
        self._fn = to_static(layer.forward if hasattr(layer, "forward")
                             else layer)
        self._example = inputs

    @staticmethod
    def trace(layer, inputs):
        tl = TracedLayer(layer, inputs)
        out = tl._fn(*inputs)
        return out, tl

    def __call__(self, *args):
        return self._fn(*args)

    def save_inference_model(self, path, feed=None, fetch=None):
        save(self._layer, path, input_spec=None)
