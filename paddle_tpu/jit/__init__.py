"""paddle.jit — dygraph→static compilation.

Reference: python/paddle/fluid/dygraph/jit.py + dygraph_to_static/. TPU-first
rework: instead of AST transpilation to ProgramDesc, `to_static` functionalizes
the layer (params become pytree inputs) and hands the SAME eager code to
`jax.jit` — XLA compiles the whole forward (or train step) into one fused TPU
computation, cached per input shape.
"""
from __future__ import annotations

import functools

import jax
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._value
    return x


def _wrap(x):
    if hasattr(x, "shape") and hasattr(x, "dtype") and not isinstance(x, Tensor):
        return Tensor(x)
    return x


class StaticFunction:
    """Compiled callable. Parameters and buffers of every Layer touched are
    passed functionally so weight updates between calls don't retrigger
    compilation (they're inputs, not constants)."""

    # Layer.__call__ must NOT run the hook protocol eagerly around this —
    # the traced body runs it (with traced params); see pure()
    _runs_layer_hooks = True

    def __init__(self, fn, layer=None, input_spec=None, donate_params=False):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        self._compiled = None
        self._training = None
        # trace snapshot is per-call state: keep it thread-local so two
        # threads calling the same StaticFunction (or a retrace while
        # another call is in flight) can't restore each other's snapshot
        import threading
        self._tls = threading.local()

    def _get_layer(self, args):
        if self._layer is not None:
            return self._layer, args
        if args and isinstance(args[0], Layer):
            return args[0], args[1:]
        return None, args

    def __call__(self, *args, **kwargs):
        from ..core import rng
        layer, call_args = self._get_layer(args)

        if layer is None:
            if self._compiled is None:
                def _traced_free(a, k):
                    # runs at TRACE time only: snapshot live layer state so
                    # the finally-restore below can undo tracer writes to
                    # closure-captured layers (BN running stats etc.) —
                    # jit is pure, such mutations cannot persist, and
                    # leaking the tracers would crash the next eager use.
                    # Steady-state (cached-compile) calls never execute
                    # this body, so they skip the O(all-layers) scan.
                    from ..nn.layer.layers import _LIVE_LAYERS
                    self._tls.trace_snap = [
                        (t, t._value) for live in list(_LIVE_LAYERS)
                        for t in list(live.parameters(
                            include_sublayers=False))
                        + list(live.buffers(include_sublayers=False))]
                    from ..core.autograd import functional_trace
                    with functional_trace():
                        out = self._fn(*a, **k)
                    return jax.tree_util.tree_map(
                        _unwrap, out,
                        is_leaf=lambda x: isinstance(x, Tensor))
                self._compiled = jax.jit(_traced_free)
            raw_args = jax.tree_util.tree_map(
                _unwrap, call_args, is_leaf=lambda x: isinstance(x, Tensor))
            raw_kw = jax.tree_util.tree_map(
                _unwrap, kwargs, is_leaf=lambda x: isinstance(x, Tensor))
            try:
                out = self._compiled(raw_args, raw_kw)
            finally:
                snap = getattr(self._tls, "trace_snap", None)
                if snap is not None:
                    self._tls.trace_snap = None
                    import jax.core as _jcore
                    snapped = set()
                    for t, v in snap:
                        snapped.add(id(t))
                        if isinstance(t._value, _jcore.Tracer):
                            t._value = v
                    # layers CREATED during the trace have no pre-trace
                    # values to restore — their params ARE tracers. A
                    # layer that outlives the call (cached in a closure/
                    # global) will crash on its next eager use; warn now
                    # with an actionable message. (Raising would break
                    # harmless inline temporaries that are about to be
                    # garbage-collected.)
                    import warnings

                    from ..nn.layer.layers import _LIVE_LAYERS
                    for live in list(_LIVE_LAYERS):
                        for t in list(live.parameters(
                                include_sublayers=False)) \
                                + list(live.buffers(
                                    include_sublayers=False)):
                            if id(t) not in snapped and isinstance(
                                    t._value, _jcore.Tracer):
                                warnings.warn(
                                    f"Layer {type(live).__name__} was "
                                    "constructed inside a @to_static free "
                                    "function and holds trace-time "
                                    "tracers; if it is reused eagerly it "
                                    "will fail — construct layers before "
                                    "decorating, or decorate the Layer "
                                    "itself", stacklevel=2)
                                break
            return jax.tree_util.tree_map(_wrap, out)

        # layer path: functionalize params/buffers
        if self._compiled is None or self._training != layer.training:
            self._training = layer.training
            fn = self._fn

            def pure(params, buffers, a, k, key):
                rng_saved = rng._default_generator._key, rng._default_generator._count
                rng._default_generator._key = key
                rng._default_generator._count = 0
                saved_p, saved_b = layer.functional_state()
                layer.load_functional_state(params, buffers)
                try:
                    # run the Layer.__call__ hook protocol: pre-forward
                    # hooks (weight_norm's reparameterization, user
                    # hooks) must see the TRACED params, not go stale —
                    # __call__ itself can't be used (layer.forward IS
                    # this StaticFunction)
                    from ..core.autograd import functional_trace
                    with functional_trace():
                        for hook in layer._forward_pre_hooks.values():
                            hout = hook(layer, a)
                            if hout is not None:
                                a = hout if isinstance(hout, tuple) \
                                    else (hout,)
                        out = fn(layer, *a, **k) \
                            if not hasattr(fn, "__self__") else fn(*a, **k)
                        for hook in layer._forward_post_hooks.values():
                            hout = hook(layer, a, out)
                            if hout is not None:
                                out = hout
                    out_raw = jax.tree_util.tree_map(
                        _unwrap, out, is_leaf=lambda x: isinstance(x, Tensor))
                    _, new_bufs = layer.functional_state()
                    return out_raw, new_bufs
                finally:
                    # restore concrete values so the live layer never holds
                    # trace-time tracers after compilation
                    layer.load_functional_state(saved_p, saved_b)
                    (rng._default_generator._key,
                     rng._default_generator._count) = rng_saved
            self._compiled = jax.jit(pure)

        params, buffers = layer.functional_state()
        raw_args = jax.tree_util.tree_map(
            _unwrap, call_args, is_leaf=lambda x: isinstance(x, Tensor))
        raw_kw = jax.tree_util.tree_map(
            _unwrap, kwargs, is_leaf=lambda x: isinstance(x, Tensor))
        out, new_bufs = self._compiled(params, buffers, raw_args, raw_kw,
                                       rng.next_key())
        layer.load_functional_state(None, new_bufs)
        # derived attributes written by hooks during the trace (e.g.
        # weight_norm's reparameterized weight) hold dead tracers now;
        # ask their hooks to recompute from the live concrete params
        for sub in layer.sublayers(include_self=True):
            for h in sub._forward_pre_hooks.values():
                refresh = getattr(h, "refresh_after_trace", None)
                if refresh is not None:
                    refresh(sub)
        return jax.tree_util.tree_map(_wrap, out)

    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(self._fn)
        except OSError:
            return "<compiled>"


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """@paddle.jit.to_static — compile a function or Layer.forward with XLA."""
    def deco(fn):
        if isinstance(fn, Layer):
            layer = fn
            sf = StaticFunction(type(layer).forward, layer=layer,
                                input_spec=input_spec)
            layer.forward = sf
            return layer
        wrapped = StaticFunction(fn, input_spec=input_spec)
        functools.update_wrapper(wrapped, fn, updated=[])
        return wrapped
    if function is not None:
        return deco(function)
    return deco


class TranslatedLayer(Layer):
    """Inference-loaded model (ref: fluid/dygraph/io.py TranslatedLayer).

    Rebuilt from the serialized StableHLO program + params archive alone —
    the original model class is NOT needed (VERDICT r2 missing #1). The
    deserialized `jax.export.Exported` is AOT XLA; `forward` re-jits its
    call for caching across invocations."""

    def __init__(self, exported, params, bufs, meta):
        super().__init__()
        self._exported = exported
        # weights arrive device-committed from read_artifact (one
        # transfer at load; host numpy here would re-ship them per call)
        self._params = params
        self._bufs = bufs
        self._meta = meta
        self._call = jax.jit(exported.call)

    def forward(self, *args):
        raw = [a._value if isinstance(a, Tensor) else jnp_asarray(a)
               for a in args]
        out = self._call(self._params, self._bufs, *raw)
        return jax.tree_util.tree_map(_wrap, out)

    @property
    def program_bytes(self):
        """The serialized StableHLO module (deployable artifact)."""
        return self._exported.mlir_module_serialized


def jnp_asarray(x):
    import jax.numpy as jnp
    return jnp.asarray(x)


_PDMODEL_MAGIC = b"PTPUEXP1"


def write_artifact(path_prefix, exported, params, bufs, meta):
    """Write the (.pdmodel, .pdiparams) artifact pair: magic + JSON header +
    serialized StableHLO module; params/buffers as a plain npz."""
    import io as _io
    import json
    import os

    import numpy as np

    parent = os.path.dirname(path_prefix)
    if parent:
        os.makedirs(parent, exist_ok=True)
    blob = exported.serialize()
    header = json.dumps(meta).encode("utf-8")
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(_PDMODEL_MAGIC)
        f.write(len(header).to_bytes(8, "little"))
        f.write(header)
        f.write(blob)
    arrays = {}

    def put(key, v):
        a = np.asarray(v)
        if a.dtype.isbuiltin != 1:
            # npz writes extension dtypes (bfloat16, float8_*) with a raw
            # '|V' descr that np.load cannot interpret — a bf16 artifact
            # (the recommended SERVING dtype) then fails at Exported.call.
            # Store a bit-preserving uint8 view plus a dtype sidecar and
            # view back on load.
            arrays["dt:" + key] = np.frombuffer(
                a.dtype.name.encode(), dtype=np.uint8)
            a = a.view(np.uint8).reshape(a.shape + (a.dtype.itemsize,))
        arrays[key] = a

    for k, v in (params or {}).items():
        put("p:" + k, v)
    for k, v in (bufs or {}).items():
        put("b:" + k, v)
    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    with open(path_prefix + ".pdiparams", "wb") as f:
        f.write(buf.getvalue())
    return path_prefix + ".pdmodel"


def read_artifact(path_prefix):
    """Read back (exported, params, bufs, meta) from the artifact pair."""
    import json

    import numpy as np
    from jax import export as jexport

    with open(path_prefix + ".pdmodel", "rb") as f:
        magic = f.read(len(_PDMODEL_MAGIC))
        if magic != _PDMODEL_MAGIC:
            raise ValueError(
                f"{path_prefix}.pdmodel is not a paddle_tpu jit.save "
                f"artifact (bad magic {magic!r}) — re-save with jit.save")
        hlen = int.from_bytes(f.read(8), "little")
        meta = json.loads(f.read(hlen).decode("utf-8"))
        blob = f.read()
    exported = jexport.deserialize(blob)
    with open(path_prefix + ".pdiparams", "rb") as f:
        npz = np.load(f, allow_pickle=False)

        def get(k):
            a = npz[k]
            dk = "dt:" + k
            if dk in npz.files:
                import ml_dtypes  # noqa: F401 — registers extension dtypes
                dt = np.dtype(bytes(npz[dk]).decode())
                a = a.view(dt).reshape(a.shape[:-1])
            return a

        import jax.numpy as jnp
        # COMMIT weights to device HERE, once, for every artifact
        # consumer (TranslatedLayer, static LoadedProgram, predictor):
        # host numpy params make jit re-transfer them on EVERY call —
        # ~130MB/call on the exported decode artifact, 8x slower than
        # in-process (r5 serving A/B: 3,460ms -> 172ms per call)
        params = {k[2:]: jnp.asarray(get(k)) for k in npz.files
                  if k.startswith("p:")}
        bufs = {k[2:]: jnp.asarray(get(k)) for k in npz.files
                if k.startswith("b:")}
    return exported, params, bufs, meta


def _symbolic_dims(n):
    """n fresh symbolic dims sharing ONE export scope — jax.export rejects
    mixing scopes within a single export, so per-dim symbolic_shape calls
    would break any model with two or more dynamic dims."""
    from jax import export as jexport
    if n == 0:
        return []
    return list(jexport.symbolic_shape(
        ", ".join(f"_d{i}" for i in range(n))))


def _resolve_input_specs(input_spec):
    """InputSpec/Tensor/ndarray list -> ShapeDtypeStructs. None/-1 dims
    become jax.export symbolic dimensions, so the serialized program stays
    batch-size-polymorphic like the reference's -1 feed shapes."""
    from ..static.program import InputSpec

    def is_dyn(d):
        return d is None or (isinstance(d, int) and d < 0)

    n_dyn = sum(1 for s in input_spec if isinstance(s, InputSpec)
                for d in s.shape if is_dyn(d))
    syms = iter(_symbolic_dims(n_dyn))
    specs = []
    for s in input_spec:
        if isinstance(s, InputSpec):
            dims = tuple(next(syms) if is_dyn(d) else d for d in s.shape)
            specs.append(jax.ShapeDtypeStruct(dims, s.dtype))
        elif isinstance(s, Tensor):
            specs.append(jax.ShapeDtypeStruct(tuple(s.shape),
                                              s._value.dtype))
        elif hasattr(s, "shape") and hasattr(s, "dtype"):
            specs.append(jax.ShapeDtypeStruct(tuple(s.shape),
                                              jnp_asarray(s).dtype))
        else:
            raise TypeError(f"input_spec entry {type(s)} not understood")
    return specs


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save — serialize the layer as a deployment artifact
    (ref: fluid/io.py:1198 save_inference_model + jit.py save):

    - `path.pdmodel`  — the traced forward as a serialized StableHLO
      module (jax.export), loadable and runnable with NO Python model
      class; multi-platform (cpu+tpu) when the graph allows it
    - `path.pdiparams` — params + buffers as a plain npz archive

    input_spec: list of InputSpec / Tensor / ndarray giving the forward's
    input shapes+dtypes (required — tracing needs concrete avals).
    """
    from jax import export as jexport

    if input_spec is None:
        raise ValueError(
            "jit.save needs input_spec=[InputSpec(shape, dtype), ...] (or "
            "example Tensors) to trace the forward for export")
    was_training = layer.training
    layer.eval()
    try:
        params, bufs = layer.functional_state()

        def pure(params, bufs, *xs):
            saved = layer.functional_state()
            layer.load_functional_state(params, bufs)
            try:
                out = layer(*[Tensor(x) for x in xs])
            finally:
                layer.load_functional_state(*saved)
            return jax.tree_util.tree_map(
                lambda t: t._value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))

        in_specs = _resolve_input_specs(input_spec)
        p_specs = jax.tree_util.tree_map(
            lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), params)
        b_specs = jax.tree_util.tree_map(
            lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), bufs)
        jf = jax.jit(pure)
        try:  # multi-platform artifact when every op lowers for both
            exported = jexport.export(jf, platforms=("cpu", "tpu"))(
                p_specs, b_specs, *in_specs)
        except Exception:
            exported = jexport.export(jf)(p_specs, b_specs, *in_specs)

        meta = {
            "format": "paddle_tpu.jit/1",
            "class_name": type(layer).__name__,
            "platforms": list(exported.platforms),
            "in_specs": [[[str(d) for d in s.shape], str(s.dtype)]
                         for s in in_specs],
        }
        write_artifact(path, exported, params, bufs, meta)
    finally:
        if was_training:
            layer.train()
    return path + ".pdmodel"


def load(path, **configs):
    """paddle.jit.load — rebuild a runnable TranslatedLayer from the
    .pdmodel (StableHLO) + .pdiparams archive. No model class import."""
    exported, params, bufs, meta = read_artifact(path)
    return TranslatedLayer(exported, params, bufs, meta)


def not_to_static(fn):
    fn.__not_to_static__ = True
    return fn


def ignore_module(modules):
    pass


class ProgramTranslator:
    """API-parity shim (ref: dygraph_to_static/program_translator.py)."""

    _instance = None
    enable_to_static = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, flag):
        ProgramTranslator.enable_to_static = flag


def enable_to_static(flag=True):
    ProgramTranslator.get_instance().enable(flag)


declarative = to_static  # 1.x decorator name (ref: fluid/dygraph/jit.py)
print_function = None


def set_verbosity(level=0, also_to_stdout=False):
    """Dygraph-to-static logging verbosity (ref: dygraph_to_static/logging_utils)."""
    _dy2static_state["verbosity"] = level


def set_code_level(level=100, also_to_stdout=False):
    _dy2static_state["code_level"] = level


_dy2static_state = {"verbosity": 0, "code_level": 0}


class _Dy2StaticModule:
    """Namespace shim for paddle.jit.dy2static (program translator info)."""
    set_verbosity = staticmethod(set_verbosity)
    set_code_level = staticmethod(set_code_level)


dy2static = _Dy2StaticModule()


class TracedLayer:
    """Trace a dygraph Layer into a static callable (ref: fluid/dygraph/jit.py
    TracedLayer). On the XLA backend tracing IS jit: the layer's forward is
    wrapped by to_static and the in/out specs recorded from the example."""

    def __init__(self, layer, inputs):
        self._layer = layer
        self._fn = to_static(layer.forward if hasattr(layer, "forward")
                             else layer)
        self._example = inputs

    @staticmethod
    def trace(layer, inputs):
        tl = TracedLayer(layer, inputs)
        out = tl._fn(*inputs)
        return out, tl

    def __call__(self, *args):
        return self._fn(*args)

    def save_inference_model(self, path, feed=None, fetch=None):
        save(self._layer, path, input_spec=None)
