"""Fleet distributed metrics.

Reference: python/paddle/distributed/fleet/metrics/metric.py — each function
all-reduces a host-side metric accumulator across workers (MPI in the
reference) then finishes the statistic locally. TPU-first: the cross-worker
reduce goes through jax's multi-host collective when a distributed world is
initialized (`jax.distributed` / process_count > 1); single-process it is the
identity, which matches the reference run on one worker.
"""
from __future__ import annotations

import numpy as np


def _all_reduce_np(arr, mode="sum"):
    """All-reduce a host numpy array across processes (multi-host), identity
    on a single process. Uses jax's cross-process collective over the global
    device set so no MPI dependency is needed. A multi-process reduce that
    fails raises — silently returning the local value would report per-worker
    statistics as global ones."""
    if mode not in ("sum", "max", "min"):
        raise ValueError(f"unsupported reduce mode {mode!r}")
    arr = np.asarray(arr, np.float64)
    try:
        import jax
        n_proc = jax.process_count()
    except Exception:
        return arr  # jax backend not initialized — single-process eager use
    if n_proc <= 1:
        return arr
    import jax.numpy as jnp
    from jax.experimental.multihost_utils import process_allgather
    gathered = np.asarray(process_allgather(jnp.asarray(arr)))
    return {"sum": gathered.sum, "max": gathered.max,
            "min": gathered.min}[mode](axis=0)


def _to_np(x):
    if hasattr(x, "numpy"):
        return np.asarray(x.numpy())
    return np.asarray(x)


def sum(input, scope=None):  # noqa: A001,A002
    return _all_reduce_np(_to_np(input), "sum")


def max(input, scope=None):  # noqa: A001,A002
    return _all_reduce_np(_to_np(input), "max")


def min(input, scope=None):  # noqa: A001,A002
    return _all_reduce_np(_to_np(input), "min")


def auc(stat_pos, stat_neg, scope=None):
    """ROC AUC from the per-bucket pos/neg counters produced by the auc op
    (ref formula: trapezoid sweep from the top bucket down)."""
    global_pos = _all_reduce_np(_to_np(stat_pos), "sum").reshape(1, -1)
    global_neg = _all_reduce_np(_to_np(stat_neg), "sum").reshape(1, -1)
    num_bucket = global_pos.shape[1]
    area = pos = neg = 0.0
    total_ins_num = 0.0
    for i in range(num_bucket):
        index = num_bucket - 1 - i
        new_pos = pos + global_pos[0][index]
        total_ins_num += global_pos[0][index]
        new_neg = neg + global_neg[0][index]
        total_ins_num += global_neg[0][index]
        area += (new_neg - neg) * (pos + new_pos) / 2
        pos, neg = new_pos, new_neg
    if pos * neg == 0 or total_ins_num == 0:
        return 0.5
    return float(area / (pos * neg))


def mae(abserr, total_ins_num, scope=None):
    # reference contract (metric.py mae): only the error accumulator is
    # all-reduced; total_ins_num is the caller-supplied GLOBAL instance count
    err = _all_reduce_np(_to_np(abserr), "sum")
    return float(err.sum() / total_ins_num)


def rmse(sqrerr, total_ins_num, scope=None):
    err = _all_reduce_np(_to_np(sqrerr), "sum")
    return float((err.sum() / total_ins_num) ** 0.5)


def mse(sqrerr, total_ins_num, scope=None):
    err = _all_reduce_np(_to_np(sqrerr), "sum")
    return float(err.sum() / total_ins_num)


def acc(correct, total, scope=None):
    c = _all_reduce_np(_to_np(correct), "sum")
    t = _all_reduce_np(_to_np(total), "sum")
    return float(c.sum() / t.sum())
