"""paddle.distributed.fleet.launch module path (ref: fleet/launch.py).

`python -m paddle.distributed.fleet.launch train.py` is the reference's
multi-process entry point; on this stack it delegates to the jax.distributed
launcher (`paddle_tpu.distributed.launch`), which boots the coordinator and
per-process ranks the same way.
"""
from ..launch import main  # noqa: F401

if __name__ == "__main__":
    main()
