"""paddle.distributed.fleet.cloud_utils module path (ref:
fleet/cloud_utils.py) — same cloud-env cluster derivation as
paddle.distributed.cloud_utils."""
from ..cloud_utils import get_cloud_cluster, get_cluster_and_pod  # noqa: F401,E501

__all__ = ["get_cloud_cluster", "get_cluster_and_pod"]
