"""Strategy lowering — the meta-optimizer equivalents.

Reference: python/paddle/distributed/fleet/meta_optimizers/*. Each reference
meta-optimizer is a graph rewrite; here each strategy flag picks an XLA-native
mechanism applied when building the hybrid train step:

  amp             -> bf16 compute policy on the step (amp_optimizer.py)
  recompute       -> jax.checkpoint around layer blocks (recompute_optimizer.py)
  gradient_merge  -> lax.scan micro-batch accumulation (gradient_merge_optimizer.py)
  sharding (ZeRO) -> params/opt-state sharded on dp axis (sharding_optimizer.py)
  localsgd        -> periodic param psum-average (localsgd_optimizer.py)
  lamb/lars       -> optimizer swap (lamb_optimizer.py / lars_optimizer.py)
  pipeline        -> pp mesh axis + microbatch schedule (pipeline_optimizer.py)
  fp16_allreduce  -> grads cast bf16 before psum (fp16_allreduce_optimizer.py)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ... import optimizer as opt_mod
from ...parallel.mesh import make_mesh, set_mesh


def wrap_optimizer(fleet_obj, optimizer, strategy):
    """lamb/lars strategies swap the inner optimizer (ref: lamb_optimizer.py
    `_can_apply`: replaces Momentum/Adam); other flags are applied at
    train-step build time."""
    if strategy.lamb and not isinstance(optimizer, opt_mod.Lamb):
        optimizer = opt_mod.Lamb(
            learning_rate=optimizer._lr,
            lamb_weight_decay=strategy.lamb_configs.get("lamb_weight_decay", 0.01),
            parameters=optimizer._parameter_list,
            grad_clip=optimizer._grad_clip)
    elif strategy.lars and isinstance(optimizer, opt_mod.Momentum):
        optimizer = opt_mod.Lars(
            learning_rate=optimizer._lr,
            momentum=optimizer._momentum,
            lars_coeff=strategy.lars_configs.get("lars_coeff", 0.001),
            lars_weight_decay=strategy.lars_configs.get("lars_weight_decay",
                                                        0.0005),
            parameters=optimizer._parameter_list,
            grad_clip=optimizer._grad_clip)
    optimizer._fleet_strategy = strategy
    return optimizer


def apply_strategy(strategy, loss_fn):
    """Wrap a pure loss_fn(params, batch, key) per strategy flags."""
    fn = loss_fn
    if strategy.recompute:
        fn = jax.checkpoint(fn)
    if strategy.amp:
        inner = fn

        def amp_fn(params, batch, key):
            cast = jax.tree_util.tree_map(
                lambda x: x.astype(jnp.bfloat16)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
            return inner(cast, batch, key)
        fn = amp_fn
    return fn


def build_hybrid_train_step(strategy, loss_fn, optimizer, mesh=None):
    """Build the full pjit'ed train step per strategy.

    loss_fn: pure (params, batch, key) -> scalar loss.
    Returns (step_fn, mesh): step_fn(params, opt_state, batch, key) ->
    (loss, new_params, new_opt_state); all collectives XLA-inserted.
    """
    hybrid = strategy.hybrid_configs
    if mesh is None:
        mesh = make_mesh(dp=None if hybrid.get("dp_degree", -1) in (-1, None)
                         else hybrid["dp_degree"],
                         mp=hybrid.get("mp_degree", 1),
                         pp=hybrid.get("pp_degree", 1),
                         sp=hybrid.get("sp_degree", 1))
        set_mesh(mesh)

    wrapped_loss = apply_strategy(strategy, loss_fn)
    k_steps = strategy.gradient_merge_configs.get("k_steps", 1) \
        if strategy.gradient_merge else 1

    def step(params, opt_state, batch, key):
        if k_steps > 1:
            # micro-batch accumulation via scan (gradient_merge)
            def micro(accum, mb):
                l, g = jax.value_and_grad(wrapped_loss)(params, mb, key)
                return (accum[0] + l,
                        jax.tree_util.tree_map(jnp.add, accum[1], g)), None
            micro_batches = jax.tree_util.tree_map(
                lambda x: x.reshape((k_steps, x.shape[0] // k_steps)
                                    + x.shape[1:]), batch)
            zero_g = jax.tree_util.tree_map(jnp.zeros_like, params)
            (loss, grads), _ = jax.lax.scan(micro, (0.0, zero_g), micro_batches)
            if strategy.gradient_merge_configs.get("avg", True):
                loss = loss / k_steps
                grads = jax.tree_util.tree_map(lambda g: g / k_steps, grads)
        else:
            loss, grads = jax.value_and_grad(wrapped_loss)(params, batch, key)
        if strategy.fp16_allreduce:
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.bfloat16).astype(g.dtype), grads)
        if optimizer._grad_clip is not None and hasattr(optimizer._grad_clip,
                                                        "clip_tree"):
            grads = optimizer._grad_clip.clip_tree(grads)
        new_params, new_state = optimizer.functional_update(params, grads,
                                                            opt_state)
        return loss, new_params, new_state

    # shardings: ZeRO shards params+opt state over dp; else replicate params
    if strategy.sharding:
        def spec_for(v):
            # shard the largest dim that divides dp degree
            dp = mesh.shape["dp"]
            for i, s in enumerate(v.shape):
                if s % dp == 0 and s >= dp:
                    return P(*([None] * i + ["dp"] + [None] * (v.ndim - i - 1)))
            return P()
        param_sharding_fn = lambda v: NamedSharding(mesh, spec_for(v))  # noqa: E731
    else:
        param_sharding_fn = lambda v: NamedSharding(mesh, P())  # noqa: E731

    def compile_for(params, batch):
        p_sh = jax.tree_util.tree_map(param_sharding_fn, params)
        b_sh = jax.tree_util.tree_map(
            lambda x: NamedSharding(mesh, P("dp", *([None] * (x.ndim - 1)))),
            batch)
        return jax.jit(step,
                       in_shardings=(p_sh, None, b_sh, None),
                       out_shardings=None,
                       donate_argnums=(0, 1))

    step.compile_for = compile_for
    step.mesh = mesh
    return step, mesh
