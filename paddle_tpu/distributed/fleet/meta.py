"""Strategy lowering — the meta-optimizer equivalents.

Reference: python/paddle/distributed/fleet/meta_optimizers/*. Each reference
meta-optimizer is a graph rewrite; here each strategy flag picks an XLA-native
mechanism applied when building the hybrid train step:

  amp             -> bf16 compute policy on the step (amp_optimizer.py)
  recompute       -> jax.checkpoint around layer blocks (recompute_optimizer.py)
  gradient_merge  -> lax.scan micro-batch accumulation (gradient_merge_optimizer.py)
  sharding (ZeRO) -> params/opt-state sharded on dp axis (sharding_optimizer.py)
  localsgd        -> periodic param psum-average (localsgd_optimizer.py)
  lamb/lars       -> optimizer swap (lamb_optimizer.py / lars_optimizer.py)
  pipeline        -> pp mesh axis + microbatch schedule (pipeline_optimizer.py)
  fp16_allreduce  -> grads cast bf16 before psum (fp16_allreduce_optimizer.py)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ... import optimizer as opt_mod
from ...parallel.mesh import make_mesh, set_mesh


def wrap_optimizer(fleet_obj, optimizer, strategy):
    """lamb/lars strategies swap the inner optimizer (ref: lamb_optimizer.py
    `_can_apply`: replaces Momentum/Adam); other flags are applied at
    train-step build time."""
    if strategy.lamb and not isinstance(optimizer, opt_mod.Lamb):
        optimizer = opt_mod.Lamb(
            learning_rate=optimizer._lr,
            lamb_weight_decay=strategy.lamb_configs.get("lamb_weight_decay", 0.01),
            parameters=optimizer._parameter_list,
            grad_clip=optimizer._grad_clip)
    elif strategy.lars and isinstance(optimizer, opt_mod.Momentum):
        optimizer = opt_mod.Lars(
            learning_rate=optimizer._lr,
            momentum=optimizer._momentum,
            lars_coeff=strategy.lars_configs.get("lars_coeff", 0.001),
            lars_weight_decay=strategy.lars_configs.get("lars_weight_decay",
                                                        0.0005),
            parameters=optimizer._parameter_list,
            grad_clip=optimizer._grad_clip)
    optimizer._fleet_strategy = strategy
    return optimizer


def _remat_policy(strategy):
    """Map recompute_configs to a jax.checkpoint policy — the TPU analogue
    of the reference's per-op checkpoints list (recompute_optimizer.py):
      granularity 'full'      -> recompute everything (default; max memory
                                 savings, most recompute FLOPs)
      granularity 'selective' -> save weight-matmul outputs, recompute
                                 batched (attention-score) dots and
                                 elementwise — the Megatron selective
                                 recompute
      granularity 'dots'      -> save every dot output, recompute only
                                 elementwise chains
    """
    gran = (strategy.recompute_configs or {}).get("granularity", "full")
    import jax.ad_checkpoint as adc
    table = {
        "full": None,  # jax.checkpoint default: recompute everything
        "selective": adc.checkpoint_policies.dots_with_no_batch_dims_saveable,
        "dots": adc.checkpoint_policies.dots_saveable,
    }
    if gran not in table:
        raise ValueError(
            f"recompute_configs.granularity must be one of {list(table)}, "
            f"got {gran!r}")
    return table[gran]


def apply_strategy(strategy, loss_fn):
    """Wrap a pure loss_fn(params, batch, key) per strategy flags."""
    fn = loss_fn
    if strategy.recompute:
        fn = jax.checkpoint(fn, policy=_remat_policy(strategy))
    if strategy.amp:
        inner = fn

        def amp_fn(params, batch, key):
            cast = jax.tree_util.tree_map(
                lambda x: x.astype(jnp.bfloat16)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
            return inner(cast, batch, key)
        fn = amp_fn
    return fn


def build_hybrid_train_step(strategy, loss_fn, optimizer, mesh=None,
                            stage_fn=None, loss_head=None):
    """Build the full pjit'ed train step per strategy.

    loss_fn: pure (params, batch, key) -> scalar loss.
    Returns (step_fn, mesh): step_fn(params, opt_state, batch, key) ->
    (loss, new_params, new_opt_state); all collectives XLA-inserted.

    strategy.pipeline (pp_degree > 1) additionally needs `stage_fn`
    ((stage_params, x) -> y, the homogeneous per-stage computation) and
    `loss_head` ((y, labels) -> scalar); the loss is then built by
    parallel/pipeline.py's GPipe schedule over the pp axis and `loss_fn`
    may be None.
    localsgd / dgc build an explicit-dp step (shard_map over dp) because
    both need per-worker gradients before the collective.
    """
    hybrid = strategy.hybrid_configs
    if mesh is None:
        mesh = make_mesh(dp=None if hybrid.get("dp_degree", -1) in (-1, None)
                         else hybrid["dp_degree"],
                         mp=hybrid.get("mp_degree", 1),
                         pp=hybrid.get("pp_degree", 1),
                         sp=hybrid.get("sp_degree", 1))
        set_mesh(mesh)

    if strategy.pipeline and mesh.shape.get("pp", 1) > 1:
        # ref: pipeline_optimizer.py — graph-partitioned GPipe. Here the
        # stage computation is user-supplied and the schedule comes from
        # parallel/pipeline.py (ppermute microbatch rotation).
        if stage_fn is None or loss_head is None:
            raise ValueError(
                "strategy.pipeline with pp_degree>1 needs stage_fn and "
                "loss_head (the reference partitions the program graph by "
                "device annotation; the TPU rebuild takes the per-stage fn)")
        from ...parallel.pipeline import make_pipeline_loss
        m = strategy.pipeline_configs.get("accumulate_steps", 1)
        # schedule: "gpipe" (default) or "interleaved" (circular, each
        # rank holds `num_virtual` non-adjacent chunks; bubble shrinks
        # from (S-1)/(M+S-1) to (S-1)/(V*M+S-1))
        sched = strategy.pipeline_configs.get("schedule", "gpipe")
        v = strategy.pipeline_configs.get("num_virtual", 1)
        pl_loss = make_pipeline_loss(stage_fn, loss_head, mesh, m, "pp",
                                     schedule=sched, num_virtual=v)

        def loss_fn(params, batch, key):  # noqa: F811
            labels = batch.get("labels", batch.get("y"))
            return pl_loss(params, batch["x"], labels)

    if strategy.localsgd or strategy.dgc \
            or getattr(strategy, "int8_allreduce", False):
        return _build_explicit_dp_step(strategy, loss_fn, optimizer, mesh)

    wrapped_loss = apply_strategy(strategy, loss_fn)
    k_steps = strategy.gradient_merge_configs.get("k_steps", 1) \
        if strategy.gradient_merge else 1

    def step(params, opt_state, batch, key):
        if k_steps > 1:
            # micro-batch accumulation via scan (gradient_merge)
            def micro(accum, mb):
                l, g = jax.value_and_grad(wrapped_loss)(params, mb, key)
                return (accum[0] + l,
                        jax.tree_util.tree_map(jnp.add, accum[1], g)), None
            micro_batches = jax.tree_util.tree_map(
                lambda x: x.reshape((k_steps, x.shape[0] // k_steps)
                                    + x.shape[1:]), batch)
            zero_g = jax.tree_util.tree_map(jnp.zeros_like, params)
            (loss, grads), _ = jax.lax.scan(micro, (0.0, zero_g), micro_batches)
            if strategy.gradient_merge_configs.get("avg", True):
                loss = loss / k_steps
                grads = jax.tree_util.tree_map(lambda g: g / k_steps, grads)
        else:
            loss, grads = jax.value_and_grad(wrapped_loss)(params, batch, key)
        if strategy.fp16_allreduce:
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.bfloat16).astype(g.dtype), grads)
        if optimizer._grad_clip is not None and hasattr(optimizer._grad_clip,
                                                        "clip_tree"):
            grads = optimizer._grad_clip.clip_tree(grads)
        new_params, new_state = optimizer.functional_update(params, grads,
                                                            opt_state)
        return loss, new_params, new_state

    # ZeRO shardings (ref: sharding_optimizer.py stages):
    #   stage 1: optimizer state sharded over dp, params/grads replicated
    #   stage 2: + gradient reduce-scatter — with dp-sharded slots XLA's
    #            SPMD partitioner emits the reduce-scatter into the update
    #            itself, so stages 1/2 share the slot-sharding lowering
    #   stage 3: + parameters sharded over dp
    def _zero_spec(v):
        # shard the largest dim that divides dp degree
        dp = mesh.shape["dp"]
        for i, s in enumerate(v.shape):
            if s % dp == 0 and s >= dp:
                return P(*([None] * i + ["dp"] + [None] * (v.ndim - i - 1)))
        return P()

    zero_stage = strategy.sharding_configs.get("stage", 2) \
        if strategy.sharding else 0
    if zero_stage >= 3:
        param_sharding_fn = lambda v: NamedSharding(mesh, _zero_spec(v))  # noqa: E731
    elif strategy.pipeline and mesh.shape.get("pp", 1) > 1:
        pp = mesh.shape["pp"]
        param_sharding_fn = lambda v: NamedSharding(  # noqa: E731
            mesh, P("pp", *([None] * (v.ndim - 1)))
            if v.ndim and v.shape[0] == pp else P())
    else:
        param_sharding_fn = lambda v: NamedSharding(mesh, P())  # noqa: E731
    slot_sharding_fn = (lambda v: NamedSharding(mesh, _zero_spec(v))) \
        if zero_stage >= 1 else None

    def compile_for(params, batch, opt_state=None):
        p_sh = jax.tree_util.tree_map(param_sharding_fn, params)
        b_sh = jax.tree_util.tree_map(
            lambda x: NamedSharding(mesh, P("dp", *([None] * (x.ndim - 1)))),
            batch)
        s_sh = None
        if opt_state is not None and slot_sharding_fn is not None:
            s_sh = jax.tree_util.tree_map(slot_sharding_fn, opt_state)
        # pin outputs to the stage contract — otherwise XLA may propagate
        # the slot sharding onto the (donated) replicated params
        out_sh = None if s_sh is None else (None, p_sh, s_sh)
        return jax.jit(step,
                       in_shardings=(p_sh, s_sh, b_sh, None),
                       out_shardings=out_sh,
                       donate_argnums=(0, 1))

    step.compile_for = compile_for
    step.mesh = mesh
    return step, mesh


def _build_explicit_dp_step(strategy, loss_fn, optimizer, mesh):
    """localsgd / dgc lowering — both need each dp worker's own gradient
    before the collective, so the step body runs under shard_map over dp.

    localsgd (ref: localsgd_optimizer.py): params carry a leading dp axis
    (one divergent copy per worker); workers update locally from LOCAL
    grads and every k_steps psum-average the copies.
    dgc (ref: dgc_optimizer.py): error-feedback top-k sparsification — the
    allreduce moves only the top (1-sparsity) gradient entries; the residual
    stays in a per-worker error buffer folded into the next step.
    """
    from jax.experimental.shard_map import shard_map

    wrapped_loss = apply_strategy(strategy, loss_fn)
    dp = mesh.shape["dp"]
    use_localsgd = strategy.localsgd
    use_dgc = strategy.dgc
    k_steps = strategy.localsgd_configs.get("k_steps", 1)
    sparsity = strategy.dgc_configs.get("sparsity", [0.999])[-1] \
        if use_dgc else 0.0

    # per-worker (divergent) state carries a leading dp axis, sharded P("dp")
    # into shard_map so each worker owns one slice of size 1:
    #   localsgd -> params + optimizer slots diverge between averaging steps
    #   dgc      -> the error-feedback residual is inherently per-worker
    stack_pi = use_localsgd        # params + inner slots
    stack_err = use_dgc

    def _stack(tree):
        return jax.tree_util.tree_map(
            lambda v: jnp.broadcast_to(v[None], (dp,) + v.shape), tree)

    def _local(tree):   # [1, ...] worker slice -> [...]
        return jax.tree_util.tree_map(lambda v: v[0], tree)

    def _relocal(tree):  # [...] -> [1, ...] for the P("dp") out concat
        return jax.tree_util.tree_map(lambda v: v[None], tree)

    def _compress(g, e):
        # error feedback: add residual, keep top-k magnitude entries
        g = g + e
        flat = g.reshape(-1)
        kk = max(1, int(flat.size * (1.0 - sparsity)))
        thresh = jax.lax.top_k(jnp.abs(flat), kk)[0][-1]
        g_send = jnp.where(jnp.abs(g) >= thresh, g, 0.0)
        return g_send, g - g_send

    def local_step(params, inner_state, err, step_ct, batch, key):
        p_local = _local(params) if stack_pi else params
        s_local = _local(inner_state) if stack_pi else inner_state
        e_local = _local(err) if stack_err else err
        loss, grads = jax.value_and_grad(wrapped_loss)(p_local, batch, key)
        if use_dgc:
            flat_g, tdef = jax.tree_util.tree_flatten(grads)
            flat_e = jax.tree_util.tree_leaves(e_local)
            pairs = [_compress(g, e) for g, e in zip(flat_g, flat_e)]
            grads = jax.tree_util.tree_unflatten(tdef, [p[0] for p in pairs])
            e_local = jax.tree_util.tree_unflatten(tdef,
                                                   [p[1] for p in pairs])
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, "dp") / dp, grads)
        elif getattr(strategy, "int8_allreduce", False) \
                and not use_localsgd:
            # (localsgd defines its OWN communication schedule — the
            # periodic param average — so int8_allreduce must not
            # reintroduce per-step grad sync under it)
            # EQuARX-pattern compressed gradient sync: int8 blockwise
            # reduce-scatter + all-gather in place of the f32 psum —
            # BUCKETED (r5): small leaves ride the compressed path and
            # each bucket is an independent collective the scheduler can
            # overlap with the rest of the backward (reference reducer)
            from ..collective import bucketed_quantized_all_reduce
            grads = jax.tree_util.tree_map(
                lambda g: g / dp,
                bucketed_quantized_all_reduce(grads, "dp"))
        elif not use_localsgd:
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, "dp"), grads)
        # localsgd: NO grad sync — the collective is the periodic param avg
        new_p, new_s = optimizer.functional_update(p_local, grads, s_local)
        if use_localsgd:
            do_avg = (step_ct % k_steps) == (k_steps - 1)
            new_p = jax.lax.cond(
                do_avg,
                lambda p: jax.tree_util.tree_map(
                    lambda v: jax.lax.pmean(v, "dp"), p),
                lambda p: p, new_p)
        if stack_pi:
            new_p, new_s = _relocal(new_p), _relocal(new_s)
        if stack_err:
            e_local = _relocal(e_local)
        return jax.lax.pmean(loss, "dp"), new_p, new_s, e_local

    def step(params, opt_state, batch, key):
        inner = opt_state["inner"]
        err = opt_state["dgc_err"]
        ct = opt_state["step"]
        rep = P()
        pi_spec = lambda tree: jax.tree_util.tree_map(  # noqa: E731
            lambda v: P("dp", *([None] * (v.ndim - 1))) if stack_pi else rep,
            tree)
        err_spec = jax.tree_util.tree_map(
            lambda v: P("dp", *([None] * (v.ndim - 1))) if stack_err else rep,
            err)
        b_spec = jax.tree_util.tree_map(
            lambda x: P("dp", *([None] * (x.ndim - 1))), batch)
        loss, new_p, new_s, new_err = shard_map(
            local_step, mesh=mesh,
            in_specs=(pi_spec(params), pi_spec(inner), err_spec, rep,
                      b_spec, rep),
            out_specs=(rep, pi_spec(params), pi_spec(inner), err_spec),
            check_rep=False)(params, inner, err, ct, batch, key)
        return loss, new_p, {"inner": new_s, "dgc_err": new_err,
                             "step": ct + 1}

    def init_opt_state(params):
        """Build (params_for_step, opt_state): step counter + dgc error
        buffers; localsgd stacks params/slots to one copy per dp worker."""
        inner = optimizer.functional_init(params)
        if use_dgc:  # per-worker residuals: [dp, ...] per param leaf
            err = jax.tree_util.tree_map(
                lambda v: jnp.zeros((dp,) + v.shape, v.dtype), params)
        else:        # unused placeholder, keeps the opt_state pytree static
            err = jax.tree_util.tree_map(
                lambda v: jnp.zeros((), v.dtype), params)
        p = params
        if stack_pi:
            p, inner = _stack(params), _stack(inner)
        return p, {"inner": inner, "dgc_err": err,
                   "step": jnp.zeros((), jnp.int32)}

    def compile_for(params, batch, opt_state=None):
        p_sh = jax.tree_util.tree_map(
            lambda v: NamedSharding(
                mesh, P("dp", *([None] * (v.ndim - 1))) if stack_pi else P()),
            params)
        b_sh = jax.tree_util.tree_map(
            lambda x: NamedSharding(mesh, P("dp", *([None] * (x.ndim - 1)))),
            batch)
        return jax.jit(step, in_shardings=(p_sh, None, b_sh, None),
                       out_shardings=None, donate_argnums=(0, 1))

    step.compile_for = compile_for
    step.init_opt_state = init_opt_state
    step.mesh = mesh
    return step, mesh


def applied_mechanisms(strategy):
    """Which strategy flags are active and the XLA mechanism each lowers
    to (ref: fleet_base._get_applied_meta_list naming the meta-optimizer
    classes; here the mechanisms are declarative, not graph passes)."""
    out = []
    if strategy is None:
        return out
    if getattr(strategy, "amp", False):
        out.append("AMPOptimizer->bf16_compute_policy")
    if getattr(strategy, "recompute", False):
        out.append("RecomputeOptimizer->jax.checkpoint")
    if getattr(strategy, "sharding", False):
        out.append("ShardingOptimizer->zero_param_sharding")
    if getattr(strategy, "gradient_merge", False):
        out.append("GradientMergeOptimizer->microbatch_scan")
    if getattr(strategy, "pipeline", False):
        out.append("PipelineOptimizer->pp_mesh_axis_gpipe")
    if getattr(strategy, "localsgd", False):
        out.append("LocalSGDOptimizer->periodic_psum_average")
    if getattr(strategy, "dgc", False):
        out.append("DGCMomentumOptimizer->topk_grad_compression")
    if getattr(strategy, "int8_allreduce", False):
        out.append("Int8AllReduce->quantized_reduce_scatter_all_gather")
    if getattr(strategy, "lamb", False):
        out.append("LambOptimizer->lamb_rule")
    if getattr(strategy, "lars", False):
        out.append("LarsOptimizer->lars_rule")
    return out
