"""Tiny threaded key-value HTTP server for job rendezvous.

Reference surface: python/paddle/distributed/fleet/utils/http_server.py
(a KVServer the gloo bootstrap uses to exchange endpoints before the
collective runtime is up). TPU-native context: jax.distributed has its
own coordinator, so this exists for API parity and for custom launchers
that need a dependency-free rendezvous: PUT/GET/DELETE under /<scope>/
<key>, plus KVHTTPServer.get_deleted_size() so a barrier can count
participants the way the reference's start/stop protocol does.
"""
from __future__ import annotations

import http.server
import threading
import urllib.request


class _KVHandler(http.server.BaseHTTPRequestHandler):
    def log_message(self, *a):  # quiet
        pass

    def do_PUT(self):
        n = int(self.headers.get("Content-Length", 0))
        self.server.kv[self.path] = self.rfile.read(n)
        self.send_response(200)
        self.end_headers()

    def do_GET(self):
        val = self.server.kv.get(self.path)
        if val is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(val)))
        self.end_headers()
        self.wfile.write(val)

    def do_DELETE(self):
        if self.server.kv.pop(self.path, None) is not None:
            self.server.deleted += 1
        self.send_response(200)
        self.end_headers()


class KVHTTPServer(http.server.ThreadingHTTPServer):
    def __init__(self, port, handler=_KVHandler):
        super().__init__(("", port), handler)
        self.kv = {}
        self.deleted = 0

    def get_deleted_size(self, key=None):
        return self.deleted


class KVServer:
    """start()/stop() lifecycle wrapper (ref: http_server.py KVServer)."""

    def __init__(self, port, size=None):
        self._port = port
        self._server = None
        self._thread = None
        self.size = size or {}

    @property
    def port(self):
        return self._port

    def start(self):
        self._server = KVHTTPServer(self._port)
        if self._port == 0:  # ephemeral: expose the bound port
            self._port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def should_stop(self):
        """True once every registered scope has been fully deleted —
        the reference's participant-countdown contract."""
        return self._server is not None and \
            self._server.get_deleted_size() >= sum(self.size.values() or [0])

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._thread.join(timeout=5)
            self._server.server_close()
            self._server = None


class KVClient:
    """HTTP client side (PUT/GET/DELETE string values)."""

    def __init__(self, endpoint):
        self._base = f"http://{endpoint}"

    def put(self, key, value):
        data = value.encode() if isinstance(value, str) else value
        req = urllib.request.Request(self._base + key, data=data,
                                     method="PUT")
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status == 200

    def get(self, key):
        try:
            with urllib.request.urlopen(self._base + key, timeout=10) as r:
                return r.read().decode()
        except urllib.error.HTTPError:
            return ""

    def delete(self, key):
        req = urllib.request.Request(self._base + key, method="DELETE")
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status == 200
