"""Filesystem abstraction for fleet checkpoint/data transfer.

Reference surface: python/paddle/distributed/fleet/utils/fs.py (FS base,
LocalFS, HDFSClient shelling to the hadoop CLI). TPU-native rework: the
same API, but LocalFS is built on pathlib/shutil, and HDFSClient runs
`hadoop fs` subcommands via subprocess with timeouts — functional when a
hadoop install is present, raising a clear ExecuteError otherwise. On
TPU VMs the normal checkpoint path is local disk / NFS / object storage
mounted as a filesystem, so LocalFS is the workhorse.
"""
from __future__ import annotations

import os
import shutil
import subprocess
import time


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FSTimeOut(Exception):
    pass


class FSShellCmdAborted(ExecuteError):
    pass


class FS:
    """Abstract filesystem (ref: fs.py:57). Subclasses implement every
    operation; `need_upload_download()` says whether paths live off-host
    (HDFS) or are directly addressable (local)."""

    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        raise NotImplementedError

    def upload_dir(self, local_dir, dest_dir):
        raise NotImplementedError

    def list_dirs(self, fs_path):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError


class LocalFS(FS):
    """Local filesystem (ref: fs.py:115)."""

    def ls_dir(self, fs_path):
        """Returns ([dirs], [files]) directly under fs_path."""
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, name))
             else files).append(name)
        return dirs, files

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def delete(self, fs_path):
        if self.is_dir(fs_path):
            shutil.rmtree(fs_path)
        elif self.is_file(fs_path):
            os.remove(fs_path)

    def need_upload_download(self):
        return False

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        with open(fs_path, "a"):
            pass

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if self.is_exist(dst_path):
            if not overwrite:
                raise FSFileExistsError(dst_path)
            self.delete(dst_path)
        os.rename(src_path, dst_path)

    def list_dirs(self, fs_path):
        """Subdirectory names only (ref semantics)."""
        return self.ls_dir(fs_path)[0]

    # upload/download degenerate to copies for a local fs
    def upload(self, local_path, fs_path):
        self._copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        self._copy(fs_path, local_path)

    def upload_dir(self, local_dir, dest_dir):
        shutil.copytree(local_dir, dest_dir, dirs_exist_ok=True)

    def _copy(self, src, dst):
        if not self.is_exist(src):
            raise FSFileNotExistsError(src)
        if self.is_dir(src):
            shutil.copytree(src, dst, dirs_exist_ok=True)
        else:
            os.makedirs(os.path.dirname(os.path.abspath(dst)), exist_ok=True)
            shutil.copy2(src, dst)


class HDFSClient(FS):
    """HDFS via the hadoop CLI (ref: fs.py:419 runs `hadoop fs` the same
    way). Requires a hadoop install: pass `hadoop_home` or set
    $HADOOP_HOME. Every call raises ExecuteError/FSTimeOut with the
    command and output on failure — never a silent no-op."""

    def __init__(self, hadoop_home=None, configs=None, time_out=5 * 60,
                 sleep_inter=1):
        self._hadoop_home = hadoop_home or os.environ.get("HADOOP_HOME")
        self._timeout = time_out
        self._sleep = sleep_inter
        self._config_args = []
        for k, v in (configs or {}).items():
            self._config_args += ["-D", f"{k}={v}"]

    def _bin(self):
        if not self._hadoop_home:
            raise ExecuteError(
                "HDFSClient needs a hadoop install: pass hadoop_home= or "
                "set $HADOOP_HOME (on TPU VMs prefer LocalFS over a "
                "mounted/NFS/object-store path)")
        return os.path.join(self._hadoop_home, "bin", "hadoop")

    def _run(self, *args, check=True):
        cmd = [self._bin(), "fs"] + self._config_args + list(args)
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=self._timeout)
        except subprocess.TimeoutExpired as e:
            raise FSTimeOut(f"{' '.join(cmd)} timed out "
                            f"after {self._timeout}s") from e
        if check and r.returncode != 0:
            raise ExecuteError(f"{' '.join(cmd)} failed "
                               f"(rc={r.returncode}): {r.stderr[:500]}")
        return r

    def need_upload_download(self):
        return True

    def is_exist(self, fs_path):
        return self._run("-test", "-e", fs_path, check=False).returncode == 0

    def is_file(self, fs_path):
        return self._run("-test", "-f", fs_path, check=False).returncode == 0

    def is_dir(self, fs_path):
        return self._run("-test", "-d", fs_path, check=False).returncode == 0

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        out = self._run("-ls", fs_path).stdout
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = parts[-1].rsplit("/", 1)[-1]
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        if self.is_exist(fs_path):
            self._run("-rm", "-r", "-f", fs_path)

    def upload(self, local_path, fs_path):
        self._run("-put", "-f", local_path, fs_path)

    def upload_dir(self, local_dir, dest_dir):
        self._run("-put", "-f", local_dir, dest_dir)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        self._run("-touchz", fs_path)

    def rename(self, fs_src_path, fs_dst_path):
        self._run("-mv", fs_src_path, fs_dst_path)

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=True):
        if test_exists:
            if not self.is_exist(fs_src_path):
                raise FSFileNotExistsError(fs_src_path)
            if self.is_exist(fs_dst_path):
                if not overwrite:
                    raise FSFileExistsError(fs_dst_path)
                self.delete(fs_dst_path)
        start = time.time()
        while True:
            try:
                self._run("-mv", fs_src_path, fs_dst_path)
                return
            except ExecuteError:
                if time.time() - start > self._timeout:
                    raise
                time.sleep(self._sleep)
