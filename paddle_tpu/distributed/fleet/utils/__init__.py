"""fleet.utils — filesystem + rendezvous helpers (ref:
python/paddle/distributed/fleet/utils/__init__.py)."""
from . import fs  # noqa: F401
from .fs import (  # noqa: F401
    ExecuteError, FS, FSFileExistsError, FSFileNotExistsError,
    FSShellCmdAborted, FSTimeOut, HDFSClient, LocalFS,
)
from .http_server import KVClient, KVHTTPServer, KVServer  # noqa: F401

__all__ = ["LocalFS", "HDFSClient", "FS", "ExecuteError",
           "FSFileExistsError", "FSFileNotExistsError", "FSTimeOut",
           "FSShellCmdAborted", "KVServer", "KVClient", "KVHTTPServer"]
