"""Fleet base: DistributedStrategy + Fleet singleton.

Reference: python/paddle/distributed/fleet/base/distributed_strategy.py (the
protobuf-backed strategy) and base/fleet_base.py. Strategy fields keep the
reference names; on TPU they lower to mesh/sharding/remat choices instead of
graph passes.
"""
from __future__ import annotations

from ... import optimizer as opt_mod
from ...core.tensor import Tensor


class DistributedStrategy:
    def __init__(self):
        # collective knobs (ref field names)
        self.amp = False
        self.amp_configs = {"init_loss_scaling": 32768.0,
                            "use_pure_fp16": False, "use_bf16": True}
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.sharding = False
        self.sharding_configs = {"sharding_degree": 1, "stage": 2}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1}
        self.dgc = False
        self.dgc_configs = {"rampup_begin_step": 0, "rampup_step": 1,
                            "sparsity": [0.999]}
        self.lamb = False
        self.lamb_configs = {"lamb_weight_decay": 0.01}
        self.lars = False
        self.lars_configs = {"lars_coeff": 0.001, "lars_weight_decay": 0.0005}
        self.fp16_allreduce = False
        # TPU-first extension (EQuARX pattern): int8 blockwise-quantized
        # gradient all-reduce — ~1/4 the ICI/DCN bytes of f32; lowers via
        # the explicit-dp step (meta.py)
        self.int8_allreduce = False
        self.nccl_comm_num = 1
        self.hybrid_configs = {"dp_degree": -1, "mp_degree": 1,
                               "pp_degree": 1, "sp_degree": 1}
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.without_graph_optimization = False

    def __repr__(self):
        on = [k for k, v in self.__dict__.items()
              if isinstance(v, bool) and v]
        return f"DistributedStrategy(enabled={on})"


class _RoleMakerBase:
    def __init__(self, is_collective=True, **kw):
        self._is_collective = is_collective
        # PS-mode roles come from the launcher env (ref: role_maker.py
        # PaddleCloudRoleMaker TRAINING_ROLE); collective mode is all-worker
        import os
        self._role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()

    def worker_index(self):
        from ..collective import get_rank
        return get_rank()

    def worker_num(self):
        from ..collective import get_world_size
        import jax
        try:
            return get_world_size()
        except Exception:  # pragma: no cover
            return jax.process_count()

    def is_worker(self):
        return self._role == "TRAINER"

    def is_server(self):
        return self._role == "PSERVER"

    def is_first_worker(self):
        return self.is_worker() and self.worker_index() == 0


class PaddleCloudRoleMaker(_RoleMakerBase):
    pass


class UserDefinedRoleMaker(_RoleMakerBase):
    def __init__(self, is_collective=True, current_id=0, role=None,
                 worker_num=None, server_endpoints=None, **kw):
        super().__init__(is_collective, **kw)
        if role is not None:
            self._role = str(role).upper()
            if self._role == "WORKER":
                self._role = "TRAINER"
        self._current_id = current_id
        self._worker_num = worker_num

    def worker_index(self):
        return self._current_id

    def worker_num(self):
        return self._worker_num or super().worker_num()


class Fleet:
    def __init__(self):
        self._role_maker = None
        self._strategy = None
        self._origin_optimizer = None
        self._origin_model = None

    def init(self, role_maker=None, is_collective=True, strategy=None):
        self._role_maker = role_maker or PaddleCloudRoleMaker(is_collective)
        self._strategy = strategy or DistributedStrategy()
        from ..collective import init_parallel_env
        init_parallel_env()
        return self

    # ---- role queries ----
    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def is_worker(self):
        return self._role_maker.is_worker()

    def is_server(self):
        return self._role_maker.is_server()

    def barrier_worker(self):
        from ..collective import barrier
        barrier()

    def worker_endpoints(self, to_string=False):
        eps = getattr(self._role_maker, "worker_endpoints", None)
        eps = eps() if callable(eps) else (eps or [])
        return ",".join(eps) if to_string else list(eps)

    def server_num(self):
        f = getattr(self._role_maker, "server_num", None)
        return f() if callable(f) else 0

    def server_index(self):
        f = getattr(self._role_maker, "server_index", None)
        return f() if callable(f) else 0

    def server_endpoints(self, to_string=False):
        eps = getattr(self._role_maker, "server_endpoints", None)
        eps = eps() if callable(eps) else (eps or [])
        return ",".join(eps) if to_string else list(eps)

    # ---- training ----
    def distributed_optimizer(self, optimizer, strategy=None):
        if strategy is not None:
            self._strategy = strategy
        self._origin_optimizer = optimizer
        from .meta import wrap_optimizer
        # the facade passthroughs (minimize/step/state_dict/...) must
        # drive THIS wrapped optimizer — a lamb/lars strategy swaps the
        # update rule, and the module-level fleet.step() has to see it
        self._wrapped_optimizer = wrap_optimizer(self, optimizer,
                                                 self._strategy)
        return self._wrapped_optimizer

    def distributed_model(self, model):
        from ..parallel import DataParallel
        self._origin_model = model
        return DataParallel(model)

    @property
    def strategy(self):
        return self._strategy

    # ---- optimizer passthroughs (ref: fleet_base.py — the fleet module
    # IS the optimizer facade after distributed_optimizer) ----
    def _user_opt(self):
        wrapped = getattr(self, "_wrapped_optimizer", None)
        if wrapped is not None:
            return wrapped
        if self._origin_optimizer is None:
            raise RuntimeError(
                "call fleet.distributed_optimizer(optimizer) first")
        return self._origin_optimizer

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._user_opt().minimize(
            loss, startup_program=startup_program, parameters=parameters,
            no_grad_set=no_grad_set)

    def step(self):
        return self._user_opt().step()

    def clear_grad(self):
        return self._user_opt().clear_grad()

    def set_lr(self, value):
        return self._user_opt().set_lr(value)

    def get_lr(self):
        return self._user_opt().get_lr()

    def state_dict(self):
        return self._user_opt().state_dict()

    def set_state_dict(self, state_dict):
        return self._user_opt().set_state_dict(state_dict)

    # ---- introspection (ref: fleet_base _final_strategy and the
    # meta/graph-optimizer lists; strategy lowering here is declarative,
    # so the "applied" lists name the XLA mechanisms selected) ----
    def _final_strategy(self):
        return self._strategy

    def _get_applied_meta_list(self):
        from .meta import applied_mechanisms
        return applied_mechanisms(self._strategy)

    def _get_applied_graph_list(self):
        return []  # graph-pass rewrites don't exist on the XLA stack

    # ---- io (worker-0 gated, ref: fleet_base save_persistables) ----
    def save_persistables(self, executor, dirname, main_program=None):
        if self.is_first_worker():
            import os
            os.makedirs(dirname, exist_ok=True)

    def save_inference_model(self, *a, **kw):
        pass

    # ---- PS-mode lifecycle (ref: fleet_base init_server/run_server; the
    # host-offloaded sparse-table runtime lives in distributed/ps.py) ----
    def stop_worker(self):
        from .. import ps
        ps.stop_worker()

    def init_worker(self):
        from .. import ps
        ps.init_worker()

    def init_server(self, *a, **kw):
        from .. import ps
        ps.init_server(*a, **kw)

    def run_server(self):
        from .. import ps
        ps.run_server()


fleet = Fleet()


def init(role_maker=None, is_collective=True, strategy=None):
    return fleet.init(role_maker, is_collective, strategy)


def is_first_worker():
    return fleet.is_first_worker()


def worker_index():
    return fleet.worker_index()


def worker_num():
    return fleet.worker_num()


def distributed_optimizer(optimizer, strategy=None):
    return fleet.distributed_optimizer(optimizer, strategy)


def distributed_model(model):
    return fleet.distributed_model(model)
