"""paddle.distributed.fleet — unified distributed training API.

Reference: python/paddle/distributed/fleet/ (base/fleet_base.py,
base/distributed_strategy.py, meta_optimizers/). TPU-first rework: a
DistributedStrategy no longer rewrites the graph with collective ops — its
flags select mesh axes + sharding rules + XLA-native mechanisms
(amp→bf16, recompute→jax.checkpoint, sharding→ZeRO param sharding,
gradient_merge→microbatch scan, pipeline→pp mesh axis), applied when building
the pjit'ed train step. See meta.py for the strategy lowering.
"""
from __future__ import annotations

from .base import (  # noqa: F401
    DistributedStrategy, Fleet, PaddleCloudRoleMaker, UserDefinedRoleMaker,
    fleet, init, is_first_worker, worker_index, worker_num,
    distributed_optimizer, distributed_model,
)
from .meta import apply_strategy, build_hybrid_train_step  # noqa: F401
from .data_generator import (  # noqa: F401
    DataGenerator, MultiSlotDataGenerator, MultiSlotStringDataGenerator)


class UtilBase:
    """Fleet util helpers (ref: python/paddle/distributed/fleet/base/
    util_factory.py): small collective conveniences over the jax backend."""

    def all_reduce(self, input, mode="sum"):  # noqa: A002
        from ..collective import ReduceOp, all_reduce as _ar
        op = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX,
              "min": ReduceOp.MIN, "avg": ReduceOp.AVG}[mode]
        return _ar(input, op=op)

    def barrier(self):
        from ..collective import barrier as _b
        _b()

    def all_gather(self, input):  # noqa: A002
        from ..collective import all_gather as _ag
        out = []
        _ag(out, input)
        return out


class Role:
    """ref: fleet/base/role_maker.py role enum."""
    WORKER = 1
    SERVER = 2


# (MultiSlotDataGenerator and friends live in data_generator.py — imported
# above; an earlier inline stub was removed in favor of the real module.)


from . import metrics  # noqa: E402,F401
