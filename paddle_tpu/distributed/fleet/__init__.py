"""paddle.distributed.fleet — unified distributed training API.

Reference: python/paddle/distributed/fleet/ (base/fleet_base.py,
base/distributed_strategy.py, meta_optimizers/). TPU-first rework: a
DistributedStrategy no longer rewrites the graph with collective ops — its
flags select mesh axes + sharding rules + XLA-native mechanisms
(amp→bf16, recompute→jax.checkpoint, sharding→ZeRO param sharding,
gradient_merge→microbatch scan, pipeline→pp mesh axis), applied when building
the pjit'ed train step. See meta.py for the strategy lowering.
"""
from __future__ import annotations

from .base import (  # noqa: F401
    DistributedStrategy, Fleet, PaddleCloudRoleMaker, UserDefinedRoleMaker,
    fleet, init, is_first_worker, worker_index, worker_num,
    distributed_optimizer, distributed_model,
)
from .meta import apply_strategy, build_hybrid_train_step  # noqa: F401
