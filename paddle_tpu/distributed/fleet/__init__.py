"""paddle.distributed.fleet — unified distributed training API.

Reference: python/paddle/distributed/fleet/ (base/fleet_base.py,
base/distributed_strategy.py, meta_optimizers/). TPU-first rework: a
DistributedStrategy no longer rewrites the graph with collective ops — its
flags select mesh axes + sharding rules + XLA-native mechanisms
(amp→bf16, recompute→jax.checkpoint, sharding→ZeRO param sharding,
gradient_merge→microbatch scan, pipeline→pp mesh axis), applied when building
the pjit'ed train step. See meta.py for the strategy lowering.
"""
from __future__ import annotations

from .base import (  # noqa: F401
    DistributedStrategy, Fleet, PaddleCloudRoleMaker, UserDefinedRoleMaker,
    fleet, init, is_first_worker, worker_index, worker_num,
    distributed_optimizer, distributed_model,
)
from .meta import apply_strategy, build_hybrid_train_step  # noqa: F401

# module-level shortcuts onto the fleet singleton — the reference binds
# every Fleet method as a fleet-module attribute (ref:
# distributed/fleet/__init__.py:36-65); real user code calls
# `fleet.init_worker()` etc. on the MODULE
_final_strategy = fleet._final_strategy
_get_applied_meta_list = fleet._get_applied_meta_list
_get_applied_graph_list = fleet._get_applied_graph_list
is_worker = fleet.is_worker
worker_endpoints = fleet.worker_endpoints
server_num = fleet.server_num
server_index = fleet.server_index
server_endpoints = fleet.server_endpoints
is_server = fleet.is_server
barrier_worker = fleet.barrier_worker
init_worker = fleet.init_worker
init_server = fleet.init_server
run_server = fleet.run_server
stop_worker = fleet.stop_worker
save_inference_model = fleet.save_inference_model
save_persistables = fleet.save_persistables
minimize = fleet.minimize
step = fleet.step
clear_grad = fleet.clear_grad
set_lr = fleet.set_lr
get_lr = fleet.get_lr
state_dict = fleet.state_dict
set_state_dict = fleet.set_state_dict
from .data_generator import (  # noqa: F401
    DataGenerator, MultiSlotDataGenerator, MultiSlotStringDataGenerator)


class UtilBase:
    """Fleet util helpers (ref: python/paddle/distributed/fleet/base/
    util_factory.py): small collective conveniences over the jax backend."""

    def all_reduce(self, input, mode="sum"):  # noqa: A002
        from ..collective import ReduceOp, all_reduce as _ar
        op = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX,
              "min": ReduceOp.MIN, "avg": ReduceOp.AVG}[mode]
        return _ar(input, op=op)

    def barrier(self):
        from ..collective import barrier as _b
        _b()

    def all_gather(self, input):  # noqa: A002
        from ..collective import all_gather as _ag
        out = []
        _ag(out, input)
        return out


class Role:
    """ref: fleet/base/role_maker.py role enum."""
    WORKER = 1
    SERVER = 2


# (MultiSlotDataGenerator and friends live in data_generator.py — imported
# above; an earlier inline stub was removed in favor of the real module.)


from . import metrics  # noqa: E402,F401
from . import utils  # noqa: E402,F401


util = UtilBase()  # ref: fleet.util (util_factory singleton)
