"""Fleet data generators for slot-formatted recsys data.

Reference: python/paddle/distributed/fleet/data_generator/ —
DataGenerator.generate_sample(line) is user-overridden to yield
(slot_name, values) pairs; run_from_stdin speaks the textual slot
protocol to the C++ feed pipe. TPU-first: the same user contract, but
the parsed samples feed distributed.dataset batches directly (no pipe);
run_from_stdin/run_from_memory remain for protocol compatibility and
offline file preparation.
"""
from __future__ import annotations

import sys


class DataGenerator:
    def __init__(self):
        self.batch_size_ = 1
        self._proto_info = None

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    # --- user contract --------------------------------------------------
    def generate_sample(self, line):
        """Override: return a callable yielding (slot_name, values)."""
        raise NotImplementedError(
            "implement generate_sample(line) returning a generator of "
            "(name, value_list) pairs")

    def generate_batch(self, samples):
        """Optional override: post-process a batch of samples."""
        def local_iter():
            for sample in samples:
                yield sample
        return local_iter

    # --- protocol runners ----------------------------------------------
    def _gen(self, line):
        it = self.generate_sample(line)
        return list(it()) if callable(it) else list(it)

    def run_from_memory(self, lines=None, memory_data=None):
        """Parse `lines`; returns the list of samples (and writes the slot
        protocol to stdout like the reference when invoked as a script)."""
        out = []
        for line in (lines if lines is not None else (memory_data or [])):
            sample = self._gen(line)
            if sample:
                out.append(sample)
        return out

    def run_from_stdin(self):
        for line in sys.stdin:
            line = line.rstrip("\n")
            if not line:
                continue
            sample = self._gen(line)
            if sample:
                sys.stdout.write(self._to_protocol(sample))

    def _to_protocol(self, sample):
        """Textual slot protocol: '<n_slots> [<len> <v>...]...' per line
        (ref: data_generator _gen_str)."""
        parts = [str(len(sample))]
        for _, vals in sample:
            parts.append(str(len(vals)))
            parts.extend(str(v) for v in vals)
        return " ".join(parts) + "\n"


class MultiSlotDataGenerator(DataGenerator):
    """Values are numbers (int ids / float dense) — ref
    MultiSlotDataGenerator type-checks numericness."""

    def _gen(self, line):
        sample = super()._gen(line)
        for name, vals in sample:
            for v in vals:
                if not isinstance(v, (int, float)):
                    raise ValueError(
                        f"MultiSlotDataGenerator slot {name!r} needs "
                        f"numeric values, got {type(v)}")
        return sample


class MultiSlotStringDataGenerator(DataGenerator):
    """Values stay strings (ref MultiSlotStringDataGenerator — avoids the
    numeric conversion cost when the consumer wants raw tokens)."""

    def _gen(self, line):
        sample = super()._gen(line)
        return [(name, [str(v) for v in vals]) for name, vals in sample]
