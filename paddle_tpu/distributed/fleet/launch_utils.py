"""paddle.distributed.fleet.launch_utils module path (ref:
fleet/launch_utils.py) — the launcher-support helpers live in
distributed.utils on this stack."""
from ..utils import (  # noqa: F401
    Cluster, Pod, Trainer, add_arguments, find_free_ports, get_cluster,
    get_host_name_ip, get_logger, terminate_local_procs,
)

__all__ = ["get_cluster", "get_host_name_ip", "find_free_ports",
           "terminate_local_procs", "get_logger", "add_arguments",
           "Cluster", "Pod", "Trainer"]
