"""DataParallel wrapper (ref: python/paddle/fluid/dygraph/parallel.py).

TPU-first: the reference allreduces grads via NCCL after backward (reducer.cc
bucketing). Here data parallelism is expressed as sharding — the wrapped
layer's train step should run under `paddle_tpu.parallel.data_parallel_step`
(pjit over the dp mesh axis) where XLA inserts the gradient all-reduce. The
eager wrapper is therefore a transparent pass-through that keeps the reference
API (scale_loss/apply_collective_grads are folded into the sharded step).
"""
from __future__ import annotations

from ..nn.layer.layers import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    @property
    def _inner_layers(self):
        return self._layers
