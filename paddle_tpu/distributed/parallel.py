"""DataParallel wrapper (ref: python/paddle/fluid/dygraph/parallel.py).

TPU-first: the reference allreduces grads via NCCL after backward (reducer.cc
bucketing). Here data parallelism is expressed as sharding — the wrapped
layer's train step should run under `paddle_tpu.parallel.data_parallel_step`
(pjit over the dp mesh axis) where XLA inserts the gradient all-reduce. The
eager wrapper is therefore a transparent pass-through that keeps the reference
API (scale_loss/apply_collective_grads are folded into the sharded step).
"""
from __future__ import annotations

from ..nn.layer.layers import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        """Pass-through: per-shard losses here are means (pmean'd in the
        sharded step), not sums over a split batch, so the reference's
        divide-by-nranks would double-scale. Kept for API parity."""
        return loss

    def apply_collective_grads(self):
        """Inside a shard_map/pjit region (eager tape running on traced
        values): psum-average every param grad over the mesh — the
        reference reducer's job. Outside traced regions it is a no-op by
        design: the pjit data-parallel path gets its gradient reduction
        from the shard_map transpose, and single-process eager has one
        replica."""
        import jax.core as jcore

        from .collective import ReduceOp, all_reduce
        for p in self._layers.parameters():
            if p.grad is not None and isinstance(p.grad._value,
                                                 jcore.Tracer):
                all_reduce(p.grad, op=ReduceOp.AVG)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    @property
    def _inner_layers(self):
        return self._layers
