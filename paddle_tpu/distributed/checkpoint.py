"""Sharded distributed checkpointing (SURVEY §2.36 at scale).

`paddle.save` materializes every array on one host — correct on a single
process, but a dp/mp-sharded train state on a multi-host mesh is neither
addressable nor affordable there. This module writes each process's
ADDRESSABLE shards only (the multi-host contract: every host writes its own
slice, no cross-host gather; replicated slabs are written once, by their
replica-0 owner), with an index describing global shape/dtype and the saved
slab layout; load reassembles lazily per target device via
`jax.make_array_from_callback`, so a checkpoint can be loaded into a
DIFFERENT mesh/sharding than it was saved from (reshard-on-load).

Consistency model: every save stamps a fresh `save_id` into its per-process
index and shard filenames; the per-process index is written last (write +
atomic rename). `load` merges ONLY the index parts carrying the newest
save_id and raises if fewer parts than the recorded `process_count` are
present — a crash mid-save or a stale mix from an older save is detected
instead of silently loading mixed-version weights.

Ref lineage: fleet checkpoint utils (python/paddle/distributed/fleet/utils/
fs.py + meta_optimizers' checkpoint hooks); design is jax.Array-native
instead of per-rank file copies.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import re as _re
import uuid

import numpy as np

import jax

from ..core.tensor import Tensor


def _flatten(state):
    """(key -> leaf, treedef) via tree_util paths — handles dicts, lists,
    tuples AND namedtuples (typical optimizer state) uniformly."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(state)
    return {jax.tree_util.keystr(p): v for p, v in paths}, treedef


def _coordinated_save_id():
    """One save_id for ALL processes of this save: process 0 draws it and
    broadcasts (jax.distributed must be initialized on multi-host, which
    multi-host meshes already require)."""
    if jax.process_count() == 1:
        return uuid.uuid4().hex[:12]
    from jax.experimental import multihost_utils
    seed = np.frombuffer(uuid.uuid4().bytes[:8], np.uint32).copy()
    seed = multihost_utils.broadcast_one_to_all(seed)
    return "".join(f"{int(x):08x}" for x in seed)[:12]


def save(state, ckpt_dir, process_index=None, save_id=None):
    """Write this process's addressable shards of `state` (a pytree of
    jax.Arrays / Tensors / scalars) under `ckpt_dir`. Every process calls
    this. Shard files carry a per-save id (coordinated across processes);
    the per-process index is renamed into place last, so readers never
    observe a partial save as current."""
    if process_index is None:
        process_index = jax.process_index()
    os.makedirs(ckpt_dir, exist_ok=True)
    if save_id is None:
        save_id = _coordinated_save_id()
    elif not _re.fullmatch(r"[0-9a-f]{12}", save_id):
        # the cleanup pass parses filenames by this exact token shape; a
        # free-form id would orphan its shard files forever
        raise ValueError(
            f"save_id must be 12 lowercase hex chars, got {save_id!r}")
    flat, _ = _flatten(state)
    index = {"__meta__": {"save_id": save_id,
                          "process_count": jax.process_count()}}
    for key, val in flat.items():
        if isinstance(val, Tensor):
            val = val._value
        if not isinstance(val, jax.Array):
            index[key] = {"scalar": val}
            continue
        shards = []
        for sh in val.addressable_shards:
            if sh.replica_id != 0:
                continue  # replicated slab: its replica-0 owner writes it
            starts = tuple(0 if s.start is None else int(s.start)
                           for s in sh.index)
            stops = tuple(val.shape[d] if s.stop is None else int(s.stop)
                          for d, s in enumerate(sh.index))
            safe_key = key.replace("/", "_").replace("'", "").replace(
                "[", ".").replace("]", "")
            # sanitization is lossy ('/'→'_', '[x]'→'.x'); the hash makes
            # distinct keys collision-proof on disk
            safe_key += "-" + hashlib.sha1(key.encode()).hexdigest()[:8]
            # rank FIRST: cleanup/ownership parse the fixed-position
            # tokens, immune to rank-like substrings in parameter names
            fname = (f"r{process_index}.{save_id}.{safe_key}"
                     f".{'_'.join(map(str, starts))}.npy")
            tmp = os.path.join(ckpt_dir, fname + ".tmp")
            with open(tmp, "wb") as f:  # np.save(path) would append .npy
                # bit-preserving byte view: np.save on an ml_dtypes array
                # (bf16, fp8) writes an opaque '|V2' descr that np.load
                # cannot cast back; the index records the true dtype
                np.save(f, np.ascontiguousarray(
                    np.asarray(sh.data)).reshape(-1).view(np.uint8))
            os.replace(tmp, os.path.join(ckpt_dir, fname))
            shards.append({"starts": starts, "stops": stops,
                           "file": fname})
        index[key] = {"shape": tuple(val.shape), "dtype": str(val.dtype),
                      "fmt": "raw1", "shards": shards}
    ipath = os.path.join(ckpt_dir, f"index.p{process_index}.pkl")
    with open(ipath + ".tmp", "wb") as f:
        pickle.dump(index, f, protocol=4)
    os.replace(ipath + ".tmp", ipath)
    # best-effort cleanup: THIS process's files from older saves, and (on
    # process 0) leftovers from ranks beyond the current process count
    # (e.g. a 4-host save resumed as 2 hosts)
    count = jax.process_count()
    for fn in os.listdir(ckpt_dir):
        stale_own = stale_rank = False
        if fn.endswith(".npy"):
            # fixed-position tokens: r<rank>.<save_id>.<key>...
            m = _re.match(r"r(\d+)\.([0-9a-f]{12})\.", fn)
            if m:
                rank, sid = int(m.group(1)), m.group(2)
                stale_own = rank == process_index and sid != save_id
                stale_rank = process_index == 0 and rank >= count
        elif fn.startswith("index.p") and fn.endswith(".pkl") \
                and process_index == 0:
            try:
                stale_rank = int(fn[len("index.p"):-len(".pkl")]) >= count
            except ValueError:
                pass
        if stale_own or stale_rank:
            try:
                os.remove(os.path.join(ckpt_dir, fn))
            except OSError:
                pass


def _merged_index(ckpt_dir):
    parts = []
    for p in sorted(os.listdir(ckpt_dir)):
        if p.startswith("index.p") and p.endswith(".pkl"):
            with open(os.path.join(ckpt_dir, p), "rb") as f:
                parts.append(pickle.load(f))
    if not parts:
        raise FileNotFoundError(f"no index.p*.pkl in {ckpt_dir}")
    by_id: dict = {}
    for part in parts:
        by_id.setdefault(part["__meta__"]["save_id"], []).append(part)
    # a save is loadable only if ALL its process indexes are present; a
    # newer save overwrites index.p0..pN-1, so at most one save_id can be
    # complete at a time — stale leftovers from older/larger runs are
    # incomplete by construction and ignored
    complete = [(sid, ps) for sid, ps in by_id.items()
                if len(ps) == ps[0]["__meta__"]["process_count"]]
    if not complete:
        sid, ps = max(by_id.items(), key=lambda kv: len(kv[1]))
        raise ValueError(
            f"checkpoint {ckpt_dir} has no complete save: best candidate "
            f"{sid} has {len(ps)}/"
            f"{ps[0]['__meta__']['process_count']} process indexes "
            "(crashed save or missing files)")
    if len(complete) > 1:
        raise ValueError(
            f"checkpoint {ckpt_dir} holds {len(complete)} complete saves "
            "— directory was shared between unrelated runs")
    save_id, chosen = complete[0]
    merged: dict = {}
    for part in chosen:
        for key, meta in part.items():
            if key == "__meta__":
                continue
            if key not in merged:
                merged[key] = dict(meta)
            elif "shards" in meta:
                have = {tuple(s["starts"]) for s in merged[key]["shards"]}
                merged[key]["shards"] += [
                    s for s in meta["shards"]
                    if tuple(s["starts"]) not in have]
    return merged


def load(ckpt_dir, like):
    """Rebuild the checkpoint into the structure AND shardings of `like`
    (a pytree whose array leaves are jax.Arrays with target shardings —
    e.g. the freshly-initialized sharded train state). Each target device
    reads only the saved slabs overlapping its shard, so loading neither
    gathers globally nor requires the saved and target meshes to match."""
    index = _merged_index(ckpt_dir)
    flat_like, treedef = _flatten(like)
    out = []
    for key, tgt in flat_like.items():
        meta = index.get(key)
        if meta is None:
            raise KeyError(f"checkpoint {ckpt_dir} has no entry '{key}'")
        if "scalar" in meta:
            out.append(meta["scalar"])
            continue
        was_tensor = isinstance(tgt, Tensor)
        tgt_arr = tgt._value if was_tensor else tgt
        shape = tuple(meta["shape"])
        if tuple(tgt_arr.shape) != shape:
            raise ValueError(f"shape mismatch for '{key}': checkpoint "
                             f"{shape} vs target {tuple(tgt_arr.shape)}")
        if str(tgt_arr.dtype) != meta["dtype"]:
            raise ValueError(
                f"dtype mismatch for '{key}': checkpoint {meta['dtype']} "
                f"vs target {tgt_arr.dtype} — cast explicitly after load")
        dtype = np.dtype(jax.numpy.dtype(meta["dtype"]))
        raw = meta.get("fmt") == "raw1"
        slabs = [(tuple(s["starts"]), tuple(s["stops"]), s["file"])
                 for s in meta["shards"]]
        files: dict = {}

        def read(fname, slab_shape, _files=files, _raw=raw, _dtype=dtype):
            if fname not in _files:
                a = np.load(os.path.join(ckpt_dir, fname), mmap_mode="r")
                if _raw:  # flat uint8 byte stream → true dtype + shape
                    a = a.view(_dtype).reshape(slab_shape)
                _files[fname] = a
            return _files[fname]

        def cb(idx, *, _slabs=slabs, _shape=shape, _dtype=dtype,
               _read=read):
            starts = tuple(0 if s.start is None else int(s.start)
                           for s in idx)
            stops = tuple(_shape[d] if s.stop is None else int(s.stop)
                          for d, s in enumerate(idx))
            block = np.empty([b - a for a, b in zip(starts, stops)],
                             _dtype)
            filled = np.zeros(block.shape, bool)
            for sst, ssp, fname in _slabs:
                inter_a = [max(a, b) for a, b in zip(starts, sst)]
                inter_b = [min(a, b) for a, b in zip(stops, ssp)]
                if any(a >= b for a, b in zip(inter_a, inter_b)):
                    continue
                src = tuple(slice(a - o, b - o)
                            for a, b, o in zip(inter_a, inter_b, sst))
                dst = tuple(slice(a - o, b - o)
                            for a, b, o in zip(inter_a, inter_b, starts))
                slab_shape = [b - a for a, b in zip(sst, ssp)]
                block[dst] = _read(fname, slab_shape)[src]
                filled[dst] = True
            if not filled.all():
                raise ValueError(
                    "checkpoint shards do not cover the requested slice "
                    "(multi-host load missing files?)")
            return block

        arr = jax.make_array_from_callback(shape, tgt_arr.sharding, cb)
        out.append(Tensor(arr) if was_tensor else arr)
    return jax.tree_util.tree_unflatten(treedef, out)
