"""paddle.distributed — collectives + Fleet.

Reference: python/paddle/distributed/. Full collective implementation in
collective.py; fleet/ holds the DistributedStrategy machinery.
"""
from __future__ import annotations

from .collective import (  # noqa: F401
    Group, ParallelEnv, all_gather, all_reduce, barrier, broadcast, get_group,
    get_rank, get_world_size, init_parallel_env, new_group, reduce, ReduceOp,
    scatter, split, reduce_scatter, alltoall, wait,
)
from .parallel import DataParallel  # noqa: F401
from .dataset import InMemoryDataset, QueueDataset  # noqa: F401
from . import checkpoint  # noqa: F401
from . import fleet  # noqa: F401
from . import ps  # noqa: F401


class MultiprocessContext:
    """Join handle for spawned workers (ref: spawn.py MultiprocessContext):
    join() waits for all, and re-raises the first worker failure with its
    traceback."""

    def __init__(self, processes, error_queues):
        self.processes = processes
        self.error_queues = error_queues

    def join(self, timeout=None):
        for p in self.processes:
            p.join(timeout)
        failures = []
        for rank, (p, q) in enumerate(zip(self.processes,
                                          self.error_queues)):
            if p.exitcode not in (0, None):
                tb = q.get() if not q.empty() else "<no traceback captured>"
                failures.append((rank, p.exitcode, tb))
        if failures:
            rank, code, tb = failures[0]
            raise RuntimeError(
                f"spawned worker {rank} exited with code {code}:\n{tb}")
        return True


def _spawn_worker(func, args, rank, nprocs, error_queue, env):
    import os
    import sys
    import traceback
    os.environ.update(env)
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    os.environ["FLAGS_selected_gpus"] = str(rank)
    try:
        if env.get("JAX_PLATFORMS"):
            # belt-and-braces: a site hook may have imported jax and pinned
            # a platform before the env var was read — override via config
            import jax
            jax.config.update("jax_platforms", env["JAX_PLATFORMS"])
        func(*args)
    except KeyboardInterrupt:
        pass
    except Exception:  # noqa: BLE001
        error_queue.put(traceback.format_exc())
        sys.exit(1)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Fork one worker process per rank and run `func(*args)` in each
    (ref: python/paddle/distributed/spawn.py:238 — per-device process
    start, join, error collection).

    TPU-first shape: on a TPU host ONE process drives all local chips
    through the mesh, so intra-host scaling never needs spawn — spawn
    exists for the reference's process-per-rank pattern (CPU workers,
    PS-lite trainers, multi-host tests). Workers default to the CPU
    platform (each owning its own XLA backend); multi-host TPU bootstrap
    goes through distributed.launch -> jax.distributed instead. Workers
    see their rank via PADDLE_TRAINER_ID (get_rank() honors it)."""
    import multiprocessing as mp

    if nprocs <= 0:
        import jax
        nprocs = max(1, jax.device_count())
    import os

    ctx = mp.get_context("spawn")
    env = {"JAX_PLATFORMS": options.pop("backend", "cpu"),
           "PALLAS_AXON_POOL_IPS": ""}
    procs, queues = [], []
    # children must see the platform env at INTERPRETER start (site hooks
    # import jax before any user code runs), so export it around start()
    saved = {k: os.environ.get(k) for k in
             (*env, "PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM")}
    try:
        for rank in range(nprocs):
            os.environ.update(env)
            os.environ["PADDLE_TRAINER_ID"] = str(rank)
            os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
            q = ctx.SimpleQueue()
            p = ctx.Process(target=_spawn_worker,
                            args=(func, args, rank, nprocs, q, env),
                            daemon=daemon)
            p.start()
            procs.append(p)
            queues.append(q)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    context = MultiprocessContext(procs, queues)
    if join:
        context.join()
    return context


def launch():
    from . import launch as launch_mod
    launch_mod.main()


def prepare_context(strategy=None):
    """1.x dygraph parallel bootstrap (ref: fluid/dygraph/parallel.py
    prepare_context) — collapses to init_parallel_env on the jax backend."""
    from .parallel import init_parallel_env
    return init_parallel_env()
