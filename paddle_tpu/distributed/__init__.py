"""paddle.distributed — collectives + Fleet.

Reference: python/paddle/distributed/. Full collective implementation in
collective.py; fleet/ holds the DistributedStrategy machinery.
"""
from __future__ import annotations

from .collective import (  # noqa: F401
    Group, ParallelEnv, all_gather, all_reduce, barrier, broadcast, get_group,
    get_rank, get_world_size, init_parallel_env, new_group, reduce, ReduceOp,
    scatter, split, reduce_scatter, alltoall, wait,
)
from .parallel import DataParallel  # noqa: F401
from . import fleet  # noqa: F401


def spawn(func, args=(), nprocs=-1, **options):
    """Single-host TPU runtime: jax owns all local chips in one process, so
    spawn degenerates to a direct call (ref: python/paddle/distributed/spawn.py
    forks one process per GPU)."""
    func(*args)


def launch():
    from . import launch as launch_mod
    launch_mod.main()


def prepare_context(strategy=None):
    """1.x dygraph parallel bootstrap (ref: fluid/dygraph/parallel.py
    prepare_context) — collapses to init_parallel_env on the jax backend."""
    from .parallel import init_parallel_env
    return init_parallel_env()
