"""Multi-host launch (ref: python/paddle/distributed/launch.py + fleet/launch.py).

The reference forks one trainer process per GPU and wires NCCL ports via env.
TPU-first: one process per HOST drives all local chips; multi-host bootstrap
is jax.distributed.initialize (coordinator address + process id), after which
jax.devices() spans every host and the same Mesh/pjit code scales out.

Usage:
  python -m paddle_tpu.distributed.launch \
      --coordinator=HOST:PORT --num_processes=N --process_id=I train.py ...
Single-host: `python -m paddle_tpu.distributed.launch train.py` just execs.
"""
from __future__ import annotations

import argparse
import os
import runpy
import sys


def initialize_from_env():
    """Initialize jax.distributed from PADDLE_* / standard env if present."""
    import jax
    coord = (os.environ.get("PADDLE_COORDINATOR")
             or os.environ.get("COORDINATOR_ADDRESS"))
    nproc = int(os.environ.get("PADDLE_TRAINERS_NUM",
                               os.environ.get("NUM_PROCESSES", "1")))
    pid = int(os.environ.get("PADDLE_TRAINER_ID",
                             os.environ.get("PROCESS_ID", "0")))
    if coord and nproc > 1:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc, process_id=pid)
    return nproc, pid


def main(argv=None):
    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--coordinator", default=None,
                        help="coordinator host:port for multi-host")
    parser.add_argument("--num_processes", type=int, default=1)
    parser.add_argument("--process_id", type=int, default=0)
    parser.add_argument("--log_dir", default=None)
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if args.coordinator and args.num_processes > 1:
        os.environ["PADDLE_COORDINATOR"] = args.coordinator
        os.environ["PADDLE_TRAINERS_NUM"] = str(args.num_processes)
        os.environ["PADDLE_TRAINER_ID"] = str(args.process_id)
        initialize_from_env()

    sys.argv = [args.script] + args.script_args
    runpy.run_path(args.script, run_name="__main__")


if __name__ == "__main__":
    main()
