"""paddle.distributed.cloud_utils module path (ref: cloud_utils.py) —
derive the Cluster/Pod tree from PaddleCloud-style environment variables
(PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ENDPOINTS, POD_IP, PADDLE_PORT).
"""
from __future__ import annotations

import os

from .utils import get_cluster


def _get_trainers_num():
    return int(os.getenv("PADDLE_TRAINERS_NUM", "1"))


def get_cloud_cluster(args_node_ips=None, args_node_ip=None,
                      args_port=None, selected_devices=None):
    node_ip = os.getenv("POD_IP", args_node_ip or "127.0.0.1")
    eps = os.getenv("PADDLE_TRAINER_ENDPOINTS")
    port = int(os.getenv("PADDLE_PORT", str(args_port or 6170)))
    if eps:
        endpoints = eps.split(",")
        node_ips = []
        for e in endpoints:
            ip = e.split(":")[0]
            if ip not in node_ips:
                node_ips.append(ip)
    else:
        node_ips = args_node_ips if isinstance(args_node_ips, list) \
            else (args_node_ips.split(",") if args_node_ips
                  else [node_ip])
        slots = selected_devices or [0]
        endpoints = [f"{ip}:{port + i}" for ip in node_ips
                     for i in range(len(slots))]
    slots = selected_devices or [0]
    cluster, pod = get_cluster(node_ips, node_ip, endpoints, slots)
    return cluster, pod


def get_cluster_and_pod(args):
    return get_cloud_cluster(
        getattr(args, "cluster_node_ips", None),
        getattr(args, "node_ip", None),
        getattr(args, "started_port", None),
        getattr(args, "selected_devices", None))


__all__ = ["get_cloud_cluster", "get_cluster_and_pod"]
