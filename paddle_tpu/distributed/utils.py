"""paddle.distributed.utils module path (ref: distributed/utils.py) —
launcher-support helpers reworked for the TPU stack: a Cluster/Pod/
Trainer description tree, endpoint assembly, free-port discovery, and
process teardown. Device slots here are TPU processes (one jax process
per host), not GPUs.
"""
from __future__ import annotations

import logging
import socket
import time


def get_logger(log_level=20, name="root"):
    logger = logging.getLogger(name)
    logger.setLevel(log_level)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(message)s"))
        logger.addHandler(h)
    return logger


class Trainer:
    def __init__(self):
        self.accelerators = []
        self.endpoint = None
        self.rank = None

    def __str__(self):
        return (f"accelerators:{self.accelerators} "
                f"endpoint:{self.endpoint} rank:{self.rank}")


class Pod:
    def __init__(self):
        self.rank = None
        self.id = None
        self.addr = None
        self.port = None
        self.trainers = []

    def __str__(self):
        return (f"rank:{self.rank} id:{self.id} addr:{self.addr} "
                f"port:{self.port} trainers:{len(self.trainers)}")


class Cluster:
    def __init__(self, hdfs=None):
        self.job_server = None
        self.pods = []
        self.hdfs = hdfs

    def trainers_nranks(self):
        return len(self.trainers_endpoints())

    def trainers_endpoints(self):
        return [t.endpoint for pod in self.pods for t in pod.trainers]

    def pods_endpoints(self):
        return [f"{pod.addr}:{pod.port}" for pod in self.pods]

    def world_device_ids(self):
        return [t.accelerators for pod in self.pods for t in pod.trainers]


def get_cluster(node_ips, node_ip, trainer_endpoints, device_ids_per_node):
    """Build a Cluster/Pod/Trainer tree (ref: utils.py:297). On this
    stack each trainer is one jax process; device_ids_per_node lists the
    local process slots (e.g. range(procs_per_host))."""
    cluster = Cluster()
    rank = 0
    for pod_rank, ip in enumerate(node_ips):
        pod = Pod()
        pod.rank = pod_rank
        pod.id = pod_rank
        pod.addr = ip
        eps = trainer_endpoints[pod_rank] \
            if isinstance(trainer_endpoints[0], (list, tuple)) \
            else [e for e in trainer_endpoints
                  if e.split(":")[0] == ip]
        for slot, ep in zip(device_ids_per_node, eps):
            t = Trainer()
            t.accelerators = [slot] if not isinstance(slot, (list, tuple)) \
                else list(slot)
            t.endpoint = ep
            t.rank = rank
            rank += 1
            pod.trainers.append(t)
        cluster.pods.append(pod)
    pod = cluster.pods[node_ips.index(node_ip)] if node_ip in node_ips \
        else cluster.pods[0]
    return cluster, pod


def get_host_name_ip():
    try:
        host = socket.gethostname()
        return host, socket.gethostbyname(socket.getfqdn(host))
    except Exception:
        return None


def find_free_ports(num):
    """Reserve `num` distinct free TCP ports (ref: utils.py:377)."""
    ports = set()
    socks = []
    try:
        while len(ports) < num:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind(("", 0))
            socks.append(s)
            ports.add(s.getsockname()[1])
        return ports
    finally:
        for s in socks:
            s.close()


def terminate_local_procs(procs):
    """Terminate launcher-spawned processes: TERM, grace, then KILL
    (ref: utils.py:324; the reference loops alive-checks the same way)."""
    for p in procs:
        proc = getattr(p, "proc", p)
        if proc.poll() is None:
            proc.terminate()
    deadline = time.time() + 10
    for p in procs:
        proc = getattr(p, "proc", p)
        while proc.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        if proc.poll() is None:
            proc.kill()


def add_arguments(argname, type, default, help, argparser, **kwargs):  # noqa: A002,E501
    argparser.add_argument("--" + argname, default=default, type=type,
                           help=help + f" Default: %(default)s.", **kwargs)


__all__ = ["get_logger", "Cluster", "Pod", "Trainer", "get_cluster",
           "get_host_name_ip", "find_free_ports", "terminate_local_procs",
           "add_arguments"]
