"""PS-lite — host-offloaded sparse embedding tables (parameter-server mode).

Reference: python/paddle/fluid/transpiler/distribute_transpiler.py:256 and
fleet/meta_optimizers/parameter_server_optimizer.py — paddle's PS mode keeps
huge recsys embedding tables on parameter-server processes; trainers pull
rows, compute, and push sparse gradients back (async SGD).

TPU-first rework: the accelerator-side analogue of a parameter server is
HOST RAM. TPU VMs carry ~10-20x more host memory than HBM, so the sparse
tables live host-side as numpy arrays; the dense minibatch of pulled rows is
what travels to the device. The pull -> device compute -> push-sparse-grad
cycle is the same contract as the reference's PS, with the "server" being
the local host arena (single-host) — multi-host sharding splits tables by
row range across workers, each host serving its shard (rows are routed by
`row % num_shards`, the reference's default hash policy).

  SparseTable        — host table with sgd/adagrad sparse updates
  PSEmbedding        — nn.Layer: pull rows -> device gather; backward pushes
                       the sparse grads back on .apply_gradients()
  fleet role API     — is_server/is_worker/init_server/run_server/
                       init_worker/stop_worker (fleet/base.py wires these)
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from .. import nn


class SparseTable:
    """Host-resident [rows, dim] embedding table with sparse updates.

    Updates are applied with np.add.at (duplicate ids accumulate, the
    reference's sum-merge of sparse grads).
    """

    def __init__(self, rows, dim, init_std=0.01, optimizer="sgd",
                 learning_rate=0.1, seed=0, num_shards=1, shard_id=0):
        self.rows, self.dim = rows, dim
        self.num_shards, self.shard_id = num_shards, shard_id
        rng = np.random.RandomState(seed)
        # each shard materializes only its own rows (row % num_shards ==
        # shard_id); a dense local index maps global row -> local slot
        self._global_rows = np.arange(shard_id, rows, num_shards)
        self.data = (rng.randn(len(self._global_rows), dim) * init_std) \
            .astype(np.float32)
        self.optimizer = optimizer
        self.lr = learning_rate
        if optimizer == "adagrad":
            self._g2 = np.zeros_like(self.data)
        elif optimizer != "sgd":
            raise ValueError(f"unsupported sparse optimizer {optimizer!r}")

    def _local(self, ids):
        ids = np.asarray(ids).reshape(-1)
        if self.num_shards > 1:
            mine = (ids % self.num_shards) == self.shard_id
            if not mine.all():
                raise ValueError("ids routed to the wrong shard")
        return ids // self.num_shards

    def pull(self, ids):
        """Gather rows for `ids` -> [n, dim] float32 (host array; the
        caller ships it to device)."""
        return self.data[self._local(ids)]

    def push(self, ids, grads):
        """Apply sparse gradients (sum-merged over duplicate ids)."""
        li = self._local(ids)
        g = np.asarray(grads, np.float32).reshape(len(li), self.dim)
        if self.optimizer == "adagrad":
            np.add.at(self._g2, li, g * g)
            g = g / (np.sqrt(self._g2[li]) + 1e-6)
        np.add.at(self.data, li, -self.lr * g)

    def state_dict(self):
        d = {"data": self.data, "global_rows": self._global_rows}
        if self.optimizer == "adagrad":
            d["g2"] = self._g2
        return d

    def set_state_dict(self, d):
        self.data = np.asarray(d["data"], np.float32)
        if "g2" in d and self.optimizer == "adagrad":
            self._g2 = np.asarray(d["g2"], np.float32)


class PSEmbedding(nn.Layer):
    """Sparse-table-backed embedding layer.

    forward(ids) pulls rows host-side, ships the dense [.., dim] block to
    the device as a differentiable leaf; after loss.backward(), call
    .apply_gradients() to push the accumulated grads back to the table.
    This is the reference's distributed-lookup-table op pair
    (lookup_table -> send sparse grad) recast for host-offload."""

    def __init__(self, num_embeddings, embedding_dim, table=None,
                 optimizer="sgd", learning_rate=0.1):
        super().__init__()
        self.table = table or SparseTable(num_embeddings, embedding_dim,
                                          optimizer=optimizer,
                                          learning_rate=learning_rate)
        self._pending = []

    def forward(self, ids):
        import jax.numpy as jnp
        ids_np = np.asarray(ids._value if isinstance(ids, Tensor) else ids)
        pulled = Tensor(jnp.asarray(self.table.pull(ids_np.reshape(-1))))
        pulled.stop_gradient = False
        self._pending.append((ids_np.reshape(-1), pulled))
        out = pulled.reshape(list(ids_np.shape) + [self.table.dim])
        return out

    def apply_gradients(self):
        """Push grads of every pull since the last call."""
        for ids, pulled in self._pending:
            if pulled.grad is not None:
                self.table.push(ids, np.asarray(pulled.grad._value))
        self._pending.clear()


# ----------------------------------------------------------- fleet PS roles

class _PSRuntime:
    """Single-host PS runtime: the 'server' is the local table registry.
    Multi-host would route pull/push by row-shard over the network; the
    role API below keeps the reference's call sequence intact."""

    def __init__(self):
        self.tables = {}
        self.running = False

    def register_table(self, name, table):
        self.tables[name] = table
        return table


_runtime = _PSRuntime()


def runtime():
    return _runtime


def init_server(model_dir=None, **kwargs):
    _runtime.running = True
    if model_dir:
        import os
        import pickle
        path = os.path.join(model_dir, "sparse_tables.pkl")
        if os.path.exists(path):
            with open(path, "rb") as f:
                states = pickle.load(f)
            for name, st in states.items():
                if name in _runtime.tables:
                    _runtime.tables[name].set_state_dict(st)


def run_server():
    _runtime.running = True


def init_worker():
    pass


def stop_worker():
    _runtime.running = False


def save_persistables(dirname, **kwargs):
    import os
    import pickle
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, "sparse_tables.pkl"), "wb") as f:
        pickle.dump({n: t.state_dict()
                     for n, t in _runtime.tables.items()}, f)
