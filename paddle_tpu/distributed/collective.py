"""Distributed collectives.

Reference: python/paddle/distributed/collective.py + the NCCL c_allreduce_op /
c_broadcast_op / c_allgather_op kernels (paddle/fluid/operators/collective/).
TPU-first rework: a "process group" is a jax.sharding.Mesh axis. In eager
mode collectives run as jitted shard_map computations over the global mesh so
XLA emits the real ICI collective (all-reduce/all-gather/...); under pjit the
same APIs trace into the surrounding computation. Multi-host bootstrap goes
through jax.distributed (launch.py), after which jax.devices() spans hosts and
the SAME mesh/collective code scales from 1 chip to a pod — no NCCL ports.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class ParallelEnv:
    def __init__(self):
        self.rank = get_rank()
        self.world_size = get_world_size()
        self.device_id = self.rank
        self.local_rank = jax.process_index()
        self.nranks = self.world_size

    @property
    def dev_id(self):
        return self.device_id


_initialized = False


def init_parallel_env():
    """On TPU one process drives many chips; data parallelism happens through
    sharding, so this records intent + returns the env."""
    global _initialized
    _initialized = True
    return ParallelEnv()


def is_initialized():
    return _initialized


def get_rank(group=None):
    return jax.process_index()


def get_world_size(group=None):
    # logical world = all addressable devices (chips), matching the
    # one-process-per-GPU reference model where world_size == #devices
    return jax.device_count()


def _mesh_1d():
    from ..parallel.mesh import current_mesh
    m = current_mesh()
    if m is not None:
        return m
    devs = np.array(jax.devices())
    return jax.sharding.Mesh(devs, ("dp",))


def _collective_1d(x, op):
    """Run `op` over a 1-D mesh covering all devices via shard_map.

    x must be replicated or host-side; result is fully replicated.
    """
    mesh = _mesh_1d()
    axis = mesh.axis_names[0]
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    f = shard_map(op, mesh=mesh, in_specs=P(), out_specs=P(),
                  check_rep=False)
    return f(x)


def _unwrap(t):
    return t._value if isinstance(t, Tensor) else jnp.asarray(t)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In-place all-reduce (ref: c_allreduce_sum_op). With a single
    participating shard per value this is identity-safe; inside shard_map /
    pjit regions XLA emits the ICI all-reduce."""
    x = _unwrap(tensor)
    axis_or_axes = None
    try:
        # inside shard_map: psum over all mesh axes present
        from jax.core import get_axis_env_size  # noqa: F401
    except Exception:
        pass
    reducer = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
               ReduceOp.MIN: jax.lax.pmin,
               ReduceOp.AVG: jax.lax.pmean}.get(op, jax.lax.psum)
    mesh = _mesh_1d()
    axis = mesh.axis_names
    try:
        out = reducer(x, axis)  # traced context with named axes
    except (NameError, Exception):
        out = x  # single logical copy: reduce over 1 participant is identity
    if isinstance(tensor, Tensor):
        tensor._value = out
        return tensor
    return Tensor(out)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    x = _unwrap(tensor)
    try:
        mesh = _mesh_1d()
        out = jax.lax.all_gather(x, mesh.axis_names[0])
        parts = [out[i] for i in range(out.shape[0])]
    except Exception:
        parts = [x] * get_world_size()
    if tensor_list is not None:
        tensor_list.clear()
        tensor_list.extend(Tensor(p) for p in parts)
        return tensor_list
    return [Tensor(p) for p in parts]


def broadcast(tensor, src=0, group=None, sync_op=True):
    return tensor  # value already replicated across the mesh


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        rank = get_rank()
        tensor._value = _unwrap(tensor_list[rank])
    return tensor


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    stacked = jnp.stack([_unwrap(t) for t in tensor_list])
    summed = jnp.sum(stacked, axis=0)
    tensor._value = summed[get_rank() % summed.shape[0]] \
        if summed.ndim > tensor._value.ndim else summed
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    outs = [Tensor(_unwrap(t)) for t in in_tensor_list]
    if out_tensor_list is not None:
        out_tensor_list.clear()
        out_tensor_list.extend(outs)
        return out_tensor_list
    return outs


def barrier(group=None):
    for d in jax.devices():
        pass
    jax.block_until_ready(jnp.zeros(()))


def wait(tensor, group=None, use_calc_stream=True):
    jax.block_until_ready(_unwrap(tensor))
    return tensor


def split(x, num_or_sections, axis=0):
    from .. import ops
    return ops.split(x, num_or_sections, axis)
