"""Distributed collectives.

Reference: python/paddle/distributed/collective.py + the NCCL c_allreduce_op /
c_broadcast_op / c_allgather_op kernels (paddle/fluid/operators/collective/).
TPU-first rework: a "process group" is a jax.sharding.Mesh axis. In eager
mode collectives run as jitted shard_map computations over the global mesh so
XLA emits the real ICI collective (all-reduce/all-gather/...); under pjit the
same APIs trace into the surrounding computation. Multi-host bootstrap goes
through jax.distributed (launch.py), after which jax.devices() spans hosts and
the SAME mesh/collective code scales from 1 chip to a pod — no NCCL ports.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..parallel.mesh import axis_size as _axis_size
import numpy as np

from ..core.tensor import Tensor


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A process group = a subset of the global ranks (ref: collective.py
    Group over an NCCL sub-communicator). TPU-first lowering: inside a
    traced region the group's collectives pass ``axis_index_groups`` to the
    XLA collective, which partitions the mesh axis into independent ICI
    rings — the hardware analogue of a sub-communicator, with no extra
    process bootstrap."""

    def __init__(self, ranks, gid):
        world = get_world_size()
        self.ranks = sorted(int(r) for r in ranks)
        if any(r < 0 or r >= world for r in self.ranks):
            raise ValueError(f"ranks {ranks} outside world of size {world}")
        self.id = gid
        self.nranks = len(self.ranks)
        # axis_index_groups must partition the axis: non-members reduce
        # among themselves (their result is unused — SPMD runs everywhere).
        # AllReduce accepts uneven groups; gather-style collectives need
        # EQUAL-sized groups, so the remainder is chunked to the group size
        # when it divides evenly (uniform partition), else those collectives
        # reject the group loudly.
        rest = [r for r in range(world) if r not in self.ranks]
        self.axis_index_groups = [self.ranks] + ([rest] if rest else [])
        n = self.nranks
        if len(rest) % n == 0:
            self.uniform_axis_index_groups = [self.ranks] + [
                rest[i:i + n] for i in range(0, len(rest), n)]
        else:
            self.uniform_axis_index_groups = None

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def process_group(self):
        return self

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks})"


_group_registry = {}
_WORLD_GROUP_ID = 0


def new_group(ranks=None, backend=None, timeout=None):
    """Create a process group over `ranks` (global device indices).
    All collectives accept it via `group=`; inside shard_map/pjit regions
    it lowers to axis_index_groups on the XLA collective."""
    if ranks is None:
        ranks = list(range(get_world_size()))
    gid = len(_group_registry) + 1
    g = Group(ranks, gid)
    _group_registry[gid] = g
    return g


def get_group(gid=0):
    if gid == _WORLD_GROUP_ID:
        return Group(range(get_world_size()), _WORLD_GROUP_ID)
    return _group_registry.get(gid)


def _group_kwargs(group, uniform=False):
    """axis_index_groups for a collective. `uniform=True` for gather-style
    collectives (all_gather/all_to_all/psum_scatter), which require
    equal-sized replica groups — raises instead of silently mis-lowering."""
    if group is None:
        return {}
    if not uniform:
        return {"axis_index_groups": group.axis_index_groups}
    if group.uniform_axis_index_groups is None:
        raise ValueError(
            f"group of {group.nranks} ranks cannot partition a world of "
            f"{get_world_size()} into equal-sized replica groups — "
            f"gather-style collectives need len(world) % len(group) == 0")
    return {"axis_index_groups": group.uniform_axis_index_groups}


class ParallelEnv:
    def __init__(self):
        self.rank = get_rank()
        self.world_size = get_world_size()
        self.device_id = self.rank
        self.local_rank = jax.process_index()
        self.nranks = self.world_size

    @property
    def dev_id(self):
        return self.device_id


_initialized = False


def init_parallel_env():
    """On TPU one process drives many chips; data parallelism happens through
    sharding, so this records intent + returns the env."""
    global _initialized
    _initialized = True
    return ParallelEnv()


def is_initialized():
    return _initialized


def get_rank(group=None):
    import os
    r = int(os.environ.get("PADDLE_TRAINER_ID", jax.process_index()))
    if group is not None:
        return group.get_group_rank(r)
    return r


def get_world_size(group=None):
    # logical world = all addressable devices (chips), matching the
    # one-process-per-GPU reference model where world_size == #devices;
    # spawned per-rank workers see the launcher-set world instead
    import os
    if group is not None:
        return group.nranks
    if "PADDLE_TRAINERS_NUM" in os.environ:
        return int(os.environ["PADDLE_TRAINERS_NUM"])
    return jax.device_count()


def _mesh_1d():
    from ..parallel.mesh import current_mesh
    m = current_mesh()
    if m is not None:
        return m
    devs = np.array(jax.devices())
    return jax.sharding.Mesh(devs, ("dp",))


def _global_rank_in(mesh):
    """Traced global linear rank across ALL mesh axes (row-major, matching
    jax device order) — axis_index of the first axis alone is only the
    global rank on a 1-D mesh."""
    me = jnp.zeros((), jnp.int32)
    for a in mesh.axis_names:
        me = me * mesh.shape[a] + jax.lax.axis_index(a)
    return me


def _collective_1d(x, op):
    """Run `op` over a 1-D mesh covering all devices via shard_map.

    x must be replicated or host-side; result is fully replicated.
    """
    mesh = _mesh_1d()
    axis = mesh.axis_names[0]
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    f = shard_map(op, mesh=mesh, in_specs=P(), out_specs=P(),
                  check_rep=False)
    return f(x)


def _unwrap(t):
    return t._value if isinstance(t, Tensor) else jnp.asarray(t)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In-place all-reduce (ref: c_allreduce_sum_op). With a single
    participating shard per value this is identity-safe; inside shard_map /
    pjit regions XLA emits the ICI all-reduce — restricted to `group`'s
    ranks via axis_index_groups when a group is passed."""
    x = _unwrap(tensor)
    reducer = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
               ReduceOp.MIN: jax.lax.pmin,
               ReduceOp.AVG: jax.lax.pmean}.get(op, jax.lax.psum)
    mesh = _mesh_1d()
    # axis_index_groups applies along ONE axis; the world group spans all
    axis = mesh.axis_names if group is None else mesh.axis_names[0]
    kw = _group_kwargs(group)  # AllReduce accepts uneven replica groups
    try:
        out = reducer(x, axis, **kw)
    except NameError:  # eager (no axis context): 1 participant == identity
        out = x
    if isinstance(tensor, Tensor):
        tensor._value = out
        return tensor
    return Tensor(out)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    x = _unwrap(tensor)
    n = group.nranks if group is not None else get_world_size()
    kw = _group_kwargs(group, uniform=True)
    try:
        mesh = _mesh_1d()
        out = jax.lax.all_gather(x, mesh.axis_names[0], **kw)
        parts = [out[i] for i in range(n)]
    except NameError:  # eager: every "rank" holds the same replica
        parts = [x] * n
    if tensor_list is not None:
        tensor_list.clear()
        tensor_list.extend(Tensor(p) for p in parts)
        return tensor_list
    return [Tensor(p) for p in parts]


def broadcast(tensor, src=0, group=None, sync_op=True):
    """Replicate src's value across the group (ref: c_broadcast_op). In a
    traced region: gather the group and select src's slot; eager the value
    is already replicated."""
    x = _unwrap(tensor)
    kw = _group_kwargs(group, uniform=True)
    try:
        mesh = _mesh_1d()
        gathered = jax.lax.all_gather(x, mesh.axis_names[0], **kw)
        slot = group.get_group_rank(src) if group is not None else src
        out = gathered[slot]
    except NameError:
        out = x  # already replicated outside traced regions
    if isinstance(tensor, Tensor):
        tensor._value = out
        return tensor
    return Tensor(out)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reduce to `dst` (ref: c_reduce_sum_op): dst ends up with the reduced
    value, every other rank keeps its ORIGINAL tensor — implemented as
    all-reduce + per-rank select, the SPMD analogue of a rooted reduce (the
    wire cost on ICI is the same all-reduce ring)."""
    x = _unwrap(tensor)
    reducer = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
               ReduceOp.MIN: jax.lax.pmin,
               ReduceOp.AVG: jax.lax.pmean}.get(op, jax.lax.psum)
    mesh = _mesh_1d()
    kw = _group_kwargs(group)
    try:
        if group is not None:
            # groups are defined along the first axis (1-D contract shared
            # with all_reduce's axis_index_groups lowering)
            reduced = reducer(x, mesh.axis_names[0], **kw)
            me = jax.lax.axis_index(mesh.axis_names[0])
        else:
            reduced = reducer(x, mesh.axis_names)
            me = _global_rank_in(mesh)  # dst is a GLOBAL rank
        out = jnp.where(me == dst, reduced, x)
    except NameError:  # eager, 1 participant: reduce == identity
        out = x
    if isinstance(tensor, Tensor):
        tensor._value = out
        return tensor
    return Tensor(out)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """Rank i receives tensor_list[i] from src (ref: c_scatter_op). In a
    traced region each rank selects its slot by axis_index — the values are
    already device-resident under SPMD, so no wire traffic is needed; eager
    falls back to host-side indexing."""
    if not tensor_list:
        return tensor
    vals = jnp.stack([_unwrap(t) for t in tensor_list])
    try:
        mesh = _mesh_1d()
        me = jax.lax.axis_index(mesh.axis_names[0]) if group is not None \
            else _global_rank_in(mesh)  # slots are GLOBAL ranks
        if group is not None:
            # position within the group; non-members keep their input
            gr = jnp.asarray(group.ranks)
            slot = jnp.argmax(gr == me)
            member = jnp.any(gr == me)
            picked = jnp.take(vals, slot, axis=0)
            tensor._value = jnp.where(member, picked, _unwrap(tensor))
        else:
            tensor._value = jnp.take(vals, me, axis=0)
    except NameError:
        rank = get_rank(group)
        tensor._value = _unwrap(tensor_list[max(rank, 0)])
    return tensor


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    x = jnp.concatenate([_unwrap(t) for t in tensor_list], axis=0) \
        if isinstance(tensor_list, (list, tuple)) else _unwrap(tensor_list)
    kw = _group_kwargs(group, uniform=True)
    try:
        mesh = _mesh_1d()
        out = jax.lax.psum_scatter(x, mesh.axis_names[0],
                                   scatter_dimension=0, tiled=True, **kw)
    except NameError:
        stacked = jnp.stack([_unwrap(t) for t in tensor_list])
        summed = jnp.sum(stacked, axis=0)
        out = summed[get_rank() % summed.shape[0]] \
            if summed.ndim > tensor._value.ndim else summed
    tensor._value = out
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    """Exchange the i-th input with rank i (ref: c_alltoall). In a traced
    region lowers to XLA all_to_all over the mesh axis (ICI all-to-all)."""
    kw = _group_kwargs(group, uniform=True)
    try:
        mesh = _mesh_1d()
        x = jnp.stack([_unwrap(t) for t in in_tensor_list])  # [n, ...]
        out = jax.lax.all_to_all(x, mesh.axis_names[0], split_axis=0,
                                 concat_axis=0, tiled=False, **kw)
        outs = [Tensor(out[i]) for i in range(out.shape[0])]
    except NameError:
        outs = [Tensor(_unwrap(t)) for t in in_tensor_list]
    if out_tensor_list is not None:
        out_tensor_list.clear()
        out_tensor_list.extend(outs)
        return out_tensor_list
    return outs


def barrier(group=None):
    """Device-wide rendezvous (ref: barrier_op): a tiny all-reduce over the
    global mesh — the result cannot materialize until every device has
    entered the collective, which IS the barrier on ICI."""
    mesh = _mesh_1d()
    axis = mesh.axis_names[0]
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    f = shard_map(lambda x: jax.lax.psum(x, axis), mesh=mesh,
                  in_specs=P(), out_specs=P(), check_rep=False)
    jax.block_until_ready(f(jnp.zeros((), jnp.int32)))


def wait(tensor, group=None, use_calc_stream=True):
    jax.block_until_ready(_unwrap(tensor))
    return tensor


def split(x, num_or_sections, axis=0):
    from .. import ops
    return ops.split(x, num_or_sections, axis)


def quantized_all_reduce(x, axis_name, bits=8, block=256):
    """Bandwidth-compressed gradient all-reduce (EQuARX pattern,
    arXiv:2506.17615 — public technique; code original): int8 blockwise-
    quantized reduce-scatter + all-gather moves ~1/4 of the f32 bytes over
    ICI/DCN. Call INSIDE shard_map over `axis_name`, like jax.lax.psum.

    Decomposition: split x into n per-rank chunks; each rank quantizes
    every chunk with a per-block scale and all_to_alls them so rank j
    receives all n copies of chunk j; summation happens dequantized in
    f32 (one quantization error per hop, not log(n)); the reduced chunk
    is requantized once and all_gathered. Worst-case relative error per
    element ~1/2^(bits-1) of the block max — gradient-noise scale, the
    same regime DGC/bf16-allreduce target."""
    from ..slim import dequantize, quantize_symmetric
    n = _axis_size(axis_name)
    if x.size < n * block:
        # tiny leaves (biases, norm scales): padding to n*block would SEND
        # more bytes than the plain f32 psum saves — don't compress them
        return jax.lax.psum(x, axis_name)
    orig_shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    pad = (-flat.size) % (n * block)
    flat = jnp.pad(flat, (0, pad))
    # [n, chunk_blocks, block]
    chunks = flat.reshape(n, -1, block)

    def quant(v):  # per-block symmetric codes (shared slim scheme: the
        # scale is the block abs-max, codes are int8/int16 by `bits`)
        scale = jnp.maximum(
            jnp.max(jnp.abs(v), axis=-1, keepdims=True), 1e-30)
        return quantize_symmetric(v, scale, bits), scale

    def dequant(q, scale):
        return dequantize(q, scale, bits)

    q, s = quant(chunks)
    # all_to_all: rank r sends its quantized chunk j to rank j; afterwards
    # axis 0 holds the n ranks' versions of MY chunk
    q_t = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                             tiled=False)
    s_t = jax.lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0,
                             tiled=False)
    reduced = jnp.sum(dequant(q_t, s_t), axis=0)  # f32 accumulate
    rq, rs = quant(reduced)
    gq = jax.lax.all_gather(rq, axis_name)
    gs = jax.lax.all_gather(rs, axis_name)
    out = dequant(gq, gs).reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(orig_shape).astype(x.dtype)


def quantized_allreduce_wire_bytes(size, n, bits=8, block=256):
    """Per-rank wire bytes of `quantized_all_reduce` vs the f32 ring
    all-reduce it replaces, for a `size`-element f32 tensor over n ranks.
    Instrumentation for the byte-savings claim (VERDICT r4 next #8) —
    the same block/padding arithmetic as the collective itself.

    Compressed: the all_to_all sends each rank's (n-1)/n foreign chunks
    once (codes + per-block scales), the all_gather sends the reduced
    local chunk to the other n-1 ranks. f32 ring: reduce-scatter +
    all-gather each move size*4*(n-1)/n bytes per rank.
    """
    f32 = 2 * size * 4 * (n - 1) // n
    if size < n * block:
        # mirrors the collective's small-tensor fallback: plain f32 psum,
        # no savings (bucket small leaves to compress them)
        return f32, f32
    code_bytes = bits // 8
    padded = size + (-size) % (n * block)
    chunk = padded // n
    scale_bytes = (chunk // block) * 4
    a2a = (n - 1) * (chunk * code_bytes + scale_bytes)
    ag = (n - 1) * (chunk * code_bytes + scale_bytes)
    return a2a + ag, f32


def bucketed_quantized_all_reduce(grads, axis_name, bucket_bytes=1 << 25,
                                  bits=8, block=256):
    """Gradient sync in fixed-size buckets of concatenated leaves (ref:
    the imperative reducer's bucketed NCCL all-reduce overlapping the
    backward). Two effects vs per-leaf quantized_all_reduce: (a) small
    leaves (biases, norms) ride the compressed path inside a bucket
    instead of falling back to plain f32 psum, and (b) each bucket is an
    INDEPENDENT collective depending only on its own leaves' grads, so
    XLA's scheduler can start bucket i's all_to_all while the backward
    for earlier layers (later buckets) is still computing — the overlap
    the reference gets from its reducer thread. Call inside shard_map
    over `axis_name`. Returns the summed tree (divide by n for mean).
    """
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    buckets, cur, cur_bytes = [], [], 0
    for i, leaf in enumerate(leaves):
        cur.append(i)
        cur_bytes += leaf.size * 4
        if cur_bytes >= bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(cur)
    out = [None] * len(leaves)

    def _blockpad(i):
        # each leaf padded to a BLOCK boundary: a tiny bias grad must not
        # share a block abs-max scale with a neighboring weight grad (a
        # shared O(1) scale quantizes an O(1e-4) bias to pure noise)
        v = leaves[i].reshape(-1).astype(jnp.float32)
        pad = (-v.size) % block
        return jnp.pad(v, (0, pad)) if pad else v, v.size + pad

    for idx in buckets:
        padded = [_blockpad(i) for i in idx]
        flat = jnp.concatenate([p[0] for p in padded])
        red = quantized_all_reduce(flat, axis_name, bits=bits, block=block)
        off = 0
        for i, (_, n_pad) in zip(idx, padded):
            n_el = leaves[i].size
            out[i] = red[off:off + n_el].reshape(
                leaves[i].shape).astype(leaves[i].dtype)
            off += n_pad
    return jax.tree_util.tree_unflatten(treedef, out)
