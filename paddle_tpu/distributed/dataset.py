"""Dataset classes for PS-style file feeding.

Reference: python/paddle/fluid/dataset.py (InMemoryDataset:329,
QueueDataset:941) — file-list driven feeding for recsys training, lines
parsed into slots by a data generator. TPU-first rework: no pipe
subprocess protocol; lines are parsed host-side by a
fleet.MultiSlot*DataGenerator (or a whitespace-float fallback) and batches
come out as dicts of numpy arrays ready for device upload. InMemory loads
and shuffles in RAM; Queue streams files lazily.
"""
from __future__ import annotations

import random

import numpy as np


class DatasetBase:
    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.use_vars = []
        self.pipe_command = None
        self.filelist = []
        self._generator = None

    # --- reference init/config surface ---------------------------------
    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command=None, input_type=0, fs_name="", fs_ugi="",
             download_cmd="cat", **kwargs):
        self.batch_size = int(batch_size)
        self.thread_num = int(thread_num)
        self.use_vars = list(use_var or [])
        self.pipe_command = pipe_command
        return self

    def set_batch_size(self, batch_size):
        self.batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self.thread_num = int(thread_num)

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def set_use_var(self, var_list):
        self.use_vars = list(var_list)

    def set_pipe_command(self, pipe_command):
        self.pipe_command = pipe_command

    def set_data_generator(self, generator):
        """TPU-first replacement for the pipe protocol: parse lines with a
        fleet.DataGenerator instance directly (no subprocess)."""
        self._generator = generator

    # --- parsing -------------------------------------------------------
    def _parse_line(self, line):
        if self._generator is not None:
            # go through the generator's _gen hook so MultiSlot numeric
            # validation / string coercion apply, and both callable and
            # plain-generator generate_sample returns are accepted
            return self._generator._gen(line)
        # fallback: whitespace-separated floats, one unnamed slot
        vals = [float(t) for t in line.split()]
        return [("slot_0", vals)]

    def _iter_files(self):
        for path in self.filelist:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.rstrip("\n")
                    if line:
                        yield self._parse_line(line)

    @staticmethod
    def _batch(samples):
        slots = {}
        for sample in samples:
            for name, vals in sample:
                slots.setdefault(name, []).append(vals)
        return {k: np.asarray(v) for k, v in slots.items()}


class InMemoryDataset(DatasetBase):
    """ref: fluid/dataset.py:329 — load the full filelist into host RAM,
    shuffle there, then iterate batches."""

    def __init__(self):
        super().__init__()
        self._memory = []

    def load_into_memory(self):
        self._memory = list(self._iter_files())

    def preload_into_memory(self, thread_num=None):
        self.load_into_memory()

    def wait_preload_done(self):
        pass

    def local_shuffle(self):
        random.shuffle(self._memory)

    def global_shuffle(self, fleet=None, thread_num=12):
        # single-host: global == local; multi-host would all-to-all rows
        self.local_shuffle()

    def release_memory(self):
        self._memory = []

    def get_memory_data_size(self, fleet=None):
        return len(self._memory)

    def get_shuffle_data_size(self, fleet=None):
        return len(self._memory)

    def __iter__(self):
        for i in range(0, len(self._memory), self.batch_size):
            yield self._batch(self._memory[i:i + self.batch_size])


class QueueDataset(DatasetBase):
    """ref: fluid/dataset.py:941 — streaming: files are read lazily, no
    global shuffle available (matches the reference's contract)."""

    def local_shuffle(self):
        raise NotImplementedError(
            "QueueDataset streams files; use InMemoryDataset for shuffle "
            "(same contract as the reference)")

    def global_shuffle(self, fleet=None, thread_num=12):
        raise NotImplementedError(
            "QueueDataset streams files; use InMemoryDataset for shuffle")

    def __iter__(self):
        buf = []
        for sample in self._iter_files():
            buf.append(sample)
            if len(buf) == self.batch_size:
                yield self._batch(buf)
                buf = []
        if buf:
            yield self._batch(buf)
