"""Struct-of-arrays slot parameter buffers for the serving engine.

`SlotParamStore` is the host-side owner of the per-slot sampling state:
one numpy column per `SamplingParams` field, indexed by decode slot, a
per-slot stop-token id set, and the [n_slots, V] token-count scatter
buffer the penalty processors read. Admission scatters a request's
params into its slot row (`set_slot`); slot release resets the row to
greedy defaults (`clear_slot`) so the dispatch MODE flags — the static
(any-sampled, any-penalties) pair that picks a compiled decode variant
— always reflect the resident requests only.

`step_args` / `packed_args` assemble the device argument dict one
jitted dispatch consumes: always the stop-token matrix; plus the
sampling columns when any resident request samples; plus the penalty
columns and count buffer when any uses penalties. Param VALUES are
traced — only the mode pair and the pow2-bucketed stop-matrix width
select compiled variants, so the variant count is small and bounded.

The count buffer round-trips functionally through the jitted decode
(like the KV pool arrays): dispatches return the updated array and the
server reinstalls it via `swap_counts`. Cost: n_slots * vocab * 4
bytes (8 slots x GPT-2 vocab = ~1.6 MB) — only materialized once a
penalty-using request is admitted.
"""
from __future__ import annotations

import numpy as np

from .params import GREEDY, SamplingParams


def _pow2(n):
    p = 1
    while p < n:
        p *= 2
    return p


def greedy_args(rows):
    """Minimal all-greedy argument dict for direct decoder calls (tests
    and offline paths that want plain argmax with no stop ids)."""
    import jax.numpy as jnp

    return {"stop": jnp.full((int(rows), 1), -1, jnp.int32)}


GREEDY_MODE = (False, False)


class SlotParamStore:
    """Per-slot sampling parameters as struct-of-arrays buffers."""

    def __init__(self, n_slots, vocab_size):
        self.n = int(n_slots)
        self.V = int(vocab_size)
        self._params: list[SamplingParams] = [GREEDY] * self.n
        self._seeds = np.zeros((self.n,), np.uint32)
        self._stop_ids: list[tuple] = [()] * self.n
        self._counts = None  # device [n, V] int32, lazy

    # ---- slot lifecycle ------------------------------------------------
    def set_slot(self, i, params, seed, eos=-1, prompt_ids=None):
        """Scatter one request's params into slot row i (admission /
        refill). The server-level EOS id joins the request's stop ids in
        the slot's stop set; `prompt_ids` seeds the penalty count row
        when the request uses penalties."""
        self._params[i] = params
        self._seeds[i] = np.uint32(int(seed) & 0xFFFFFFFF)
        ids = set(params.stop_token_ids)
        if eos is not None and eos >= 0:
            ids.add(int(eos))
        self._stop_ids[i] = tuple(sorted(ids))
        if params.uses_penalties and prompt_ids is not None:
            self.reset_counts_row(i, prompt_ids)

    def clear_slot(self, i):
        self._params[i] = GREEDY
        self._seeds[i] = 0
        self._stop_ids[i] = ()

    def params(self, i):
        return self._params[i]

    # ---- dispatch mode (static jit-variant selector) -------------------
    def mode(self, rows=None):
        ps = (self._params if rows is None
              else [self._params[r] for r in rows])
        return (any(not p.is_greedy for p in ps),
                any(p.uses_penalties for p in ps))

    # ---- count scatter buffer ------------------------------------------
    @property
    def counts(self):
        import jax.numpy as jnp

        if self._counts is None:
            self._counts = jnp.zeros((self.n, self.V), jnp.int32)
        return self._counts

    def reset_counts_row(self, i, prompt_ids):
        import jax.numpy as jnp

        row = np.bincount(np.asarray(prompt_ids, np.int64).reshape(-1),
                          minlength=self.V)[:self.V].astype(np.int32)
        self._counts = self.counts.at[i].set(jnp.asarray(row))

    def swap_counts(self, new):
        """Reinstall the count buffer a dispatch returned (None when the
        dispatch ran a no-penalty variant)."""
        if new is not None:
            self._counts = new

    # ---- device argument assembly --------------------------------------
    def _stop_matrix(self, rows):
        w = _pow2(max([len(self._stop_ids[r]) for r in rows] + [1]))
        m = np.full((len(rows), w), -1, np.int32)
        for j, r in enumerate(rows):
            ids = self._stop_ids[r]
            m[j, :len(ids)] = ids
        return m

    def _assemble(self, rows, steps, mode):
        import jax.numpy as jnp

        sampled, penalties = mode
        ps = [self._params[r] for r in rows]
        sp = {"stop": jnp.asarray(self._stop_matrix(rows))}
        if sampled:
            temp = np.array([p.temperature for p in ps], np.float32)
            sp["temperature"] = jnp.asarray(temp)
            sp["sample"] = jnp.asarray(temp > 0.0)
            sp["top_k"] = jnp.asarray(
                np.array([p.top_k for p in ps], np.int32))
            sp["top_p"] = jnp.asarray(
                np.array([p.top_p for p in ps], np.float32))
            sp["min_p"] = jnp.asarray(
                np.array([p.min_p for p in ps], np.float32))
            sp["seeds"] = jnp.asarray(self._seeds[list(rows)])
            sp["steps"] = jnp.asarray(np.asarray(steps, np.int32))
        if penalties:
            sp["rep"] = jnp.asarray(
                np.array([p.repetition_penalty for p in ps], np.float32))
            sp["pres"] = jnp.asarray(
                np.array([p.presence_penalty for p in ps], np.float32))
            sp["freq"] = jnp.asarray(
                np.array([p.frequency_penalty for p in ps], np.float32))
            sp["counts"] = self.counts
        return sp

    def step_args(self, steps):
        """Decode-dispatch arguments: one row per slot (row == slot).
        `steps` [n_slots] int32 = tokens generated so far per slot (the
        PRNG step counter). Returns (sp dict, mode)."""
        rows = list(range(self.n))
        mode = self.mode()
        return self._assemble(rows, steps, mode), mode

    def verify_args(self, slot_rows, steps):
        """Speculative-verification arguments: compact plan rows like
        `packed_args`, plus per-row base PRNG steps. `slot_rows` maps
        plan row -> slot index (None = padding row); `steps` [P] int32
        is each row's generated-token count — verify position j samples
        at step base+j on device, the same counter j sequential decode
        steps would fold in. Padding rows alias slot 0's columns; the
        verify program masks them via dlen == -1. Returns (sp dict,
        mode)."""
        import jax.numpy as jnp

        real = [r for r in slot_rows if r is not None]
        mode = self.mode(real)
        rows = [r if r is not None else 0 for r in slot_rows]
        sp = self._assemble(rows, np.asarray(steps, np.int32), mode)
        if mode[1]:
            sp["crows"] = jnp.asarray(np.array(rows, np.int32))
        return sp, mode

    def unified_args(self, slot_rows, emit_rows, steps):
        """Unified-round arguments (one-kernel round, r16): compact
        plan rows like `verify_args`, covering every row KIND the
        fused round mixes. `slot_rows` maps plan row -> slot (None =
        padding row); `emit_rows` marks rows whose samples are real —
        decode rows, verify rows and prefill rows completing their
        prompt this round; still-feeding prefill rows and padding rows
        compute a discarded sample and are masked out of the dispatch
        MODE selection, the sample flags and (via dlen == -1 on
        device) the stop/penalty accounting. `steps` [P] int32 is each
        row's base PRNG step (overridden on device by the async
        carry where steps_map names a slot). Returns (sp dict,
        mode)."""
        import jax.numpy as jnp

        emit = list(emit_rows)
        real = [r for r, e in zip(slot_rows, emit) if r is not None
                and e]
        mode = self.mode(real)
        rows = [r if r is not None else 0 for r in slot_rows]
        sp = self._assemble(rows, np.asarray(steps, np.int32), mode)
        if mode[0]:
            # non-emitting rows must not sample (their seeds may alias
            # another slot's stream — and their token is discarded)
            sp["sample"] = sp["sample"] & jnp.asarray(
                np.asarray(emit, bool))
        if mode[1]:
            sp["crows"] = jnp.asarray(np.array(rows, np.int32))
        return sp, mode

    def warm_unified_args(self, n_rows, mode=GREEDY_MODE):
        """`unified_args` SHAPED like a live dispatch for `n_rows`
        all-padding plan rows under `mode` — the unified-round half of
        `warm_args` (same key set as a live `unified_args` call, so
        the compiled variant is the one traffic hits)."""
        import jax.numpy as jnp

        rows = [0] * int(n_rows)
        steps = np.zeros((len(rows),), np.int32)
        sp = self._assemble(rows, steps, mode)
        if mode[0]:
            sp["sample"] = sp["sample"] & jnp.zeros((len(rows),), bool)
        if mode[1]:
            sp["crows"] = jnp.asarray(np.array(rows, np.int32))
        return sp

    def warm_args(self, n_rows, mode=GREEDY_MODE):
        """Packed-prefill argument dict SHAPED like a live dispatch for
        `n_rows` plan rows under `mode`, built from idle-slot defaults —
        the sampling-buffer side of the serving engine's shape-bucket
        pre-warm (`PagedGenerationServer.warm_buckets`). Every key a
        real `packed_args` call would carry for that mode is present
        with the same dtype/shape (stop-matrix width 1 — the idle /
        single-stop-id case, which is also the pow2 bucket a lone EOS
        id selects), so a jitted variant compiled against it is the
        variant live traffic hits."""
        import jax.numpy as jnp

        rows = [0] * int(n_rows)
        steps = np.zeros((len(rows),), np.int32)
        sp = self._assemble(rows, steps, mode)
        if mode[0]:
            # no warm row ever actually samples (mirrors packed_args'
            # padding-row masking: same structure, all-False)
            sp["sample"] = sp["sample"] & jnp.zeros((len(rows),), bool)
        if mode[1]:
            sp["crows"] = jnp.asarray(np.array(rows, np.int32))
            sp["row_done"] = jnp.asarray(np.zeros((len(rows),), bool))
        return sp

    def packed_args(self, slot_rows, done_mask, steps=None):
        """Packed-prefill arguments: compact plan rows. `slot_rows` maps
        plan row -> slot index (None = padding row); `done_mask` marks
        rows whose prompt completes this chunk (the only rows whose
        token-0 sample is real). `steps` [P] int32 is each row's PRNG
        base step — 0 for a fresh prompt (token 0 samples at step 0),
        and the generated-token count for a PREEMPTED request resuming
        by re-prefill (round 12), so the resume prefill draws the same
        counter-based stream position an uninterrupted decode would
        have. None = all zeros (the exact pre-resume behavior).
        Returns (sp dict, mode)."""
        import jax.numpy as jnp

        real = [r for r in slot_rows if r is not None]
        mode = self.mode(real)
        rows = [r if r is not None else 0 for r in slot_rows]
        valid = np.array([r is not None for r in slot_rows], bool)
        if steps is None:
            steps = np.zeros((len(rows),), np.int32)
        sp = self._assemble(rows, np.asarray(steps, np.int32), mode)
        if not mode[0]:
            sp.pop("sample", None)
        else:
            # padding rows must not sample (their seeds alias slot 0)
            sp["sample"] = sp["sample"] & jnp.asarray(valid)
        if mode[1]:
            sp["crows"] = jnp.asarray(np.array(rows, np.int32))
            sp["row_done"] = jnp.asarray(
                np.asarray(done_mask, bool) & valid)
        return sp, mode
