"""Per-request sampling parameters with EAGER validation.

`SamplingParams` is the one request-level knob bundle of the serving
stack (ISSUE 5): temperature / top-k / top-p / min-p, the three
penalties, an optional reproducibility seed, stop conditions, and a
per-request token budget. Validation happens in `__post_init__` — a bad
value raises a ValueError that NAMES the offending field and value at
`submit()` time, instead of surfacing minutes later as a jit-time
shape or NaN failure inside a compiled decode program.

The dataclass is frozen: instances are shared freely between the
client thread, the scheduler, and the slot parameter buffers without
copy or lock.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field


def _check_finite(name, v, lo=None, hi=None, lo_open=False):
    """Reject NaN/inf and range violations, naming field and value."""
    v = float(v)
    if math.isnan(v) or math.isinf(v):
        raise ValueError(f"{name} must be finite, got {v!r}")
    if lo is not None and (v <= lo if lo_open else v < lo):
        bound = f"> {lo}" if lo_open else f">= {lo}"
        raise ValueError(f"{name} must be {bound}, got {v!r}")
    if hi is not None and v > hi:
        raise ValueError(f"{name} must be <= {hi}, got {v!r}")
    return v


@dataclass(frozen=True)
class SamplingParams:
    """Per-request decode configuration.

    temperature: 0.0 = greedy (bitwise-identical to the pre-sampling
        argmax path); > 0 samples from the scaled distribution.
    top_k: keep only the k highest-probability tokens (0 = off).
    top_p: nucleus — keep the smallest set of tokens whose cumulative
        probability reaches top_p, in (0, 1]; 1.0 = off.
    min_p: drop tokens whose probability is below min_p * max-prob,
        in [0, 1); 0.0 = off.
    repetition_penalty: HF-style — logits of tokens already seen
        (prompt + generated) are divided (if > 0) / multiplied (if < 0)
        by this; 1.0 = off.
    presence_penalty / frequency_penalty: OpenAI-style additive
        penalties on seen tokens (flat / per-occurrence); 0.0 = off.
    seed: per-request PRNG stream seed. A fixed seed reproduces the
        sampled tokens REGARDLESS of batch composition or slot index
        (counter-based streams: fold_in(seed, step)). None = the server
        derives a unique seed per request.
    stop_token_ids: generation stops when any of these ids is emitted
        (checked on device, like EOS; the stop token is kept in the
        output).
    stop_strings: generation stops when the detokenized tail of the
        output contains any of these strings (checked host-side;
        requires the server to be built with a `detokenize` callable).
    max_new_tokens: per-request budget; None = the server default.
    """

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    min_p: float = 0.0
    repetition_penalty: float = 1.0
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    seed: int | None = None
    stop_token_ids: tuple = field(default_factory=tuple)
    stop_strings: tuple = field(default_factory=tuple)
    max_new_tokens: int | None = None

    def __post_init__(self):
        set_ = object.__setattr__
        set_(self, "temperature",
             _check_finite("temperature", self.temperature, lo=0.0))
        try:
            tk = int(self.top_k)
            if tk != self.top_k or tk < 0:
                raise ValueError
        except (TypeError, ValueError):
            raise ValueError(
                f"top_k must be an int >= 0, got {self.top_k!r}") from None
        set_(self, "top_k", tk)
        # top_p in (0, 1]: 0 would keep no tokens at all
        set_(self, "top_p",
             _check_finite("top_p", self.top_p, lo=0.0, hi=1.0,
                           lo_open=True))
        # min_p in [0, 1): 1 would drop everything but exact-max ties
        mp = _check_finite("min_p", self.min_p, lo=0.0)
        if mp >= 1.0:
            raise ValueError(f"min_p must be < 1, got {self.min_p!r}")
        set_(self, "min_p", mp)
        set_(self, "repetition_penalty",
             _check_finite("repetition_penalty", self.repetition_penalty,
                           lo=0.0, lo_open=True))
        set_(self, "presence_penalty",
             _check_finite("presence_penalty", self.presence_penalty))
        set_(self, "frequency_penalty",
             _check_finite("frequency_penalty", self.frequency_penalty))
        if self.seed is not None:
            try:
                sd = int(self.seed)
            except (TypeError, ValueError):
                raise ValueError(f"seed must be an int or None, "
                                 f"got {self.seed!r}")
            set_(self, "seed", sd & 0xFFFFFFFF)
        stop_ids = tuple(self.stop_token_ids)
        for t in stop_ids:
            if int(t) < 0:
                raise ValueError(
                    f"stop_token_ids must be >= 0, got {t!r}")
        set_(self, "stop_token_ids", tuple(int(t) for t in stop_ids))
        stops = tuple(self.stop_strings)
        for s in stops:
            if not isinstance(s, str) or s == "":
                raise ValueError(
                    f"stop_strings entries must be non-empty strings, "
                    f"got {s!r}")
        set_(self, "stop_strings", stops)
        if self.max_new_tokens is not None:
            mnt = int(self.max_new_tokens)
            if mnt < 1:
                raise ValueError(f"max_new_tokens must be >= 1, "
                                 f"got {self.max_new_tokens!r}")
            set_(self, "max_new_tokens", mnt)

    # ---- derived flags the slot buffers key their fast paths on -------
    @property
    def is_greedy(self):
        """True = this request takes the argmax path (no PRNG draw)."""
        return self.temperature == 0.0

    @property
    def uses_penalties(self):
        """True = the [B, V] token-count buffer must be maintained."""
        return (self.repetition_penalty != 1.0
                or self.presence_penalty != 0.0
                or self.frequency_penalty != 0.0)


GREEDY = SamplingParams()
