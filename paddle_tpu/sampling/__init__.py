"""Per-request sampling subsystem (ISSUE 5).

Three layers:

* `params` — `SamplingParams`, the eagerly-validated per-request knob
  bundle (temperature / top-k / top-p / min-p, penalties, seed, stop
  conditions, token budget);
* `processors` — pure, vectorized `([B, V] logits, per-slot arrays) ->
  [B, V]` logit processors plus the counter-based per-request PRNG
  streams, composed inside the jitted decode step so ONE dispatch
  serves a batch mixing greedy and sampled requests;
* `buffers` — `SlotParamStore`, the host-side struct-of-arrays slot
  buffers (scattered on admit/refill) and the [B, V] token-count
  scatter buffer behind the penalty processors.

`nn.decode.PagedDecoder` consumes the buffers; both serving engines
accept `SamplingParams` on `submit`; `GPT2.generate` threads them
through the offline paged path. See docs/SERVING.md ("Per-request
sampling").
"""
from .buffers import GREEDY_MODE, SlotParamStore, greedy_args  # noqa: F401
from .params import GREEDY, SamplingParams  # noqa: F401

__all__ = ["SamplingParams", "GREEDY", "GREEDY_MODE", "SlotParamStore",
           "greedy_args"]
