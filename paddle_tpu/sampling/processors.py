"""Vectorized logit processors + per-request PRNG streams.

Every processor is a pure `([B, V] logits, per-slot param arrays) ->
[B, V]` function, composed INSIDE the jitted decode step: the per-slot
parameters live in struct-of-arrays device buffers (sampling/buffers.py)
indexed by slot row, so one compiled dispatch serves a batch mixing
greedy and arbitrarily-configured sampled requests — the same way block
tables already let one dispatch serve ragged sequence lengths.

Randomness is COUNTER-BASED per request: row r's draw at generation
step s uses `fold_in(PRNGKey(seed_r), s)`. No stream ever advances
because of another slot's activity, so (a) a slot refill cannot perturb
or correlate a co-resident request's tokens, and (b) a fixed seed
reproduces a request's sampled tokens bit-for-bit regardless of batch
composition or slot placement (the batch-invariance bar of ISSUE 5).

The all-greedy fast path (`sampled=False`) compiles to a bare argmax —
zero sort/PRNG cost when no resident request samples. The flags are
STATIC (they select a compiled variant); the parameter VALUES are
traced, so new values never recompile.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.search import topk_impl

_NEG_INF = jnp.float32(-jnp.inf)


def fold_in_keys(seeds, steps):
    """[R] uint32 request seeds + [R] int32 step counters -> [R] PRNG
    keys. Counter-based: key(r, s) depends only on (seed_r, s)."""
    def one(seed, step):
        return jax.random.fold_in(jax.random.PRNGKey(seed), step)

    return jax.vmap(one)(seeds, steps)


def apply_penalties(logits, counts, rep, pres, freq):
    """HF-style repetition penalty + OpenAI-style presence/frequency
    penalties, vectorized over slots. `counts` [R, V] int32 holds each
    slot's token occurrence counts (prompt + generated — the scatter
    buffer sampling/buffers.py maintains); rep/pres/freq are [R].
    Defaults (rep=1, pres=freq=0) are numeric identities, so greedy
    rows sharing the dispatch are unaffected."""
    seen = counts > 0
    rep = rep[:, None]
    out = jnp.where(seen,
                    jnp.where(logits > 0, logits / rep, logits * rep),
                    logits)
    cf = counts.astype(jnp.float32)
    out = out - freq[:, None] * cf - pres[:, None] * seen.astype(
        jnp.float32)
    return out


def filter_logits(scaled, top_k, top_p, min_p):
    """Compose the top-k / top-p / min-p filters from ONE descending
    sort (ops.search.topk_impl with k = V — the shared implementation).

    Per-row semantics (0 / 1.0 / 0.0 disable a filter for that row):
      * top_k keeps the k highest logits;
      * top_p keeps the smallest prefix of the top-k-FILTERED,
        renormalized distribution whose exclusive cumulative probability
        stays under top_p (the best token always survives) — matching
        the dense-path nucleus semantics in models/gpt2.py;
      * min_p drops tokens whose probability in that filtered
        distribution is below min_p * max-probability.
    Ties at a threshold value are kept (standard top-k tie behavior)."""
    R, V = scaled.shape
    sorted_desc, _ = topk_impl(scaled, V)                   # [R, V]
    pos = jnp.arange(V)[None, :]
    k_eff = jnp.where(top_k > 0, jnp.minimum(top_k, V), V)  # [R]
    kth = jnp.take_along_axis(sorted_desc, (k_eff - 1)[:, None], axis=-1)
    keep = scaled >= kth
    # the top-k-filtered distribution IS the sorted array with ranks
    # >= k masked (filtering the k largest preserves descending order)
    sorted_f = jnp.where(pos < k_eff[:, None], sorted_desc, _NEG_INF)
    probs = jax.nn.softmax(sorted_f, axis=-1)
    cum = jnp.cumsum(probs, axis=-1) - probs                # exclusive
    n_keep = jnp.maximum(
        jnp.sum(cum < top_p[:, None], axis=-1, keepdims=True), 1)
    # top_p = 1.0 means OFF exactly (float round-off in cum must not
    # clip genuinely reachable tail tokens)
    n_keep = jnp.where(top_p[:, None] >= 1.0, V, n_keep)
    kth_p = jnp.take_along_axis(sorted_f, n_keep - 1, axis=-1)
    keep &= scaled >= kth_p
    logz = jax.nn.logsumexp(sorted_f, axis=-1, keepdims=True)
    p_tok = jnp.exp(scaled - logz)                          # [R, V]
    keep &= p_tok >= min_p[:, None] * probs[:, :1]
    return jnp.where(keep, scaled, _NEG_INF)


def sample_tokens(logits, sp, *, sampled, penalties):
    """The composed per-slot sampling pipeline (one dispatch, mixed
    configs). logits [R, V] float32; sp is the struct-of-arrays buffer
    dict (sampling/buffers.py). `sampled` / `penalties` are STATIC
    variant flags. Returns [R] int32 tokens.

    Greedy rows take `argmax(logits)` — bitwise identical to the
    pre-sampling-subsystem greedy path when the penalty buffers are
    inactive (and numerically identical when they are, since default
    penalties are identities)."""
    if penalties:
        counts = sp["counts"]
        if "crows" in sp:  # packed prefill: gather compact plan rows
            counts = counts[sp["crows"]]
        logits = apply_penalties(logits, counts, sp["rep"], sp["pres"],
                                 sp["freq"])
    tok_greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if not sampled:
        return tok_greedy
    scaled = logits / jnp.maximum(sp["temperature"], 1e-6)[:, None]
    filt = filter_logits(scaled, sp["top_k"], sp["top_p"], sp["min_p"])
    keys = fold_in_keys(sp["seeds"], sp["steps"])
    gum = jax.vmap(
        lambda k: jax.random.gumbel(k, filt.shape[-1:], jnp.float32))(keys)
    tok_s = jnp.argmax(filt + gum, axis=-1).astype(jnp.int32)
    return jnp.where(sp["sample"], tok_s, tok_greedy)


def update_counts(counts, rows, tok, inc):
    """Scatter-add the freshly emitted tokens into the [S, V] count
    buffer: counts[rows[r], tok[r]] += inc[r]. `inc` masks rows that
    did not really emit (idle decode slots, packing-pad prefill rows,
    plan rows whose prompt is still feeding)."""
    return counts.at[rows, tok].add(inc.astype(jnp.int32))


def check_stops(tok, stop_matrix, active):
    """Device-side stop-token check: [R] tokens against the per-slot
    [R, W] stop-id matrix (-1-padded; generated ids are >= 0, so pad
    never matches). Returns [R] bool."""
    return active & (tok[:, None] == stop_matrix).any(axis=-1)
