"""paddle.onnx namespace (ref: python/paddle/onnx/).

DESIGN DECISION (recorded in SURVEY.md §2 #39): ONNX export is
deliberately dropped. The reference's paddle.onnx.export exists to
escape into third-party inference runtimes; this framework's deployment
artifact is the serialized StableHLO module from jit.save (.pdmodel) —
portable across XLA platforms, versioned, loadable with no Python model
class. `export` raises with that guidance. This is a real package so
both `paddle.onnx.export(...)` and `from paddle.onnx.export import
export` (the reference's module path) resolve before raising.
"""
from .export import export  # noqa: F401

__all__ = ["export"]
