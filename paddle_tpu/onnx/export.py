"""paddle.onnx.export module path (ref: onnx/export.py)."""


def export(*a, **kw):
    raise NotImplementedError(
        "ONNX export is intentionally not supported (SURVEY.md §2 #39):"
        " the deployment artifact is the StableHLO .pdmodel from "
        "paddle_tpu.jit.save (portable across XLA platforms, loadable "
        "without model classes via inference.create_predictor).")


__all__ = ["export"]
