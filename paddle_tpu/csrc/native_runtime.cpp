// Native runtime core: bounded byte-queue for data prefetch + host arena.
//
// Reference roles: paddle/fluid/memory/ (allocators) and the C++ side of the
// reader/DataLoader pipeline (paddle/fluid/operators/reader/ buffered readers,
// blocking_queue.h — behavior studied, code re-designed). TPU-first: the host
// side only needs to (a) keep the input pipeline ahead of the device without
// holding the GIL during copies, and (b) reuse pinned-ish staging buffers so
// numpy batch assembly doesn't thrash the allocator. ctypes releases the GIL
// around every call into this library, so producer/consumer memcpys and
// blocking waits overlap Python-side work.
//
// Build: cc -O3 -shared -fPIC native_runtime.cpp -o libpaddle_tpu_native.so
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Bounded blocking byte queue (multi-producer, multi-consumer)
// ---------------------------------------------------------------------------

struct ByteQueue {
    std::mutex mu;
    std::condition_variable not_full;
    std::condition_variable not_empty;
    std::deque<std::vector<uint8_t>> items;
    size_t capacity_items;
    size_t capacity_bytes;
    size_t bytes = 0;
    bool closed = false;
};

void* ptq_create(size_t capacity_items, size_t capacity_bytes) {
    auto* q = new ByteQueue();
    q->capacity_items = capacity_items ? capacity_items : 1;
    q->capacity_bytes = capacity_bytes ? capacity_bytes : (size_t)1 << 62;
    return q;
}

// Returns 0 on success, -1 if queue closed.
int ptq_push(void* handle, const uint8_t* data, size_t nbytes) {
    auto* q = static_cast<ByteQueue*>(handle);
    std::unique_lock<std::mutex> lk(q->mu);
    q->not_full.wait(lk, [&] {
        return q->closed || (q->items.size() < q->capacity_items &&
                             q->bytes + nbytes <= q->capacity_bytes) ||
               q->items.empty();  // oversized item allowed when queue empty
    });
    if (q->closed) return -1;
    q->items.emplace_back(data, data + nbytes);
    q->bytes += nbytes;
    q->not_empty.notify_one();
    return 0;
}

// Push with a 1-byte frame tag prepended — saves the caller assembling a
// tag+payload copy in Python (the memcpy out of shared memory happens here,
// with the GIL already released by ctypes).
int ptq_push_tagged(void* handle, uint8_t tag, const uint8_t* data,
                    size_t nbytes) {
    auto* q = static_cast<ByteQueue*>(handle);
    std::unique_lock<std::mutex> lk(q->mu);
    q->not_full.wait(lk, [&] {
        return q->closed || (q->items.size() < q->capacity_items &&
                             q->bytes + nbytes + 1 <= q->capacity_bytes) ||
               q->items.empty();
    });
    if (q->closed) return -1;
    std::vector<uint8_t> item(nbytes + 1);
    item[0] = tag;
    std::memcpy(item.data() + 1, data, nbytes);
    q->items.emplace_back(std::move(item));
    q->bytes += nbytes + 1;
    q->not_empty.notify_one();
    return 0;
}

// Returns size of the popped item (>=0), -1 when closed+drained.
// The item is copied into out (caller sizes it via ptq_peek_size).
int64_t ptq_peek_size(void* handle) {
    auto* q = static_cast<ByteQueue*>(handle);
    std::unique_lock<std::mutex> lk(q->mu);
    q->not_empty.wait(lk, [&] { return q->closed || !q->items.empty(); });
    if (q->items.empty()) return -1;
    return (int64_t)q->items.front().size();
}

int64_t ptq_pop(void* handle, uint8_t* out, size_t out_cap) {
    auto* q = static_cast<ByteQueue*>(handle);
    std::unique_lock<std::mutex> lk(q->mu);
    q->not_empty.wait(lk, [&] { return q->closed || !q->items.empty(); });
    if (q->items.empty()) return -1;
    auto& front = q->items.front();
    size_t n = front.size();
    if (n > out_cap) return -2;  // caller must re-size via ptq_peek_size
    std::memcpy(out, front.data(), n);
    q->bytes -= n;
    q->items.pop_front();
    q->not_full.notify_one();
    return (int64_t)n;
}

// Timed variant: waits up to timeout_ms for an item. Returns item size
// (>=0) on success, -1 closed+drained, -2 out too small, -3 timed out.
int64_t ptq_pop_timed(void* handle, uint8_t* out, size_t out_cap,
                      int64_t timeout_ms) {
    auto* q = static_cast<ByteQueue*>(handle);
    std::unique_lock<std::mutex> lk(q->mu);
    bool ok = q->not_empty.wait_for(
        lk, std::chrono::milliseconds(timeout_ms),
        [&] { return q->closed || !q->items.empty(); });
    if (!ok) return -3;
    if (q->items.empty()) return -1;
    auto& front = q->items.front();
    size_t n = front.size();
    if (n > out_cap) return -2;
    std::memcpy(out, front.data(), n);
    q->bytes -= n;
    q->items.pop_front();
    q->not_full.notify_one();
    return (int64_t)n;
}

int64_t ptq_size(void* handle) {
    auto* q = static_cast<ByteQueue*>(handle);
    std::lock_guard<std::mutex> lk(q->mu);
    return (int64_t)q->items.size();
}

void ptq_close(void* handle) {
    auto* q = static_cast<ByteQueue*>(handle);
    std::lock_guard<std::mutex> lk(q->mu);
    q->closed = true;
    q->not_empty.notify_all();
    q->not_full.notify_all();
}

void ptq_destroy(void* handle) {
    delete static_cast<ByteQueue*>(handle);
}

// ---------------------------------------------------------------------------
// Host staging arena: size-bucketed freelist allocator
// (reference role: paddle/fluid/memory BestFit/auto-growth allocators)
// ---------------------------------------------------------------------------

struct Arena {
    std::mutex mu;
    // bucket (log2-rounded size) -> freelist of blocks
    std::unordered_map<size_t, std::vector<void*>> freelists;
    std::unordered_map<void*, size_t> live;  // ptr -> bucket size
    std::atomic<size_t> total_reserved{0};
    size_t limit_bytes;
};

static size_t round_bucket(size_t n) {
    size_t b = 256;
    while (b < n) b <<= 1;
    return b;
}

void* arena_create(size_t limit_bytes) {
    auto* a = new Arena();
    a->limit_bytes = limit_bytes ? limit_bytes : (size_t)4 << 30;
    return a;
}

void* arena_alloc(void* handle, size_t nbytes) {
    auto* a = static_cast<Arena*>(handle);
    size_t bucket = round_bucket(nbytes);
    {
        std::lock_guard<std::mutex> lk(a->mu);
        auto it = a->freelists.find(bucket);
        if (it != a->freelists.end() && !it->second.empty()) {
            void* p = it->second.back();
            it->second.pop_back();
            a->live[p] = bucket;
            return p;
        }
    }
    if (a->total_reserved.load() + bucket > a->limit_bytes) {
        // reclaim: drop all cached blocks
        std::lock_guard<std::mutex> lk(a->mu);
        for (auto& kv : a->freelists) {
            for (void* p : kv.second) {
                ::operator delete(p);
                a->total_reserved -= kv.first;
            }
            kv.second.clear();
        }
    }
    void* p = ::operator new(bucket, std::nothrow);
    if (!p) return nullptr;
    a->total_reserved += bucket;
    std::lock_guard<std::mutex> lk(a->mu);
    a->live[p] = bucket;
    return p;
}

void arena_free(void* handle, void* p) {
    auto* a = static_cast<Arena*>(handle);
    std::lock_guard<std::mutex> lk(a->mu);
    auto it = a->live.find(p);
    if (it == a->live.end()) return;
    a->freelists[it->second].push_back(p);
    a->live.erase(it);
}

int64_t arena_reserved_bytes(void* handle) {
    return (int64_t)static_cast<Arena*>(handle)->total_reserved.load();
}

void arena_destroy(void* handle) {
    auto* a = static_cast<Arena*>(handle);
    for (auto& kv : a->freelists)
        for (void* p : kv.second) ::operator delete(p);
    for (auto& kv : a->live) ::operator delete(kv.first);
    delete a;
}

// ---------------------------------------------------------------------------
// MultiSlot text parser (reference role: the C++ MultiSlotDataFeed's line
// parser — paddle/fluid/framework/data_feed.cc; behavior studied, code
// re-designed). One sample per line, per slot "<n> v1 ... vn". Two-pass:
// ms_scan counts samples + per-slot max width, the caller allocates padded
// [n_samples, width] arrays, ms_fill parses values straight into them.
// The buffer MUST be NUL-terminated (strtoll/strtof read past token ends).
// ---------------------------------------------------------------------------

static inline const char* ms_ws(const char* p, const char* end) {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
    return p;
}

long long ms_scan(const char* buf, long long len, int n_slots,
                  long long* max_widths) {
    const char* p = buf;
    const char* end = buf + len;
    for (int s = 0; s < n_slots; ++s) max_widths[s] = 0;
    long long n_samples = 0;
    while (p < end) {
        p = ms_ws(p, end);
        if (p < end && *p == '\n') { ++p; continue; }
        if (p >= end) break;
        for (int s = 0; s < n_slots; ++s) {
            p = ms_ws(p, end);
            // strtoll would skip '\n' as whitespace and silently merge a
            // short line with the next one — a missing slot must ERROR
            if (p >= end || *p == '\n') return -1;
            char* q;
            long long n = strtoll(p, &q, 10);
            if (q == p || n < 0) return -1;
            p = q;
            if (n > max_widths[s]) max_widths[s] = n;
            for (long long i = 0; i < n; ++i) {
                p = ms_ws(p, end);
                const char* t = p;
                while (p < end && *p != ' ' && *p != '\t' && *p != '\n'
                       && *p != '\r') ++p;
                if (p == t) return -1;  // fewer values than declared
            }
        }
        p = ms_ws(p, end);
        if (p < end) {
            if (*p != '\n') return -1;  // trailing tokens: slot mismatch
            ++p;
        }
        ++n_samples;
    }
    return n_samples;
}

static inline bool ms_tok_end(const char* p, const char* end) {
    // a parsed number must terminate at whitespace/newline/end; stopping
    // mid-token ("2.0" under strtoll) would desync the line framing
    return p >= end || *p == ' ' || *p == '\t' || *p == '\r'
           || *p == '\n' || *p == '\0';
}

int ms_fill(const char* buf, long long len, int n_slots,
            const uint8_t* is_float, const long long* widths, void** outs,
            long long n_samples) {
    const char* p = buf;
    const char* end = buf + len;
    long long row = 0;
    while (p < end) {
        p = ms_ws(p, end);
        if (p < end && *p == '\n') { ++p; continue; }
        if (p >= end) break;
        if (row >= n_samples) return -1;  // MUST match ms_scan's count
        for (int s = 0; s < n_slots; ++s) {
            p = ms_ws(p, end);
            if (p >= end || *p == '\n') return -1;  // short line
            char* q;
            long long n = strtoll(p, &q, 10);
            if (q == p || n < 0 || n > widths[s] || !ms_tok_end(q, end))
                return -1;
            p = q;
            long long base = row * widths[s];
            for (long long i = 0; i < n; ++i) {
                p = ms_ws(p, end);
                if (p >= end || *p == '\n') return -1;  // short line
                char* r;
                if (is_float[s]) {
                    float v = strtof(p, &r);
                    if (r == p || !ms_tok_end(r, end)) return -1;
                    static_cast<float*>(outs[s])[base + i] = v;
                } else {
                    long long v = strtoll(p, &r, 10);
                    if (r == p || !ms_tok_end(r, end)) return -1;
                    static_cast<int64_t*>(outs[s])[base + i] = v;
                }
                p = r;
            }
        }
        p = ms_ws(p, end);
        if (p < end) {
            if (*p != '\n' && *p != '\0') return -1;  // trailing junk
            ++p;
        }
        ++row;
    }
    return 0;
}

}  // extern "C"
