"""Probability distributions (ref: python/paddle/distribution.py —
Distribution/Uniform/Normal/Categorical)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .core import rng as rng_mod
from .core.tensor import Tensor
from .ops._registry import raw


def _as(x):
    return jnp.asarray(raw(x), jnp.float32)


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _as(low)
        self.high = _as(high)

    def sample(self, shape=(), seed=0):
        key = rng_mod.next_key() if not seed else jax.random.key(seed)
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape,
                                                    self.high.shape)
        u = jax.random.uniform(key, shape)
        return Tensor(self.low + u * (self.high - self.low))

    def log_prob(self, value):
        v = _as(value)
        lp = -jnp.log(self.high - self.low)
        inside = (v >= self.low) & (v < self.high)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def probs(self, value):
        return Tensor(jnp.exp(self.log_prob(value)._value))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as(loc)
        self.scale = _as(scale)

    def sample(self, shape=(), seed=0):
        key = rng_mod.next_key() if not seed else jax.random.key(seed)
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        return Tensor(self.loc + self.scale * jax.random.normal(key, shape))

    def log_prob(self, value):
        v = _as(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def probs(self, value):
        return Tensor(jnp.exp(self.log_prob(value)._value))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi)
                      + jnp.log(self.scale)
                      + jnp.zeros_like(self.loc))

    def kl_divergence(self, other):
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


class Categorical(Distribution):
    """Reference semantics (distribution.py Categorical): `logits` are
    NON-NEGATIVE WEIGHTS for sample/probs/log_prob (probs = logits/sum —
    the reference's doc example passes paddle.rand values), while
    entropy/kl_divergence use softmax space (e_logits/z). The asymmetry
    is the reference's own documented behavior, reproduced for migration
    fidelity."""

    def __init__(self, logits, name=None):
        self.logits = _as(logits)

    @property
    def _weight_probs(self):
        w = self.logits
        s = jnp.sum(w, axis=-1, keepdims=True)
        # weights must form a distribution; failing loudly beats the
        # silent NaNs/negative "probabilities" a bare divide produces
        # (validation is skipped under tracing, where values are unknown)
        import jax.core as _jcore
        if not isinstance(w, _jcore.Tracer) and (
                bool(jnp.any(w < 0)) or bool(jnp.any(s <= 0))):
            raise ValueError(
                "Categorical logits are non-negative weights with a "
                "positive sum under the reference semantics "
                "(probs = w / w.sum()); got negative or all-zero weights")
        return w / s

    def sample(self, shape=(), seed=0):
        key = rng_mod.next_key() if not seed else jax.random.key(seed)
        _ = self._weight_probs  # validate weights
        # categorical takes unnormalized log-weights (same pattern as
        # sampling_id below) — no need to normalize first
        return Tensor(jax.random.categorical(
            key, jnp.log(jnp.maximum(self.logits, 1e-30)),
            shape=tuple(shape) + self.logits.shape[:-1]))

    @property
    def _probs(self):
        return jax.nn.softmax(self.logits, axis=-1)

    def probs(self, value=None):
        if value is None:
            return Tensor(self._weight_probs)
        idx = jnp.asarray(raw(value)).astype(jnp.int32)
        p = self._weight_probs
        if p.ndim == 1:  # unbatched distribution: gather categories
            return Tensor(jnp.take(p, idx, axis=-1))
        return Tensor(jnp.take_along_axis(p, idx[..., None],
                                          axis=-1)[..., 0])

    def log_prob(self, value):
        # plain log like the reference: zero-probability categories give
        # -inf, not a clamped finite value
        return Tensor(jnp.log(raw(self.probs(value))))

    def entropy(self):
        p = self._probs
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor(-jnp.sum(p * logp, axis=-1))

    def kl_divergence(self, other):
        p = self._probs
        return Tensor(jnp.sum(
            p * (jax.nn.log_softmax(self.logits, -1)
                 - jax.nn.log_softmax(other.logits, -1)), axis=-1))


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if probs is not None:
            self.probs_ = _as(probs)
            self.logits = jnp.log(self.probs_ / (1 - self.probs_))
        else:
            self.logits = _as(logits)
            self.probs_ = jax.nn.sigmoid(self.logits)

    def sample(self, shape=(), seed=0):
        key = rng_mod.next_key() if not seed else jax.random.key(seed)
        return Tensor(jax.random.bernoulli(
            key, self.probs_, tuple(shape) + self.probs_.shape
        ).astype(jnp.float32))

    def log_prob(self, value):
        v = _as(value)
        return Tensor(v * jax.nn.log_sigmoid(self.logits)
                      + (1 - v) * jax.nn.log_sigmoid(-self.logits))

    def entropy(self):
        p = self.probs_
        return Tensor(-(p * jnp.log(jnp.maximum(p, 1e-30))
                        + (1 - p) * jnp.log(jnp.maximum(1 - p, 1e-30))))


def kl_divergence(p, q):
    return p.kl_divergence(q)


class MultivariateNormalDiag(Distribution):
    """Diagonal-covariance multivariate normal (ref:
    python/paddle/distribution.py MultivariateNormalDiag)."""

    def __init__(self, loc, scale):
        self.loc = loc if isinstance(loc, Tensor) else Tensor(jnp.asarray(loc))
        self.scale = scale if isinstance(scale, Tensor) \
            else Tensor(jnp.asarray(scale))

    def sample(self, shape=()):
        from .core import rng
        lv = self.loc._value
        d = self._diag()
        eps = jax.random.normal(rng.next_key(),
                                tuple(shape) + lv.shape, lv.dtype)
        return Tensor(lv + d * eps)

    def _diag(self):
        """Per-dimension stddevs. `scale` is a diagonal vector (possibly
        batched, same shape as loc); a full matrix form (loc.ndim+1 dims with
        square trailing axes) has its diagonal extracted."""
        sv = self.scale._value
        lv = self.loc._value
        if sv.ndim == lv.ndim + 1 and sv.shape[-1] == sv.shape[-2]:
            return jnp.diagonal(sv, axis1=-2, axis2=-1)
        return sv

    def log_prob(self, value):
        v = value._value if isinstance(value, Tensor) else jnp.asarray(value)
        d = self._diag()
        z = (v - self.loc._value) / d
        return Tensor(-0.5 * jnp.sum(z * z, -1)
                      - jnp.sum(jnp.log(d), -1)
                      - 0.5 * d.shape[-1] * jnp.log(2 * jnp.pi))

    def entropy(self):
        d = self._diag()
        k = d.shape[-1]
        return Tensor(0.5 * k * (1 + jnp.log(2 * jnp.pi))
                      + jnp.sum(jnp.log(d), -1))

    def kl_divergence(self, other):
        d0, d1 = self._diag(), other._diag()
        m0, m1 = self.loc._value, other.loc._value
        return Tensor(jnp.sum(jnp.log(d1) - jnp.log(d0)
                              + (d0 ** 2 + (m0 - m1) ** 2) / (2 * d1 ** 2)
                              - 0.5, -1))


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64"):  # noqa: A002
    """Sample a column index per row from a probability matrix (ref:
    sampling_id_op.cc)."""
    from .core import dtype as dtype_mod
    from .core import rng
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    key = rng.next_key() if seed == 0 else jax.random.key(seed)
    idx = jax.random.categorical(key, jnp.log(jnp.maximum(xv, 1e-30)), -1)
    return Tensor(idx.astype(dtype_mod.convert_dtype(dtype)))
