"""paddle.optimizer.adagrad module path (ref: optimizer/adagrad.py)."""
from .optimizer import Adagrad  # noqa: F401

__all__ = ["Adagrad"]
