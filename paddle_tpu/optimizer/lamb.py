"""paddle.optimizer.lamb module path (ref: optimizer/lamb.py)."""
from .optimizer import Lamb  # noqa: F401

__all__ = ["Lamb"]
