"""paddle.optimizer namespace (ref: python/paddle/optimizer/)."""
from __future__ import annotations

from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    SGD, Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, Lars, Momentum,
    Optimizer, RMSProp,
)
