"""paddle.optimizer.adadelta module path (ref: optimizer/adadelta.py)."""
from .optimizer import Adadelta  # noqa: F401

__all__ = ["Adadelta"]
