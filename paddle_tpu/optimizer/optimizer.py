"""Optimizers.

Reference: python/paddle/optimizer/ (Adam/AdamW/SGD/Momentum/...) and the C++
kernels in paddle/fluid/operators/optimizers/. TPU-first split: each optimizer
defines a pure functional rule (`init_slots` / `rule`) over raw jax arrays;
the stateful paddle API (`step`, `minimize`, `clear_grad`) drives it in eager
mode, and jitted/pjit train steps call `functional_update` on whole pytrees so
the update fuses into the compiled step (and shards with the params).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        self._lr = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._param_groups = None
        self._group_of = {}  # id(param) -> group dict (per-group lr/wd)
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            self._param_groups = self._parameter_list
            flat = []
            for g in self._param_groups:
                for p in g["params"]:
                    flat.append(p)
                    self._group_of[id(p)] = g
            self._parameter_list = flat
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._slots = {}  # id(param) -> dict of slot arrays
        self._step_count = 0
        self._name = name

    # ---- functional core (override in subclasses) ------------------------
    def init_slots(self, p):
        """Return dict of slot arrays for one param value `p` (jax array)."""
        return {}

    def rule(self, p, g, slots, lr, t):
        """Pure update: returns (new_p, new_slots). t is the 1-based step."""
        raise NotImplementedError

    # ---- lr --------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._lr, LRScheduler):
            return self._lr()
        return self._lr

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("optimizer lr is a scheduler; call sched.step()")
        self._lr = value

    @property
    def _learning_rate(self):
        return self._lr

    # ---- weight decay / clip --------------------------------------------
    def _decay_coeff(self):
        wd = self._weight_decay
        if wd is None:
            return 0.0
        if hasattr(wd, "coeff"):  # L2Decay / L1Decay instance
            return float(wd.coeff) if wd.__class__.__name__ == "L2Decay" else 0.0
        return float(wd)

    def _decoupled(self):
        return False  # AdamW overrides

    def _apply_regularization(self, p, g):
        """Couple L2 decay into grads (reference: regularization appended as
        grad-op). L1Decay adds sign(p)*coeff."""
        wd = self._weight_decay
        reg = getattr(p, "regularizer", None) or wd
        if reg is None or self._decoupled():
            return g
        if hasattr(reg, "coeff"):
            if reg.__class__.__name__ == "L1Decay":
                return g + reg.coeff * jnp.sign(p._value)
            return g + reg.coeff * p._value
        return g + float(reg) * p._value

    # ---- stateful API ----------------------------------------------------
    @jax.named_scope("optimizer_step")
    def step(self):
        params = self._parameter_list
        if params is None:
            raise ValueError("optimizer constructed without parameters")
        self._step_count += 1
        lr = self.get_lr()
        grads = []
        live = []
        for p in params:
            if p is None or p.grad is None or not p.trainable:
                continue
            g = p.grad._value.astype(p._value.dtype)
            g = self._apply_regularization(p, g)
            live.append(p)
            grads.append(g)
        if self._grad_clip is not None:
            grads = self._grad_clip._clip_raw(live, grads)
        for p, g in zip(live, grads):
            slots = self._slots.get(id(p))
            if slots is None:
                slots = self.init_slots(p._value)
                self._slots[id(p)] = slots
            group = self._group_of.get(id(p))
            p_lr = lr * p.optimize_attr.get("learning_rate", 1.0) \
                if isinstance(p, Parameter) and hasattr(p, "optimize_attr") else lr
            if group is not None and "learning_rate" in group:
                p_lr = lr * group["learning_rate"]
            wd = self._decay_coeff()
            if group is not None and "weight_decay" in group:
                gw = group["weight_decay"]
                wd = float(gw.coeff) if hasattr(gw, "coeff") else float(gw)
            new_p, new_slots = self.rule(p._value, g, slots, p_lr,
                                         self._step_count)
            if self._decoupled() and wd > 0.0 and \
                    getattr(p, "no_weight_decay", False) is False:
                new_p = new_p - p_lr * wd * p._value
            p._value = new_p
            self._slots[id(p)] = new_slots

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None, parameter_list=None):
        # `parameter_list` is the fluid-era spelling of `parameters`
        parameters = parameters if parameters is not None else parameter_list
        from ..core import mode
        if mode.in_static_mode():
            from ..static import program as static_program
            return static_program._minimize(self, loss, parameters)
        loss.backward()
        self.step()
        return None, None

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list or []:
            if p is not None:
                p.grad = None

    clear_gradients = clear_grad

    # ---- functional bridge (jit/pjit path) -------------------------------
    def functional_init(self, params_tree):
        """params_tree: pytree of jax arrays -> opt state pytree."""
        slots = jax.tree_util.tree_map(lambda p: self.init_slots(p), params_tree,
                                       is_leaf=lambda x: isinstance(x, jnp.ndarray) or hasattr(x, "shape"))
        return {"slots": slots, "t": jnp.zeros((), jnp.int32)}

    def functional_update(self, params_tree, grads_tree, opt_state, lr=None,
                          wd_mask=None):
        """Pure whole-tree update, safe under jit/pjit. wd_mask: pytree of
        bools controlling decoupled weight decay per leaf."""
        t = opt_state["t"] + 1
        if lr is None:
            lr = self.get_lr() if not isinstance(self._lr, LRScheduler) \
                else self._lr.lr_at(t)
        coeff = self._decay_coeff()
        decoupled = self._decoupled()

        leaves_p, treedef = jax.tree_util.tree_flatten(params_tree)
        leaves_g = treedef.flatten_up_to(grads_tree)
        leaves_s = treedef.flatten_up_to(opt_state["slots"])
        leaves_m = treedef.flatten_up_to(wd_mask) if wd_mask is not None \
            else [True] * len(leaves_p)

        new_p, new_s = [], []
        for p, g, s, m in zip(leaves_p, leaves_g, leaves_s, leaves_m):
            if not decoupled and coeff > 0.0 and m:
                g = g + coeff * p
            np_, ns_ = self.rule(p, g.astype(p.dtype), s, lr, t)
            if decoupled and coeff > 0.0 and m:
                np_ = np_ - lr * coeff * p
            new_p.append(np_)
            new_s.append(ns_)
        return (jax.tree_util.tree_unflatten(treedef, new_p),
                {"slots": jax.tree_util.tree_unflatten(treedef, new_s), "t": t})

    # ---- state dict ------------------------------------------------------
    def state_dict(self):
        out = {"@step": self._step_count}
        names = self._param_names()
        for p, name in names.items():
            for k, v in self._slots.get(p, {}).items():
                out[f"{name}.{k}"] = Tensor(v)
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        return out

    def set_state_dict(self, state):
        # resolve + validate EVERYTHING first; mutate only at the end, so
        # a rejected checkpoint leaves the optimizer untouched
        names = {name: pid for pid, name in self._param_names().items()}
        # saved per-param key order == parameter_list order at save time
        saved_pnames = []
        saved_slots = {}
        for key, v in state.items():
            if key in ("@step", "LR_Scheduler"):
                continue
            pname, slot = key.rsplit(".", 1)
            if pname not in saved_pnames:
                saved_pnames.append(pname)
            arr = v._value if isinstance(v, Tensor) else jnp.asarray(v)
            saved_slots.setdefault(pname, {})[slot] = arr
        cur_params = [p for p in (self._parameter_list or [])
                      if p is not None]
        unmatched = [pn for pn in saved_pnames if pn not in names]
        if not unmatched:
            mapping = {pn: names[pn] for pn in saved_pnames}
        else:
            # Same-architecture resume with regenerated global names (a
            # second model built in the process shifts the unique
            # counter): align saved groups to parameters by ORDER + SHAPE
            # — all-positional once engaged (a coincidental stale name
            # match must not override position), and shape-skipping
            # tolerates frozen params that never grew slots.
            import warnings
            # positional matching is only sound when the saved run and
            # this run have compatible parameter rosters. Slots are
            # created lazily (only for params that received grads), so
            # saved groups <= trainable params is legitimate — but MORE
            # saved groups than trainable params means the architectures
            # differ and every later group would land on a wrong,
            # possibly same-shape, parameter undetected.
            slot_bearing = [p for p in cur_params if p.trainable]
            if len(saved_pnames) > len(slot_bearing):
                raise ValueError(
                    f"optimizer state has {len(saved_pnames)} parameter "
                    f"groups but the model has only {len(slot_bearing)} "
                    "trainable parameters — positional resume would "
                    "misalign moments; architectures differ")
            mapping = {}
            ci = 0
            pairing = []

            def _shape_of(slots):
                for a in slots.values():
                    if hasattr(a, "shape") and a.shape:
                        return tuple(a.shape)
                return None

            names_by_id = self._param_names()
            for pn in saved_pnames:
                want = _shape_of(saved_slots[pn])
                while ci < len(cur_params) and want is not None and \
                        tuple(cur_params[ci].shape) != want:
                    ci += 1  # frozen/slotless param: skip
                if ci >= len(cur_params):
                    raise ValueError(
                        f"optimizer state group '{pn}' (shape {want}) has "
                        "no positional parameter match — wrong "
                        "architecture?")
                mapping[pn] = id(cur_params[ci])
                pairing.append((pn, names_by_id.get(id(cur_params[ci]))))
                ci += 1
            shown = pairing[:5]
            warnings.warn(
                f"optimizer state names {unmatched[:3]}... not found; "
                "matched saved slots to parameters by order and shape "
                f"(same-architecture resume): {shown}"
                + (f" ... ({len(pairing)} pairs total)"
                   if len(pairing) > len(shown) else ""), stacklevel=2)
        # shape guard for the name-matched path too
        shapes = {id(p): tuple(p.shape) for p in cur_params}
        by_param = {}
        for pn, slots in saved_slots.items():
            pid = mapping.get(pn)
            if pid is None:
                continue
            for slot, arr in slots.items():
                if hasattr(arr, "shape") and arr.shape and \
                        tuple(arr.shape) != shapes.get(pid):
                    raise ValueError(
                        f"optimizer slot '{pn}.{slot}' shape "
                        f"{tuple(arr.shape)} does not match parameter "
                        f"shape {shapes.get(pid)}")
            by_param[pid] = dict(slots)
        # ---- commit ----
        self._step_count = int(state.get("@step", 0))
        if isinstance(self._lr, LRScheduler) and "LR_Scheduler" in state:
            self._lr.set_state_dict(state["LR_Scheduler"])
        self._slots.update(by_param)

    set_dict = set_state_dict

    def _param_names(self):
        out = {}
        for i, p in enumerate(self._parameter_list or []):
            if p is not None:
                out[id(p)] = p.name or f"param_{i}"
        return out


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def rule(self, p, g, slots, lr, t):
        return p - lr * g, slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def init_slots(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def rule(self, p, g, slots, lr, t):
        v = self._momentum * slots["velocity"] + g
        if self._nesterov:
            update = g + self._momentum * v
        else:
            update = v
        return p - lr * update, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, name=None,
                 multi_precision=False, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def init_slots(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p)}

    def rule(self, p, g, slots, lr, t):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        t = jnp.asarray(t, jnp.float32) if not isinstance(t, int) else t
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        new_p = p - lr * mhat / (jnp.sqrt(vhat) + self._eps)
        return new_p, {"moment1": m, "moment2": v}


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, name=None, multi_precision=False, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, name)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decoupled(self):
        return True

    def step(self):
        # mark params excluded from decay by name predicate
        if self._apply_decay_param_fun is not None:
            for p in self._parameter_list or []:
                if p is not None:
                    p.no_weight_decay = not self._apply_decay_param_fun(p.name)
        super().step()


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def init_slots(self, p):
        return {"moment": jnp.zeros_like(p), "inf_norm": jnp.zeros_like(p)}

    def rule(self, p, g, slots, lr, t):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * slots["inf_norm"], jnp.abs(g))
        t = jnp.asarray(t, jnp.float32) if not isinstance(t, int) else t
        new_p = p - lr / (1 - b1 ** t) * m / (u + self._eps)
        return new_p, {"moment": m, "inf_norm": u}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def init_slots(self, p):
        return {"moment": jnp.full_like(p, self._init_acc)}

    def rule(self, p, g, slots, lr, t):
        acc = slots["moment"] + jnp.square(g)
        return p - lr * g / (jnp.sqrt(acc) + self._eps), {"moment": acc}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps, self._rho = epsilon, rho

    def init_slots(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p),
                "avg_squared_update": jnp.zeros_like(p)}

    def rule(self, p, g, slots, lr, t):
        rho, eps = self._rho, self._eps
        asg = rho * slots["avg_squared_grad"] + (1 - rho) * jnp.square(g)
        update = -jnp.sqrt((slots["avg_squared_update"] + eps) / (asg + eps)) * g
        asu = rho * slots["avg_squared_update"] + (1 - rho) * jnp.square(update)
        return p + lr * update, {"avg_squared_grad": asg,
                                 "avg_squared_update": asu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def init_slots(self, p):
        s = {"mean_square": jnp.zeros_like(p), "momentum": jnp.zeros_like(p)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(p)
        return s

    def rule(self, p, g, slots, lr, t):
        rho = self._rho
        ms = rho * slots["mean_square"] + (1 - rho) * jnp.square(g)
        new = {"mean_square": ms}
        if self._centered:
            mg = rho * slots["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + self._eps)
            new["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * slots["momentum"] + lr * g / denom
        new["momentum"] = mom
        return p - mom, new


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_slots(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p)}

    def rule(self, p, g, slots, lr, t):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        t = jnp.asarray(t, jnp.float32) if not isinstance(t, int) else t
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._eps) + self._wd * p
        w_norm = jnp.linalg.norm(p)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, {"moment1": m, "moment2": v}


class Lars(Optimizer):
    """LARS (ref: fleet meta_optimizers/lars_optimizer.py wraps Momentum)."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005, parameters=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = momentum
        self._coeff = lars_coeff
        self._wd = lars_weight_decay

    def init_slots(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def rule(self, p, g, slots, lr, t):
        w_norm = jnp.linalg.norm(p)
        g_norm = jnp.linalg.norm(g)
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._coeff * w_norm / (g_norm + self._wd * w_norm + 1e-12), 1.0)
        v = self._momentum * slots["velocity"] + lr * local_lr * (
            g + self._wd * p)
        return p - v, {"velocity": v}
