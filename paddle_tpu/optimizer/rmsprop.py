"""paddle.optimizer.rmsprop module path (ref: optimizer/rmsprop.py)."""
from .optimizer import RMSProp  # noqa: F401

__all__ = ["RMSProp"]
