"""paddle.optimizer.adamw module path (ref: optimizer/adamw.py)."""
from .optimizer import AdamW  # noqa: F401

__all__ = ["AdamW"]
