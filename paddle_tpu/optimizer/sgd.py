"""paddle.optimizer.sgd module path (ref: optimizer/sgd.py)."""
from .optimizer import SGD  # noqa: F401

__all__ = ["SGD"]
