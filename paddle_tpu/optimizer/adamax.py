"""paddle.optimizer.adamax module path (ref: optimizer/adamax.py)."""
from .optimizer import Adamax  # noqa: F401

__all__ = ["Adamax"]
