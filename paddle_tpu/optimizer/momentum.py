"""paddle.optimizer.momentum module path (ref: optimizer/momentum.py)."""
from .optimizer import Momentum  # noqa: F401

__all__ = ["Momentum"]
