"""Learning-rate schedulers.

Reference: python/paddle/optimizer/lr.py. Stateful paddle-style API
(`sched.step()`, `sched.get_lr()`); each also exposes `lr_at(step)` — a pure
function of the step count — so jitted train steps can fold the schedule into
the compiled computation (lax-friendly, no host sync).
"""
from __future__ import annotations

import math

import jax.numpy as jnp


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = learning_rate
        self.last_epoch = last_epoch
        self.last_lr = None
        self.verbose = verbose
        self.step()

    def get_lr(self):
        raise NotImplementedError

    def lr_at(self, step):
        """Pure schedule value at integer/traced `step` (jit-friendly)."""
        saved = self.last_epoch
        try:
            self.last_epoch = step
            return self.get_lr()
        finally:
            self.last_epoch = saved

    def step(self, epoch=None):
        self.last_epoch = self.last_epoch + 1 if epoch is None else epoch
        self.last_lr = self.get_lr()

    def __call__(self):
        return self.last_lr

    def state_dict(self):
        return {k: v for k, v in self.__dict__.items()
                if isinstance(v, (int, float, bool, str, list))}

    def set_state_dict(self, state):
        self.__dict__.update(state)

    set_dict = set_state_dict


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1,
                 verbose=False):
        self.d_model, self.warmup_steps = d_model, warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 1) if isinstance(self.last_epoch, int) \
            else jnp.maximum(self.last_epoch, 1)
        if isinstance(step, int):
            return self.base_lr * (self.d_model ** -0.5) * min(
                step ** -0.5, step * self.warmup_steps ** -1.5)
        return self.base_lr * (self.d_model ** -0.5) * jnp.minimum(
            step ** -0.5, step * self.warmup_steps ** -1.5)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries, self.values = list(boundaries), list(values)
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        e = self.last_epoch
        if not isinstance(e, int):
            idx = jnp.searchsorted(jnp.asarray(self.boundaries), e, side="right")
            return jnp.asarray(self.values)[idx]
        for b, v in zip(self.boundaries, self.values):
            if e < b:
                return v
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        e = self.last_epoch
        return self.base_lr * (math.exp(-self.gamma * e) if isinstance(e, int)
                               else jnp.exp(-self.gamma * e))


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** self.last_epoch


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps, self.end_lr = decay_steps, end_lr
        self.power, self.cycle = power, cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        e = self.last_epoch
        if self.cycle:
            if isinstance(e, int):
                div = max(1.0, math.ceil(e / self.decay_steps))
            else:
                div = jnp.maximum(1.0, jnp.ceil(e / self.decay_steps))
            steps = self.decay_steps * div
            frac = e / steps
        else:
            if isinstance(e, int):
                frac = min(e, self.decay_steps) / self.decay_steps
            else:
                frac = jnp.minimum(e, self.decay_steps) / self.decay_steps
        return (self.base_lr - self.end_lr) * (1 - frac) ** self.power + self.end_lr


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1, verbose=False):
        self.lr_sched = learning_rate if isinstance(learning_rate, LRScheduler) else None
        self.peak = learning_rate if not self.lr_sched else None
        self.warmup_steps = warmup_steps
        self.start_lr, self.end_lr = start_lr, end_lr
        super().__init__(end_lr, last_epoch, verbose)

    def get_lr(self):
        e = self.last_epoch
        warm = self.start_lr + (self.end_lr - self.start_lr) * (
            e / max(self.warmup_steps, 1))
        if self.lr_sched is not None:
            after = self.lr_sched.lr_at(e - self.warmup_steps) \
                if isinstance(e, int) and e >= self.warmup_steps else \
                (self.lr_sched.lr_at(jnp.maximum(e - self.warmup_steps, 0))
                 if not isinstance(e, int) else warm)
        else:
            after = self.peak
        if isinstance(e, int):
            return warm if e < self.warmup_steps else after
        return jnp.where(e < self.warmup_steps, warm, after)


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.step_size, self.gamma = step_size, gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** (self.last_epoch // self.step_size)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.milestones, self.gamma = list(milestones), gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        e = self.last_epoch
        if isinstance(e, int):
            n = sum(1 for m in self.milestones if e >= m)
        else:
            n = jnp.sum(e >= jnp.asarray(self.milestones))
        return self.base_lr * self.gamma ** n


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)


class MultiplicativeDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        self._acc = 1.0
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch > 0:
            self._acc *= self.lr_lambda(self.last_epoch)
        return self.base_lr * self._acc


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1,
                 verbose=False):
        self.T_max, self.eta_min = T_max, eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        e = self.last_epoch
        cos = (math.cos(math.pi * e / self.T_max) if isinstance(e, int)
               else jnp.cos(jnp.pi * e / self.T_max))
        return self.eta_min + (self.base_lr - self.eta_min) * (1 + cos) / 2


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3,
                 anneal_strategy="cos", three_phase=False, last_epoch=-1,
                 verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.up_steps = int(phase_pct * total_steps)
        super().__init__(self.initial_lr, last_epoch, verbose)

    def get_lr(self):
        e = self.last_epoch
        up, down = self.up_steps, self.total_steps - self.up_steps

        def interp(lo, hi, frac):
            c = (math.cos(math.pi * frac) if isinstance(frac, float)
                 else jnp.cos(jnp.pi * frac)) * 0.5 + 0.5
            return hi + (lo - hi) * (1 - c) if False else lo + (hi - lo) * (1 - c)

        if isinstance(e, int):
            if e < up:
                return interp(self.initial_lr, self.max_lr, e / max(up, 1))
            frac = min((e - up) / max(down, 1), 1.0)
            return interp(self.max_lr, self.end_lr, frac)
        frac_up = e / max(up, 1)
        frac_dn = jnp.clip((e - up) / max(down, 1), 0.0, 1.0)
        return jnp.where(e < up, interp(self.initial_lr, self.max_lr, frac_up),
                         interp(self.max_lr, self.end_lr, frac_dn))


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate, max_learning_rate, step_size_up,
                 step_size_down=None, mode="triangular", exp_gamma=1.0,
                 scale_fn=None, scale_mode="cycle", last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.up = step_size_up
        self.down = step_size_down or step_size_up
        self.mode, self.exp_gamma = mode, exp_gamma
        super().__init__(base_learning_rate, last_epoch, verbose)

    def get_lr(self):
        e = self.last_epoch
        total = self.up + self.down
        cycle = e // total
        pos = e - cycle * total
        if isinstance(e, int):
            frac = pos / self.up if pos < self.up else 1 - (pos - self.up) / self.down
        else:
            frac = jnp.where(pos < self.up, pos / self.up,
                             1 - (pos - self.up) / self.down)
        scale = {"triangular": 1.0,
                 "triangular2": 0.5 ** cycle if isinstance(cycle, int) else 0.5 ** cycle,
                 "exp_range": self.exp_gamma ** e}.get(self.mode, 1.0)
        return self.base_lr + (self.max_lr - self.base_lr) * frac * scale


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        self.mode, self.factor, self.patience = mode, factor, patience
        self.threshold, self.threshold_mode = threshold, threshold_mode
        self.cooldown, self.min_lr = cooldown, min_lr
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0
        self.base_lr = learning_rate
        self.last_lr = learning_rate
        self.last_epoch = 0

    def get_lr(self):
        return self.last_lr

    def step(self, metrics=None, epoch=None):
        self.last_epoch += 1
        if metrics is None:
            return
        m = float(metrics.item() if hasattr(metrics, "item") else metrics)
        better = (self.best is None
                  or (self.mode == "min" and m < self.best - (
                      abs(self.best) * self.threshold
                      if self.threshold_mode == "rel" else self.threshold))
                  or (self.mode == "max" and m > self.best + (
                      abs(self.best) * self.threshold
                      if self.threshold_mode == "rel" else self.threshold)))
        if better:
            self.best = m
            self.num_bad = 0
        else:
            self.num_bad += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad = 0
        if self.num_bad > self.patience:
            self.last_lr = max(self.last_lr * self.factor, self.min_lr)
            self.cooldown_counter = self.cooldown
            self.num_bad = 0


class CosineAnnealingWarmRestarts(LRScheduler):
    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0, last_epoch=-1,
                 verbose=False):
        self.T_0, self.T_mult, self.eta_min = T_0, T_mult, eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        e = max(self.last_epoch, 0)
        t_i, t_cur = self.T_0, e
        while t_cur >= t_i:
            t_cur -= t_i
            t_i *= self.T_mult
        return self.eta_min + (self.base_lr - self.eta_min) * (
            1 + math.cos(math.pi * t_cur / t_i)) / 2
