"""paddle.optimizer.adam module path (ref: optimizer/adam.py)."""
from .optimizer import Adam  # noqa: F401

__all__ = ["Adam"]
