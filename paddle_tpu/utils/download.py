"""paddle.utils.download (ref: python/paddle/utils/download.py).

The reference downloads weights to ~/.cache/paddle/hapi/weights; this
environment has no network egress, so the module resolves from the LOCAL
weights directory the vision zoo documents ($PADDLE_TPU_PRETRAINED_DIR,
falling back to ~/.cache/paddle_tpu/hub) and raises with staging guidance
when a file is absent — never silently returning garbage.
"""
from __future__ import annotations

import os

__all__ = ["get_weights_path_from_url"]

WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle_tpu/hub")


def _weights_dir():
    return os.environ.get("PADDLE_TPU_PRETRAINED_DIR", WEIGHTS_HOME)


def get_weights_path_from_url(url, md5sum=None):
    """Resolve the LOCAL path a reference-era weights URL maps to (the
    file's basename inside the weights dir); raises FileNotFoundError
    with staging instructions when absent."""
    fname = os.path.basename(str(url).split("?")[0])
    path = os.path.join(_weights_dir(), fname)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"weights '{fname}' not found at {path}. This environment "
            "cannot download; place the file there (or set "
            "$PADDLE_TPU_PRETRAINED_DIR to the directory holding it).")
    if md5sum is not None:
        import hashlib
        with open(path, "rb") as f:
            got = hashlib.md5(f.read()).hexdigest()
        if got != md5sum:
            raise ValueError(
                f"md5 mismatch for {path}: expected {md5sum}, got {got}")
    return path


def get_path_from_url(url, root_dir=None, md5sum=None, check_exist=True):
    return get_weights_path_from_url(url, md5sum)
