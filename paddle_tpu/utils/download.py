"""paddle.utils.download (ref: python/paddle/utils/download.py).

The reference downloads weights to ~/.cache/paddle/hapi/weights; this
environment has no network egress, so the module resolves from the LOCAL
weights directory the vision zoo documents ($PADDLE_TPU_PRETRAINED_DIR,
falling back to ~/.cache/paddle_tpu/hub) and raises with staging guidance
when a file is absent — never silently returning garbage.
"""
from __future__ import annotations

import os

# ONE source of truth for the staging dir: the vision zoo's pretrained
# loader defines it (vision/models/_weights.py)
from ..vision.models._weights import _DEFAULT_DIR as WEIGHTS_HOME
from ..vision.models._weights import PRETRAINED_DIR_ENV

__all__ = ["get_weights_path_from_url"]


def _weights_dir():
    return os.environ.get(PRETRAINED_DIR_ENV, WEIGHTS_HOME)


def _resolve(url, md5sum, root_dir=None):
    fname = os.path.basename(str(url).split("?")[0])
    path = os.path.join(root_dir or _weights_dir(), fname)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"weights '{fname}' not found at {path}. This environment "
            "cannot download; place the file there (or set "
            f"${PRETRAINED_DIR_ENV} to the directory holding it).")
    if md5sum is not None:
        from ..dataset.common import md5file  # chunked: no whole-file RAM
        got = md5file(path)
        if got != md5sum:
            raise ValueError(
                f"md5 mismatch for {path}: expected {md5sum}, got {got}")
    return path


def get_weights_path_from_url(url, md5sum=None):
    """Resolve the LOCAL path a reference-era weights URL maps to (the
    file's basename inside the weights dir); raises FileNotFoundError
    with staging instructions when absent."""
    return _resolve(url, md5sum)


def get_path_from_url(url, root_dir=None, md5sum=None, check_exist=True):
    """ref signature: root_dir overrides the default staging dir."""
    return _resolve(url, md5sum, root_dir=root_dir)
