"""Training watchdog — hang/failure detection for long-running loops
(SURVEY §5 aux subsystems: failure detection; ref lineage: fleet's
elastic/heartbeat monitoring, rebuilt host-side and device-agnostic).

A TPU training job can wedge without crashing: a stuck collective, a
dead data-loader worker, an unresponsive device tunnel. The watchdog is
a daemon thread armed with a step heartbeat; if no `beat()` arrives
within `timeout` seconds it (1) dumps every Python thread's stack to
stderr (or `dump_path`), (2) invokes `on_timeout` (e.g. an emergency
checkpoint via framework.io.async_save), and (3) applies `action`:
"warn" (keep waiting — it re-arms), "interrupt" (raise
KeyboardInterrupt in the main thread), or "abort" (os._exit for an
external supervisor to restart).

Action choice matters: "interrupt" is delivered when the main thread
next runs Python bytecode — it unwedges Python-level stalls (slow data
source, livelocked loop) and lets finally/except cleanup run, but it
CANNOT break a main thread blocked inside a C call (a stuck collective
or device transfer); for those, use action="abort" with a supervisor,
which always recovers. The stack dump and emergency callback run either
way, so the hang is diagnosable and the state is saved even when the
process must be killed.

    with Watchdog(timeout=300, on_timeout=save_emergency) as wd:
        for batch in loader:
            loss = train_step(batch)
            wd.beat(loss=float(loss))
"""
from __future__ import annotations

import os
import sys
import threading
import time
import traceback

from ..observability import log as _log
from ..observability import metrics as _metrics

_logger = _log.get_logger(__name__)
# heartbeat age is PULLED at metrics-export time (gauge_fn) so the
# beat() hot path stays untouched; with several live watchdogs the
# gauge follows the most recently started one
_m_fired = _metrics.counter(
    "watchdog_fired_total", "watchdog timeouts observed")
_m_beats = _metrics.counter(
    "watchdog_beats_total", "heartbeats received")


class Watchdog:
    def __init__(self, timeout, on_timeout=None, action="interrupt",
                 dump_path=None, poll_interval=None):
        if action not in ("warn", "interrupt", "abort"):
            raise ValueError(
                f"action must be warn|interrupt|abort, got {action!r}")
        self.timeout = float(timeout)
        self.on_timeout = on_timeout
        self.action = action
        self.dump_path = dump_path
        self.poll = poll_interval or min(1.0, self.timeout / 4)
        self._last = time.monotonic()
        self._beats = 0
        self._fired = 0
        self._stop = threading.Event()
        self._thread = None
        self._info = {}

    # ---- heartbeat -------------------------------------------------------
    def beat(self, **info):
        """Call once per training step; `info` (loss, step, ...) is shown
        in the timeout report."""
        self._last = time.monotonic()
        self._beats += 1
        _m_beats.inc()
        if info:
            self._info = info

    # ---- lifecycle -------------------------------------------------------
    def start(self):
        if self._thread is not None and not self._thread.is_alive():
            self._thread = None  # reap a fired/finished thread: re-arm
        if self._thread is not None:
            return self
        # PER-START stop event: a previous thread still draining its
        # on_timeout callback holds the OLD event, so a stop()+start()
        # cycle can never let it resurrect and fire against the new run
        self._stop = threading.Event()
        self._last = time.monotonic()
        _metrics.REGISTRY.gauge_fn(
            "watchdog_heartbeat_age_seconds",
            "seconds since the last beat() of the active watchdog",
            lambda: time.monotonic() - self._last)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="paddle-tpu-watchdog",
                                        args=(self._stop,))
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None and t is not threading.current_thread():
            t.join(timeout=self.poll * 4)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    @property
    def fired(self):
        return self._fired

    # ---- internals -------------------------------------------------------
    def _run(self, stop):
        # `stop` is THIS thread's own event (see start()) — checking the
        # instance attribute would race with a stop()+start() re-arm
        while not stop.wait(self.poll):
            idle = time.monotonic() - self._last
            if idle < self.timeout:
                continue
            self._fired += 1
            _m_fired.inc()
            self._report(idle)
            cb = self.on_timeout
            if cb is not None:
                try:
                    cb(self)
                except Exception:  # noqa: BLE001 — report, keep watching
                    traceback.print_exc(file=sys.stderr)
            # the callback takes time; if the loop finished cleanly and
            # stop() ran meanwhile, do NOT kill/interrupt a healthy exit
            if stop.is_set():
                return
            if self.action == "interrupt":
                import _thread
                _thread.interrupt_main()
                return
            if self.action == "abort":
                os._exit(70)  # EX_SOFTWARE: let the supervisor restart us
            self._last = time.monotonic()  # warn: re-arm

    def _report(self, idle):
        lines = [
            f"[watchdog] no heartbeat for {idle:.1f}s "
            f"(timeout {self.timeout:.0f}s, {self._beats} beats, "
            f"last info {self._info or '{}'}) — thread stacks:"]
        frames = sys._current_frames()
        for t in threading.enumerate():
            frame = frames.get(t.ident)
            lines.append(f"--- thread {t.name} ({t.ident}) ---")
            if frame is not None:
                lines.extend(
                    ln.rstrip() for ln in traceback.format_stack(frame))
        report = "\n".join(lines)
        _logger.error(report)
        if self.dump_path:
            try:
                with open(self.dump_path, "a") as f:
                    f.write(report + "\n")
            except OSError:
                pass
