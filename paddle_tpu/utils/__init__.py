"""paddle.utils (ref: python/paddle/utils/)."""
from __future__ import annotations

from . import profiler  # noqa: F401


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError:
        raise ImportError(f"module {name} not available in this environment")


def run_check():
    import jax
    print("paddle_tpu is installed successfully!")
    print(f"devices: {jax.devices()}")
    from .. import nn, optimizer, to_tensor
    lin = nn.Linear(4, 2)
    out = lin(to_tensor([[1.0, 2.0, 3.0, 4.0]]))
    loss = out.sum()
    loss.backward()
    opt = optimizer.SGD(0.1, parameters=lin.parameters())
    opt.step()
    print("single-device training check: OK")


def deprecated(since=None, update_to=None, reason=None):
    def deco(fn):
        return fn
    return deco
