"""paddle.utils (ref: python/paddle/utils/)."""
from __future__ import annotations

from . import profiler  # noqa: F401
from . import watchdog  # noqa: F401
from .watchdog import Watchdog  # noqa: F401


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError:
        raise ImportError(f"module {name} not available in this environment")


def run_check():
    import jax
    print("paddle_tpu is installed successfully!")
    print(f"devices: {jax.devices()}")
    from .. import nn, optimizer, to_tensor
    lin = nn.Linear(4, 2)
    out = lin(to_tensor([[1.0, 2.0, 3.0, 4.0]]))
    loss = out.sum()
    loss.backward()
    opt = optimizer.SGD(0.1, parameters=lin.parameters())
    opt.step()
    print("single-device training check: OK")


def deprecated(since=None, update_to=None, reason=None):
    def deco(fn):
        return fn
    return deco


def download(url, path=None, md5sum=None, **kw):
    """ref: python/paddle/utils/download.py — no network egress here; callers
    must point datasets at local files."""
    raise RuntimeError(
        "network downloads are unavailable in this environment; pass "
        "data_file= pointing at a local copy instead")


def dump_config(config, path=None):
    import json
    s = json.dumps(config, indent=2, default=str)
    if path:
        with open(path, "w") as f:
            f.write(s)
    return s


def require_version(min_version, max_version=None):
    from ..version import full_version

    def _tup(v):
        return tuple(int(p) for p in str(v).split(".")[:3] if p.isdigit())
    cur = _tup(full_version)
    if _tup(min_version) > cur:
        raise Exception(
            f"paddle_tpu>={min_version} required, found {full_version}")
    if max_version and _tup(max_version) < cur:
        raise Exception(
            f"paddle_tpu<={max_version} required, found {full_version}")


def load_op_library(lib_path):
    """Custom-op loading (ref: utils/op_version.py era API). Native TPU ops
    are Pallas kernels; C runtime extensions load via ctypes."""
    import ctypes
    return ctypes.CDLL(lib_path)


from ..core import unique_name  # noqa: E402,F401
