"""paddle.utils (ref: python/paddle/utils/)."""
from __future__ import annotations

from . import profiler  # noqa: F401
from . import watchdog  # noqa: F401
from .watchdog import Watchdog  # noqa: F401


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError:
        raise ImportError(f"module {name} not available in this environment")


def run_check():
    import jax
    print("paddle_tpu is installed successfully!")  # cli-print: run_check
    print(f"devices: {jax.devices()}")  # cli-print
    from .. import nn, optimizer, to_tensor
    lin = nn.Linear(4, 2)
    out = lin(to_tensor([[1.0, 2.0, 3.0, 4.0]]))
    loss = out.sum()
    loss.backward()
    opt = optimizer.SGD(0.1, parameters=lin.parameters())
    opt.step()
    print("single-device training check: OK")  # cli-print


def deprecated(since=None, update_to=None, reason=None):
    def deco(fn):
        return fn
    return deco


from . import download  # noqa: E402,F401  (the reference binds the
# MODULE at paddle.utils.download — paddle.utils.download.get_path_from_url
# is attribute-style in real zoo code)


def dump_config(config, path=None):
    import json
    s = json.dumps(config, indent=2, default=str)
    if path:
        with open(path, "w") as f:
            f.write(s)
    return s


def require_version(min_version, max_version=None):
    from ..version import full_version

    def _tup(v):
        return tuple(int(p) for p in str(v).split(".")[:3] if p.isdigit())
    cur = _tup(full_version)
    if _tup(min_version) > cur:
        raise Exception(
            f"paddle_tpu>={min_version} required, found {full_version}")
    if max_version and _tup(max_version) < cur:
        raise Exception(
            f"paddle_tpu<={max_version} required, found {full_version}")


def load_op_library(lib_path):
    """Custom-op loading (ref: utils/op_version.py era API). Native TPU ops
    are Pallas kernels; C runtime extensions load via ctypes."""
    import ctypes
    return ctypes.CDLL(lib_path)


from ..core import unique_name  # noqa: E402,F401


class ProfilerOptions:
    """Config dict with defaults (ref: utils/profiler.py:26)."""

    def __init__(self, options=None):
        import sys as _sys
        self.options = {
            "state": "All", "sorted_key": "default",
            "tracer_level": "Default", "batch_range": [0, _sys.maxsize],
            "output_thread_detail": False, "profile_path": "none",
            "timeline_path": "none", "op_summary_path": "none",
        }
        if options is not None:
            for key in self.options:
                if options.get(key) is not None:
                    self.options[key] = options[key]

    def with_state(self, state):
        self.options["state"] = state
        return self

    def __getitem__(self, name):
        if self.options.get(name) is None:
            raise ValueError(
                f"ProfilerOptions does not have an option named {name}.")
        val = self.options[name]
        return None if isinstance(val, str) and val == "none" else val


_current_profiler = None


class Profiler:
    """Batch-windowed profiling context (ref: utils/profiler.py:63):
    starts/stops the profiler when batch_id enters/leaves batch_range;
    reset_once_per_batch drives it from the train loop."""

    def __init__(self, enabled=True, options=None):
        self.profiler_options = options if options is not None \
            else ProfilerOptions()
        self.batch_id = 0
        self.enabled = enabled
        self._running = False

    def __enter__(self):
        global _current_profiler
        self.previous_profiler = _current_profiler
        _current_profiler = self
        if self.enabled and \
                self.profiler_options["batch_range"][0] == 0:
            self.start()
        return self

    def __exit__(self, *exc):
        global _current_profiler
        _current_profiler = self.previous_profiler
        self.stop()

    def start(self):
        if self.enabled and not self._running:
            # the trace destination is fixed at START on this stack
            # (jax.profiler.start_trace takes the dir)
            profiler.start_profiler(
                state=self.profiler_options["state"],
                tracer_option=self.profiler_options["tracer_level"],
                profile_path=self.profiler_options["profile_path"]
                or "/tmp/paddle_tpu_profile")
            self._running = True

    def stop(self):
        if self.enabled and self._running:
            profiler.stop_profiler(
                sorted_key=self.profiler_options["sorted_key"])
            self._running = False

    def reset(self):
        lo, hi = self.profiler_options["batch_range"]
        if self.batch_id == lo:
            self.start()
        elif self.batch_id == hi:
            self.stop()
        self.batch_id += 1

    # reference name for per-batch driving
    reset_once_per_batch = reset


def get_profiler():
    global _current_profiler
    if _current_profiler is None:
        _current_profiler = Profiler()
    return _current_profiler


class OpLastCheckpointChecker:
    """Op version-checkpoint query (ref: utils/op_version.py:50). The
    reference reads the C++ op version map; here ops carry no version
    checkpoints (one JAX fn per op, versioned with the package), so every
    query returns the empty update list — the honest answer, same type."""

    def __init__(self):
        self.checkpoints_map = {}

    def filter_updates(self, op_name, type=None, key=""):  # noqa: A002
        return []


def enable_persistent_compilation_cache(path=None):
    """Point jax at the repo-local persistent XLA compile cache so a
    warm-up run skips the 20-40s TPU compiles. One definition for
    bench.py and the perf/endurance scripts."""
    import os as _os

    import jax as _jax
    if path is None:
        path = _os.path.join(
            _os.path.dirname(_os.path.dirname(_os.path.dirname(
                _os.path.abspath(__file__)))), ".jax_cache")
    try:
        _os.makedirs(path, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", path)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs",
                           2.0)
    except Exception:  # pragma: no cover - cache is best-effort
        pass
    return path
