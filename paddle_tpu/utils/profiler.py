"""Profiler (ref: python/paddle/fluid/profiler.py).

TPU-first: wraps jax.profiler — traces land in a TensorBoard-compatible dir
with XLA HLO + TPU timeline instead of the reference's chrome-trace of CUDA
kernels. Also provides a light host-side step timer.
"""
from __future__ import annotations

import contextlib
import functools
import time
from collections import defaultdict

import jax

from ..observability import log as _log

_logger = _log.get_logger(__name__)
_records = defaultdict(list)


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/paddle_tpu_profile"):
    jax.profiler.start_trace(profile_path)
    t0 = time.time()
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        _logger.info("[profiler] trace written to %s (%.2fs)",
                     profile_path, time.time() - t0)


def start_profiler(state="All", tracer_option="Default",
                   profile_path="/tmp/paddle_tpu_profile"):
    jax.profiler.start_trace(profile_path)


def stop_profiler(sorted_key=None, profile_path="/tmp/paddle_tpu_profile"):
    jax.profiler.stop_trace()


class RecordEvent:
    """Host-side event timer: context manager AND decorator.

        with RecordEvent("matmul"): ...
        @record_event("step")            # or bare @record_event: the
        def step(...): ...               # event is named after the fn
    """

    def __init__(self, name):
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        _records[self.name].append(time.perf_counter() - self._t0)

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with RecordEvent(self.name):
                return fn(*args, **kwargs)
        return wrapped


def record_event(name):
    """RecordEvent factory; also usable as a bare decorator
    (`@record_event`), naming the event after the function."""
    if callable(name):
        return RecordEvent(name.__qualname__)(name)
    return RecordEvent(name)


def summary():
    """Per-event stats: count/total/mean plus min/max/p50/p99 (nearest-
    rank percentiles over the recorded samples)."""
    out = {}
    for name, times in _records.items():
        ts = sorted(times)
        n = len(ts)
        total = sum(ts)
        pct = (lambda p: ts[min(n - 1, int(p * n))])
        out[name] = {"count": n, "total": total, "mean": total / n,
                     "min": ts[0], "max": ts[-1],
                     "p50": pct(0.50), "p99": pct(0.99)}
    return out


def reset():
    _records.clear()


def _aggregate_ops(fn, steps, trace_dir, include_host):
    """Run `fn()` `steps` times under jax.profiler.trace and aggregate
    event durations by op name: {name: [total_ms, count]}. Only ONE
    timeline level is counted — the 'XLA Ops' lines when the trace has
    them (TPU), else all non-python lines — so module/step envelope
    events are not double-counted on top of their member ops."""
    import glob
    import os
    import tempfile
    from collections import defaultdict as _dd

    from jax.profiler import ProfileData

    own_dir = trace_dir is None
    trace_dir = trace_dir or tempfile.mkdtemp(prefix="ptpu_prof_")
    try:
        fn()  # warm/compile outside the trace
        with jax.profiler.trace(trace_dir):
            for _ in range(steps):
                fn()
        files = sorted(glob.glob(
            os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True))
        if not files:
            raise RuntimeError(f"no xplane.pb under {trace_dir}")
        pd = ProfileData.from_file(files[-1])
        planes = list(pd.planes)
        device_planes = [p for p in planes
                         if not p.name.startswith("/host:")
                         and "Task Environment" not in p.name]
        if not device_planes or include_host:
            device_planes = planes
        # one level only: prefer the per-op timeline when present
        plane_lines = []
        for plane in device_planes:
            lines = [ln for ln in plane.lines if ln.name != "python"]
            op_lines = [ln for ln in lines if ln.name == "XLA Ops"]
            plane_lines.append(op_lines or lines)
        totals = _dd(lambda: [0.0, 0])
        for lines in plane_lines:
            for line in lines:
                for ev in line.events:
                    name = ev.name
                    if name.startswith("end:") or not ev.duration_ns:
                        continue
                    t = totals[name]
                    t[0] += ev.duration_ns / 1e6
                    t[1] += 1
        return totals
    finally:
        if own_dir:  # don't leak multi-MB xplane traces into /tmp
            import shutil
            shutil.rmtree(trace_dir, ignore_errors=True)


def top_ops(fn, steps=3, k=25, trace_dir=None, include_host=False):
    """Profile `fn()` (already-compiled, zero-arg) and return the top-k
    device ops by total time: [(op_name, total_ms, count)].

    The missing tool for MFU work: runs `steps` calls under
    jax.profiler.trace, parses the xplane with jax.profiler.ProfileData
    (no TensorBoard round-trip), and aggregates event durations on the
    device planes — on TPU that is the XLA-op timeline, so the answer to
    "where do the milliseconds go" is one call away.
    """
    totals = _aggregate_ops(fn, steps, trace_dir, include_host)
    return sorted(((n, ms, c) for n, (ms, c) in totals.items()),
                  key=lambda x: -x[1])[:k]


def print_top_ops(fn, steps=3, k=25):
    totals = _aggregate_ops(fn, steps, None, False)
    grand = sum(ms for ms, _ in totals.values())
    rows = sorted(((n, ms, c) for n, (ms, c) in totals.items()),
                  key=lambda x: -x[1])[:k]
    shown = sum(ms for _, ms, _ in rows)
    print(f"{'op':<60} {'ms':>10} {'count':>7} {'%':>6}")  # cli-print
    for name, ms, c in rows:
        print(f"{name[:60]:<60} {ms:>10.3f} {c:>7} "  # cli-print: table
              f"{100 * ms / max(grand, 1e-9):>5.1f}%")
    print(f"# top-{len(rows)} covers "  # cli-print: print_top_ops report
          f"{100 * shown / max(grand, 1e-9):.1f}% "
          f"of {grand:.1f}ms total device-op time")
    return rows
