"""Profiler (ref: python/paddle/fluid/profiler.py).

TPU-first: wraps jax.profiler — traces land in a TensorBoard-compatible dir
with XLA HLO + TPU timeline instead of the reference's chrome-trace of CUDA
kernels. Also provides a light host-side step timer.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax

_records = defaultdict(list)


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/paddle_tpu_profile"):
    jax.profiler.start_trace(profile_path)
    t0 = time.time()
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        print(f"[profiler] trace written to {profile_path} "
              f"({time.time() - t0:.2f}s)")


def start_profiler(state="All", tracer_option="Default",
                   profile_path="/tmp/paddle_tpu_profile"):
    jax.profiler.start_trace(profile_path)


def stop_profiler(sorted_key=None, profile_path="/tmp/paddle_tpu_profile"):
    jax.profiler.stop_trace()


@contextlib.contextmanager
def record_event(name):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _records[name].append(time.perf_counter() - t0)


class RecordEvent:
    def __init__(self, name):
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        _records[self.name].append(time.perf_counter() - self._t0)


def summary():
    out = {}
    for name, times in _records.items():
        out[name] = {"count": len(times), "total": sum(times),
                     "mean": sum(times) / len(times)}
    return out


def reset():
    _records.clear()
