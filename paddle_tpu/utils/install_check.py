"""paddle.utils.install_check module path (ref: utils/install_check.py)."""
from . import run_check  # noqa: F401

__all__ = ["run_check"]
