"""Version info (ref: python/paddle/version.py generated at build time)."""
full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
istaged = True
commit = "tpu-native"


def show():
    print(f"paddle_tpu {full_version} (commit {commit})")  # cli-print
