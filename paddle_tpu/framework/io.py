"""paddle.save / paddle.load — checkpoint serialization.

Reference: python/paddle/framework/io.py + fluid/dygraph/checkpoint.py.
Format: a pickle of {key: np.ndarray | nested dict | scalars}. Tensors are
pulled to host as numpy; loading returns plain dicts of Tensors, matching the
reference behavior of returning a state_dict for `set_state_dict`.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._value), str(obj._value.dtype))
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    return obj


class _TensorPayload:
    __slots__ = ("array", "dtype")

    def __init__(self, array, dtype):
        self.array = array
        self.dtype = dtype


def _from_saveable(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        import jax.numpy as jnp
        return Tensor(jnp.asarray(obj.array).view(jnp.dtype(obj.dtype))
                      if obj.array.dtype.itemsize != jnp.dtype(obj.dtype).itemsize
                      else jnp.asarray(obj.array).astype(jnp.dtype(obj.dtype)))
    if isinstance(obj, dict):
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saveable(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_saveable(obj, return_numpy)
