"""paddle.save / paddle.load — checkpoint serialization.

Reference: python/paddle/framework/io.py + fluid/dygraph/checkpoint.py.
Format: a pickle of {key: np.ndarray | nested dict | scalars}. Tensors are
pulled to host as numpy; loading returns plain dicts of Tensors, matching the
reference behavior of returning a state_dict for `set_state_dict`.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._value), str(obj._value.dtype))
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    return obj


class _TensorPayload:
    __slots__ = ("array", "dtype")

    def __init__(self, array, dtype):
        self.array = array
        self.dtype = dtype


def _from_saveable(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        import jax.numpy as jnp
        return Tensor(jnp.asarray(obj.array).view(jnp.dtype(obj.dtype))
                      if obj.array.dtype.itemsize != jnp.dtype(obj.dtype).itemsize
                      else jnp.asarray(obj.array).astype(jnp.dtype(obj.dtype)))
    if isinstance(obj, dict):
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saveable(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_saveable(obj, return_numpy)


class AsyncSaver:
    """Failure-safe async checkpointing (SURVEY §2.36): snapshot to host
    memory synchronously (cheap device→host copy), write to disk on a
    background thread, atomic rename so a crash mid-write never corrupts the
    previous checkpoint."""

    def __init__(self):
        import threading
        self._thread = None
        self._lock = threading.Lock()

    def save(self, obj, path):
        import threading
        payload = _to_saveable(obj)  # device→host happens here, synchronously
        self.wait()

        def _write():
            tmp = path + ".tmp"
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "wb") as f:
                pickle.dump(payload, f, protocol=4)
            os.replace(tmp, path)

        with self._lock:
            self._thread = threading.Thread(target=_write, daemon=True)
            self._thread.start()

    def wait(self):
        with self._lock:
            t = self._thread
        if t is not None:
            t.join()


_async_saver = AsyncSaver()


def async_save(obj, path):
    """paddle.framework.io.async_save — non-blocking checkpoint write."""
    _async_saver.save(obj, path)


def wait_save():
    _async_saver.wait()
