"""Framework-level helpers (ref: python/paddle/framework/)."""
from __future__ import annotations

from ..core.mode import in_dygraph_mode  # noqa: F401
from ..core.place import CPUPlace, CUDAPlace, TPUPlace, _expected_place  # noqa: F401
from ..core.rng import seed  # noqa: F401
from . import io  # noqa: F401
from .io import load, save  # noqa: F401


def get_default_dtype():
    from ..core.dtype import get_default_dtype as g
    return g()


def set_default_dtype(d):
    from ..core.dtype import set_default_dtype as s
    return s(d)
from ..core.place import CUDAPinnedPlace  # noqa: E402,F401
from ..core.param_attr import ParamAttr  # noqa: E402,F401
from ..core.autograd import grad, no_grad  # noqa: E402,F401
from ..distributed.parallel import DataParallel  # noqa: E402,F401
from ..nn.layer.layers import LayerList  # noqa: E402,F401
from ..fluid.layers import create_parameter  # noqa: E402,F401
from ..compat import ComplexVariable, VarBase  # noqa: E402,F401
from ..fluid import core  # noqa: E402,F401
from ..core import rng as random  # noqa: E402,F401,A004  (framework.random)
