"""Places — device abstraction.

Reference: paddle/fluid/platform/place.h (CPUPlace/CUDAPlace/CUDAPinnedPlace) and
python/paddle/device.py. Here a Place wraps a jax device; TPUPlace is the
native accelerator place, CUDAPlace is accepted as an alias so reference-era
user code runs unchanged.
"""
from __future__ import annotations

import jax


class Place:
    """Base place: a logical device slot."""

    _kind = "undefined"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    def jax_device(self):
        devs = [d for d in jax.devices() if self._matches(d)]
        if not devs:
            devs = jax.devices()
        return devs[min(self._device_id, len(devs) - 1)]

    def _matches(self, d) -> bool:
        return True

    def __eq__(self, other):
        return type(self) is type(other) and self._device_id == other._device_id

    def __hash__(self):
        return hash((type(self).__name__, self._device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self._device_id})"


class CPUPlace(Place):
    _kind = "cpu"

    def __init__(self):
        super().__init__(0)

    def _matches(self, d):
        return d.platform == "cpu"


class TPUPlace(Place):
    _kind = "tpu"

    def _matches(self, d):
        return d.platform != "cpu"


# Alias: reference code constructing CUDAPlace(i) lands on the accelerator.
CUDAPlace = TPUPlace
XPUPlace = TPUPlace
CUDAPinnedPlace = CPUPlace

_current_device = None


def _accelerator_available() -> bool:
    try:
        return any(d.platform != "cpu" for d in jax.devices())
    except Exception:
        return False


def set_device(device):
    """paddle.set_device: 'cpu', 'tpu', 'tpu:0', 'gpu:0' (alias of tpu)."""
    global _current_device
    if isinstance(device, Place):
        _current_device = device
        return device
    name, _, idx = str(device).partition(":")
    idx = int(idx) if idx else 0
    if name in ("cpu",):
        _current_device = CPUPlace()
    elif name in ("tpu", "gpu", "cuda", "xpu", "npu"):
        _current_device = TPUPlace(idx)
    else:
        raise ValueError(f"unknown device {device!r}")
    return _current_device


def get_device():
    p = _expected_place()
    if isinstance(p, CPUPlace):
        return "cpu"
    return f"tpu:{p.get_device_id()}"


def _expected_place() -> Place:
    global _current_device
    if _current_device is None:
        _current_device = TPUPlace(0) if _accelerator_available() else CPUPlace()
    return _current_device


def is_compiled_with_cuda() -> bool:  # reference API parity
    return False


def is_compiled_with_tpu() -> bool:
    return True


def is_compiled_with_xpu() -> bool:
    return False
