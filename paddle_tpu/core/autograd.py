"""Eager autograd engine.

Reference: paddle/fluid/imperative/ (tracer + basic_engine, partial_grad).
TPU-first rework: instead of per-op handwritten grad kernels, every eager op
records a `jax.vjp` pullback closure as a Node in a dynamic graph hanging off
output Tensors. `backward()` walks the graph in reverse topological order and
accumulates cotangents into leaf `.grad`. Everything stays on-device; the
pullbacks are XLA computations. The jitted/static paths bypass this entirely
(whole-step `jax.grad`), so this engine only pays its cost in pure-eager code.
"""
from __future__ import annotations

import contextlib
import weakref
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor

_grad_enabled = True
_hooks: dict = {}  # id(tensor) -> list[hook]


class Node:
    __slots__ = ("vjp_fn", "inputs", "out_refs", "out_avals", "name", "multi",
                 "_out_mask", "pure_fn")

    def __init__(self, vjp_fn, inputs, outputs, name, multi, pure_fn=None):
        self.vjp_fn = vjp_fn
        self.inputs: List[Tensor] = inputs          # strong refs upstream
        self.out_refs = [weakref.ref(o) for o in outputs]
        self.out_avals = [(o._value.shape, o._value.dtype) for o in outputs]
        self.name = name
        self.multi = multi
        self._out_mask = None  # True per original output position kept as Tensor
        # primal closure (diff input values -> raw outputs): lets
        # `grad(create_graph=True)` re-derive the pullback as a *recorded*
        # op, so grads-of-grads re-enter the tape (double backward)
        self.pure_fn = pure_fn


def grad_enabled() -> bool:
    return _grad_enabled


# contextvar, not a module global: one thread's functional trace must not
# disable the eager tape for a concurrent thread running dygraph backward()
# (the jit trace snapshot is threading.local for the same reason)
import contextvars as _contextvars

_functional_trace = _contextvars.ContextVar("functional_trace", default=False)


def functional_trace_enabled() -> bool:
    return _functional_trace.get()


@contextlib.contextmanager
def functional_trace():
    """Marks a region where framework ops execute inside an OUTER jax
    transform that owns differentiation (build_train_step losses,
    Layer.functional_call, the static executor lowering, to_static).
    Inside it, ops with tracer operands skip the eager-tape jax.vjp and
    are called directly — the outer AD differentiates the primal and
    sees kernel custom_vjp rules natively (an inner jax.vjp would
    consume them: the pallas flash backward was silently lost this way).
    Eager code and user-managed traces that rely on Tensor.backward()
    (e.g. dygraph DataParallel inside shard_map) are unaffected."""
    token = _functional_trace.set(True)
    try:
        yield
    finally:
        _functional_trace.reset(token)


@contextlib.contextmanager
def no_grad():
    global _grad_enabled
    prev, _grad_enabled = _grad_enabled, False
    try:
        yield
    finally:
        _grad_enabled = prev


@contextlib.contextmanager
def enable_grad():
    global _grad_enabled
    prev, _grad_enabled = _grad_enabled, True
    try:
        yield
    finally:
        _grad_enabled = prev


class _NoGradDecorator:
    """paddle.no_grad usable as both context manager and decorator."""

    def __call__(self, fn=None):
        if fn is None:
            return no_grad()
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with no_grad():
                return fn(*a, **k)
        return wrapper

    def __enter__(self):
        self._cm = no_grad()
        return self._cm.__enter__()

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)


def register_hook(tensor: Tensor, hook):
    _hooks.setdefault(id(tensor), []).append(hook)

    class _Handle:
        def remove(self_inner):
            lst = _hooks.get(id(tensor), [])
            if hook in lst:
                lst.remove(hook)
    return _Handle()


def _zero_cotangent(shape, dtype):
    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, jax.dtypes.float0)


def _run_hooks(tensor: Tensor, g):
    for hook in _hooks.get(id(tensor), []):
        out = hook(Tensor(g, stop_gradient=True))
        if out is not None:
            g = out._value if isinstance(out, Tensor) else out
    return g


def _accumulate_leaf(tensor: Tensor, g):
    if tensor.stop_gradient:
        return
    if isinstance(g, Tensor):
        # create_graph path: keep the grad's own tape so it can be
        # differentiated again (paddle.grad(..., create_graph=True))
        for hook in _hooks.get(id(tensor), []):
            out = hook(g)
            if out is not None:
                g = out if isinstance(out, Tensor) else Tensor(out)
        tensor.grad = g if tensor.grad is None else tensor.grad + g
        return
    g = _run_hooks(tensor, g)
    if tensor.grad is None:
        tensor.grad = Tensor(g, stop_gradient=True)
    else:
        grad_val = (tensor.grad._value if isinstance(tensor.grad, Tensor)
                    else tensor.grad)
        tensor.grad = Tensor(grad_val + g, stop_gradient=True)


def _topo_from(root: Node) -> List[Node]:
    order, seen = [], set()
    stack = [(root, False)]
    while stack:
        node, done = stack.pop()
        if done:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            if t._node is not None:
                stack.append((t._node, False))
    return order  # post-order: dependencies first; iterate reversed for backward


def _recorded_pullback(node: Node, full):
    """Run `node`'s pullback as a *recorded* framework op, so the returned
    grads carry their own tape (arbitrary-order differentiation). The op's
    tensor inputs are the primal diff inputs plus the (possibly graphed)
    cotangents; inside, the forward is re-linearized with jax.vjp — the
    recompute is the price of making d(grad)/d(input) exact, residual terms
    included."""
    from ..ops._registry import apply_op

    n_in = len(node.inputs)
    pure_fn = node.pure_fn
    multi = node.multi
    out_mask = node._out_mask

    def pb_fn(*vals):
        xs, cts = vals[:n_in], list(vals[n_in:])
        _, vjp = jax.vjp(pure_fn, *xs)
        if out_mask is not None and len(out_mask) != len(cts):
            it = iter(cts)
            cts = [next(it) if keep else None for keep in out_mask]
        ct = tuple(cts) if multi else cts[0]
        return tuple(vjp(ct))

    args = tuple(node.inputs) + tuple(full)
    out = apply_op(pb_fn, node.name + "_grad", args, {})
    return list(out)


def backward(tensor: Tensor, grad_tensor: Optional[Tensor] = None,
             retain_graph: bool = False, create_graph: bool = False):
    if grad_tensor is None:
        seed = jnp.ones(tensor._value.shape, tensor._value.dtype)
    else:
        seed = grad_tensor._value if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)

    if tensor._node is None:
        _accumulate_leaf(tensor, seed)
        return
    if create_graph:
        retain_graph = True

    topo = _topo_from(tensor._node)
    # node id -> list of cotangents (one slot per output); under
    # create_graph the slots may hold Tensors (graphed cotangents)
    cots: dict = {}

    def _add_cts(a, b):
        if isinstance(a, Tensor) or isinstance(b, Tensor):
            a = a if isinstance(a, Tensor) else Tensor(a)
            return a + b
        return a + b

    def seed_output(node: Node, t: Tensor, g):
        slots = cots.setdefault(id(node), [None] * len(node.out_refs))
        for i, ref in enumerate(node.out_refs):
            if ref() is t:
                slots[i] = g if slots[i] is None else _add_cts(slots[i], g)
                return
        raise RuntimeError("tensor not found among its node outputs")

    seed_output(tensor._node, tensor, seed)

    for node in reversed(topo):
        slots = cots.pop(id(node), None)
        if slots is None:
            continue
        full = []
        for s, (shape, dtype) in zip(slots, node.out_avals):
            full.append(_zero_cotangent(shape, dtype) if s is None else s)
        if create_graph and node.pure_fn is None:
            # PyLayer etc.: the pullback is an opaque user function — we
            # cannot re-record it, and silently detaching would make
            # higher-order grads wrong instead of loud
            raise RuntimeError(
                f"op '{node.name}' is not twice differentiable: its backward "
                "is a user-defined function (PyLayer); create_graph=True "
                "cannot flow through it")
        if create_graph:
            in_grads = _recorded_pullback(node, full)
        else:
            raw = [g._value if isinstance(g, Tensor) else g for g in full]
            if node._out_mask is not None and len(node._out_mask) != len(raw):
                # re-insert None cotangents for None outputs of the primal fn
                it = iter(raw)
                raw = [next(it) if keep else None for keep in node._out_mask]
            ct = tuple(raw) if node.multi else raw[0]
            in_grads = node.vjp_fn(ct)
        for t, g in zip(node.inputs, in_grads):
            if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
                continue
            if t._node is not None:
                seed_output(t._node, t, g)
                if id(t) in _hooks:
                    _run_hooks(t, g._value if isinstance(g, Tensor) else g)
            else:
                _accumulate_leaf(t, g)
        if not retain_graph:
            node.vjp_fn = None

    if not retain_graph:
        for node in topo:
            for ref in node.out_refs:
                t = ref()
                if t is not None:
                    t._node = None
            node.inputs = []


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, allow_unused=False):
    """paddle.grad — functional gradient of outputs wrt inputs (no .grad writes).

    Implemented by running backward with temporary grad capture.
    """
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    gos = grad_outputs if isinstance(grad_outputs, (list, tuple)) else [grad_outputs] * len(outputs)
    saved = [(t.grad, t.stop_gradient) for t in inputs]
    for t in inputs:
        t.grad = None
        t.stop_gradient = False
    try:
        for o, go in zip(outputs, gos):
            backward(o, go,
                     retain_graph=True if retain_graph is None else retain_graph,
                     create_graph=create_graph)
        result = []
        for t in inputs:
            if t.grad is None:
                if not allow_unused:
                    result.append(Tensor(jnp.zeros(t._value.shape, t._value.dtype)))
                else:
                    result.append(None)
            else:
                result.append(t.grad)
    finally:
        for t, (g, sg) in zip(inputs, saved):
            t.grad, t.stop_gradient = g, sg
    return result
