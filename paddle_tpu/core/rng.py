"""Seeding and PRNG-key management.

Reference: python/paddle/fluid/generator.py + paddle.seed. JAX has explicit
functional PRNG keys; we keep a process-global generator so the paddle-style
imperative API (dropout, uniform, ...) works, while jitted/static paths thread
keys explicitly (each static Program run derives per-op keys from a root key).
"""
from __future__ import annotations

import threading

import jax
import numpy as np


class Generator:
    """PRNG-key manager. Key materialization is LAZY: `jax.random.key`
    initializes the XLA backend, and importing the framework must not do
    that — multi-host programs need `jax.distributed.initialize` to run
    before any backend touch (distributed/launch.py)."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._seed = int(seed)
        self._key = None
        self._count = 0

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._key = jax.random.key(self._seed)
        self._count = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def _ensure(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)

    def next_key(self):
        """Draw a fresh key (fold_in of a monotone counter — cheap, traceable)."""
        with self._lock:
            self._ensure()
            self._count += 1
            return jax.random.fold_in(self._key, self._count)

    def split(self, n: int):
        return jax.random.split(self.next_key(), n)


_default_generator = Generator(seed=np.random.SeedSequence().entropy % (2**31) if False else 0)


def seed(s: int):
    """paddle.seed — reseed the global generator (and numpy for host-side aug)."""
    _default_generator.manual_seed(s)
    np.random.seed(s % (2**32))
    return _default_generator


def default_generator() -> Generator:
    return _default_generator


def next_key():
    return _default_generator.next_key()


def get_rng_state():
    return {"seed": _default_generator._seed, "count": _default_generator._count}


def set_rng_state(state):
    _default_generator.manual_seed(state["seed"])
    _default_generator._count = state["count"]
