"""Tensor — the imperative array type.

Reference: paddle/fluid/imperative (VarBase) + python/paddle/fluid/dygraph/
varbase_patch_methods.py + math_op_patch.py. TPU-first: a Tensor is a thin
handle on a `jax.Array`; every method lowers to XLA ops, autograd records a
per-op `jax.vjp` pullback graph (see autograd.py) so eager mode is correct
while `@to_static`/jitted paths trace the same code into one XLA computation.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtype_mod
from .place import CPUPlace, TPUPlace, _expected_place


def _to_jax(data, dtype=None, place=None):
    if isinstance(data, Tensor):
        data = data._value
    if isinstance(data, (jax.Array,)):
        arr = data if dtype is None else data.astype(dtype_mod.convert_dtype(dtype))
    else:
        npd = np.asarray(data)
        if dtype is not None:
            npd = npd.astype(np.dtype(jnp.dtype(dtype_mod.convert_dtype(dtype))))
        elif npd.dtype == np.float64:
            npd = npd.astype(np.float32)  # paddle default: fp32
        arr = jnp.asarray(npd)
    if place is not None:
        arr = jax.device_put(arr, place.jax_device())
    return arr


class Tensor:
    __slots__ = ("_value", "stop_gradient", "grad", "name", "persistable",
                 "_node", "trainable", "__weakref__")

    # ops resolve higher than numpy arrays in dunders
    __array_priority__ = 100

    def __init__(self, value, dtype=None, place=None, stop_gradient=True,
                 name=None, persistable=False):
        self._value = _to_jax(value, dtype, place)
        self.stop_gradient = stop_gradient
        self.grad: Optional[Tensor] = None
        self.name = name
        self.persistable = persistable
        self._node = None  # autograd.Node that produced this tensor
        self.trainable = False

    # ---- basic properties -------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        try:
            dev = next(iter(self._value.devices()))
            return CPUPlace() if dev.platform == "cpu" else TPUPlace(dev.id)
        except Exception:
            return _expected_place()

    @property
    def is_leaf(self):
        return self._node is None

    def numel(self):
        return self.size

    def numpy(self):
        return np.asarray(self._value)

    def item(self, *idx):
        return self._value[idx].item() if idx else self._value.item()

    def tolist(self):
        return self.numpy().tolist()

    def __jax_array__(self):
        return self._value

    # ---- autograd ---------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from . import autograd
        autograd.backward(self, grad_tensor, retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def clone(self) -> "Tensor":
        from .. import ops
        return ops.assign(self)

    def stop_gradient_(self, flag=True):
        self.stop_gradient = flag
        return self

    @property
    def gradient(self):
        return None if self.grad is None else self.grad.numpy()

    def register_hook(self, hook):
        from . import autograd
        return autograd.register_hook(self, hook)

    # ---- conversion / movement -------------------------------------------
    def astype(self, dtype) -> "Tensor":
        from .. import ops
        return ops.cast(self, dtype)

    def cast(self, dtype) -> "Tensor":
        return self.astype(dtype)

    def cpu(self) -> "Tensor":
        return Tensor(jax.device_put(self._value, CPUPlace().jax_device()),
                      stop_gradient=self.stop_gradient)

    def tpu(self, device_id=0) -> "Tensor":
        return Tensor(jax.device_put(self._value, TPUPlace(device_id).jax_device()),
                      stop_gradient=self.stop_gradient)

    cuda = tpu

    def pin_memory(self):
        return self.cpu()

    def set_value(self, value):
        """In-place update of the payload (used by optimizers/checkpoint load)."""
        if isinstance(value, Tensor):
            value = value._value
        arr = _to_jax(value)
        if tuple(arr.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {arr.shape} vs {self._value.shape}")
        self._value = arr.astype(self._value.dtype)

    def copy_(self, other, *a):
        self.set_value(other)
        return self

    # ---- repr -------------------------------------------------------------
    def __repr__(self):
        grad_txt = f", stop_gradient={self.stop_gradient}"
        return (f"Tensor(shape={self.shape}, dtype={self._value.dtype.name}"
                f"{grad_txt},\n       {np.asarray(self._value)!r})")

    __str__ = __repr__

    def __hash__(self):
        return id(self)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self.shape[0]

    def __bool__(self):
        return bool(self._value)

    def __int__(self):
        return int(self._value)

    def __float__(self):
        return float(self._value)

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return object.__format__(self, spec)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # dunder arithmetic is patched in by ops (math_op_patch pattern)


class Parameter(Tensor):
    """Trainable tensor owned by a Layer (ref: framework.py Parameter)."""

    __slots__ = ("optimize_attr", "regularizer", "need_clip",
                 "is_distributed", "no_weight_decay")

    def __init__(self, value, dtype=None, name=None, trainable=True):
        super().__init__(value, dtype=dtype, stop_gradient=not trainable,
                         name=name, persistable=True)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False

    # For a Parameter, `trainable` and `stop_gradient` are two views of one
    # bit (ref: ParamBase couples them): freezing via either attribute must
    # be seen by optimizers that check the other.
    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, value):
        self.stop_gradient = not value

    def __repr__(self):
        return ("Parameter containing:\n" + super().__repr__())


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """paddle.to_tensor"""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)
