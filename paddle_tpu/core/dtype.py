"""Dtype registry.

TPU-first: bfloat16 is a first-class dtype. Mirrors the dtype surface of the
reference (paddle/fluid/framework/data_type.h; python/paddle/fluid/data_feeder.py)
without the protobuf VarType enum — names map straight onto XLA element types.

64-bit policy: TPUs have no native 64-bit compute and JAX runs with x64
disabled, so "int64"/"float64"/"complex128" canonicalize to their 32-bit
counterparts (the standard JAX/flax convention). Reference code that feeds
int64 labels etc. runs unchanged; values are stored as int32.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical name -> jnp dtype
_DTYPES = {
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float32,  # canonicalized: no native f64 on TPU
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int32,  # canonicalized: no native i64 on TPU
    "uint8": jnp.uint8,
    "bool": jnp.bool_,
    "complex64": jnp.complex64,
    "complex128": jnp.complex64,  # canonicalized
}

_ALIASES = {
    "fp16": "float16",
    "bf16": "bfloat16",
    "fp32": "float32",
    "fp64": "float64",
    "half": "float16",
    "float": "float32",
    "double": "float64",
    "int": "int32",
    "long": "int64",
}

float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float32
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int32
uint8 = jnp.uint8
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex64

_default_dtype = "float32"


def set_default_dtype(d):
    global _default_dtype
    _default_dtype = dtype_name(d)


def get_default_dtype():
    return _default_dtype


def convert_dtype(dtype):
    """Normalize any dtype spec (str alias, np/jnp dtype, None) to a jnp dtype."""
    if dtype is None:
        return _DTYPES[_default_dtype]
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name not in _DTYPES:
            raise ValueError(f"unsupported dtype {dtype!r}")
        return _DTYPES[name]
    return jnp.dtype(dtype).type if not hasattr(dtype, "dtype") else dtype


def dtype_name(dtype) -> str:
    if dtype is None:
        return _default_dtype
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name in _DTYPES:
            return name
        raise ValueError(f"unsupported dtype {dtype!r}")
    return np.dtype(dtype).name if np.dtype(dtype).name in _DTYPES else str(np.dtype(dtype))


def is_floating(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(convert_dtype(dtype)), jnp.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(convert_dtype(dtype)), jnp.integer)
