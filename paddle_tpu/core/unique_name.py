"""Unique name generator (ref: python/paddle/fluid/unique_name.py)."""
from __future__ import annotations

import contextlib
from collections import defaultdict

_counters = defaultdict(int)
_prefix = []


def generate(key: str) -> str:
    _counters[key] += 1
    name = f"{key}_{_counters[key] - 1}"
    return "/".join(_prefix + [name]) if _prefix else name


@contextlib.contextmanager
def guard(new_prefix=None):
    global _counters
    old = _counters
    _counters = defaultdict(int)
    if new_prefix:
        _prefix.append(new_prefix.rstrip("/"))
    try:
        yield
    finally:
        _counters = old
        if new_prefix:
            _prefix.pop()


def switch():
    global _counters
    _counters = defaultdict(int)
