"""Global execution mode: dygraph (eager, default — as in reference 2.0) vs static.

Reference: python/paddle/fluid/framework.py `in_dygraph_mode` / `_dygraph_guard`.
In static mode op wrappers append to the current Program instead of executing;
the hook is registered by paddle_tpu.static to avoid an import cycle.
"""
from __future__ import annotations

_static_mode = False
# set by paddle_tpu.static: fn(opname, fn, args, kwargs, meta) -> outputs
_static_append_op_hook = None


def enable_static():
    global _static_mode
    _static_mode = True


def disable_static():
    global _static_mode
    _static_mode = False


def in_dygraph_mode() -> bool:
    return not _static_mode


def in_static_mode() -> bool:
    return _static_mode


def register_static_hook(hook):
    global _static_append_op_hook
    _static_append_op_hook = hook


def static_hook():
    return _static_append_op_hook
