"""paddle.slim — quantization toolkit (QAT + PTQ), TPU-first.

Reference: python/paddle/fluid/contrib/slim/quantization/ —
QuantizationTransformPass inserts fake_quantize/dequantize ops into the
program; imperative/qat.py (ImperativeQuantAware) swaps dygraph layers for
quantized variants; post_training_quantization.py calibrates activation
ranges then emits an int8 program.

TPU-first rework: int8 matmul/conv are first-class MXU ops, so the
converted path quantizes activations on the fly, runs the contraction in
int8 with an int32 accumulator (`preferred_element_type`), and folds the
(act_scale × weight_scale) rescale into one multiply — XLA fuses it into
the epilogue. Fake-quant for QAT is a straight-through estimator
(custom_vjp). Observers are host-side state updated eagerly (the reference
QAT is dygraph-only too).

Public API (reference names):
  ImperativeQuantAware      — QAT: .quantize(model) swaps layers in place
  PostTrainingQuantization  — PTQ: calibrate → .convert() int8 model
  fake_quant, quantize_symmetric, dequantize — functional pieces
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .. import nn


def _qmax(bits):
    return (1 << (bits - 1)) - 1


def quantize_symmetric(x, scale, bits=8):
    """x (float) -> int8/int16 codes with symmetric per-tensor scale."""
    qm = _qmax(bits)
    dt = jnp.int8 if bits <= 8 else jnp.int16
    safe = jnp.maximum(scale, 1e-12)
    return jnp.clip(jnp.round(x / safe * qm), -qm, qm).astype(dt)


def dequantize(q, scale, bits=8):
    return q.astype(jnp.float32) * (scale / _qmax(bits))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fake_quant(x, scale, bits=8):
    """Quantize→dequantize with a straight-through gradient (ref:
    fake_quantize_dequantize ops in quantization_pass.py)."""
    return dequantize(quantize_symmetric(x, scale, bits), scale, bits)


def _fq_fwd(x, scale, bits):
    safe = jnp.maximum(scale, 1e-12)
    in_range = jnp.abs(x) <= safe
    return fake_quant(x, scale, bits), in_range


def _fq_bwd(bits, res, g):
    in_range = res
    return (jnp.where(in_range, g, 0.0), jnp.zeros(()))


fake_quant.defvjp(_fq_fwd, _fq_bwd)


# ---------------------------------------------------------------- observers

class AbsmaxObserver:
    """Running max(|x|) (ref algo='abs_max')."""

    def __init__(self):
        self.scale = 0.0

    def update(self, x):
        self.scale = max(self.scale, float(jnp.max(jnp.abs(x))))


class MovingAverageAbsmaxObserver:
    """EMA of per-batch max(|x|) (ref algo='moving_average_abs_max')."""

    def __init__(self, momentum=0.9):
        self.momentum = momentum
        self.scale = 0.0
        self._init = False

    def update(self, x):
        cur = float(jnp.max(jnp.abs(x)))
        if not self._init:
            self.scale, self._init = cur, True
        else:
            self.scale = self.momentum * self.scale \
                + (1 - self.momentum) * cur


class PercentileObserver:
    """Percentile of |x| over calibration (ref algo='hist'-style, robust to
    outliers)."""

    def __init__(self, percentile=99.9):
        self.percentile = percentile
        self._samples = []

    def update(self, x):
        a = np.abs(np.asarray(x)).ravel()
        if a.size > 4096:  # subsample to bound memory
            a = a[:: max(1, a.size // 4096)]
        self._samples.append(a)

    @property
    def scale(self):
        if not self._samples:
            return 0.0
        return float(np.percentile(np.concatenate(self._samples),
                                   self.percentile))


_OBSERVERS = {
    "abs_max": AbsmaxObserver,
    "moving_average_abs_max": MovingAverageAbsmaxObserver,
    "hist": PercentileObserver,
}


# ---------------------------------------------------------- quantized layers

class QuantedLinear(nn.Layer):
    """Linear in one of three modes:
    - 'qat': fake-quant weight + input each call (STE grads), observer
      tracks the activation range;
    - 'calib': float forward, observer records input absmax;
    - 'int8': real int8×int8→int32 matmul on the MXU, one rescale."""

    def __init__(self, inner, mode="qat", weight_bits=8, activation_bits=8,
                 act_observer="moving_average_abs_max"):
        super().__init__()
        self.inner = inner
        self.mode = mode
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.act_observer = _OBSERVERS[act_observer]()
        self.w_scale = float(jnp.max(jnp.abs(inner.weight._value)))
        self._wq = None

    def _observe(self, xv):
        import jax.core as jcore
        if not isinstance(xv, jcore.Tracer):  # observers are eager-only
            self.act_observer.update(xv)

    def convert(self):
        """Freeze to int8: quantize the weight once."""
        self._wq = quantize_symmetric(self.inner.weight._value,
                                      self.w_scale, self.weight_bits)
        self.mode = "int8"
        return self

    def forward(self, x):
        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        if self.mode == "calib":
            self._observe(xv)
            return self.inner(x)
        if self.mode == "qat":
            self._observe(xv)
            a_scale = self.act_observer.scale or float(jnp.max(jnp.abs(xv)))
            from ..ops._registry import apply_op

            def core(xv, wv, *bias):
                xq = fake_quant(xv, jnp.asarray(a_scale),
                                self.activation_bits)
                wq = fake_quant(wv, jnp.asarray(self.w_scale),
                                self.weight_bits)
                y = xq @ wq
                return y + bias[0] if bias else y

            args = [x if isinstance(x, Tensor) else Tensor(xv),
                    self.inner.weight]
            if self.inner.bias is not None:
                args.append(self.inner.bias)
            return apply_op(core, "quanted_linear", tuple(args), {})
        # int8 inference path
        a_scale = self.act_observer.scale or 1.0
        xq = quantize_symmetric(xv, a_scale, self.activation_bits)
        acc = jax.lax.dot_general(
            xq, self._wq,
            (((xv.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        rescale = (a_scale / _qmax(self.activation_bits)) * \
            (self.w_scale / _qmax(self.weight_bits))
        y = acc.astype(jnp.float32) * rescale
        if self.inner.bias is not None:
            y = y + self.inner.bias._value
        return Tensor(y)


class QuantedConv2D(nn.Layer):
    """Conv2D counterpart of QuantedLinear (NCHW)."""

    def __init__(self, inner, mode="qat", weight_bits=8, activation_bits=8,
                 act_observer="moving_average_abs_max"):
        super().__init__()
        self.inner = inner
        self.mode = mode
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.act_observer = _OBSERVERS[act_observer]()
        self.w_scale = float(jnp.max(jnp.abs(inner.weight._value)))
        self._wq = None

    def _observe(self, xv):
        import jax.core as jcore
        if not isinstance(xv, jcore.Tracer):
            self.act_observer.update(xv)

    def convert(self):
        self._wq = quantize_symmetric(self.inner.weight._value,
                                      self.w_scale, self.weight_bits)
        self.mode = "int8"
        return self

    def _conv(self, x, w, preferred=None):
        inner = self.inner
        st = inner.stride if isinstance(inner.stride, (list, tuple)) \
            else (inner.stride, inner.stride)
        pd = inner.padding if isinstance(inner.padding, (list, tuple)) \
            else (inner.padding, inner.padding)
        dl = inner.dilation if isinstance(inner.dilation, (list, tuple)) \
            else (inner.dilation, inner.dilation)
        kw = {}
        if preferred is not None:
            kw["preferred_element_type"] = preferred
        return jax.lax.conv_general_dilated(
            x, w, window_strides=tuple(st),
            padding=[(pd[0], pd[0]), (pd[1], pd[1])],
            rhs_dilation=tuple(dl), feature_group_count=inner.groups,
            dimension_numbers=("NCHW", "OIHW", "NCHW"), **kw)

    def forward(self, x):
        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        if self.mode == "calib":
            self._observe(xv)
            return self.inner(x)
        if self.mode == "qat":
            self._observe(xv)
            a_scale = self.act_observer.scale or float(jnp.max(jnp.abs(xv)))
            from ..ops._registry import apply_op

            def core(xv, wv, *bias):
                xq = fake_quant(xv, jnp.asarray(a_scale),
                                self.activation_bits)
                wq = fake_quant(wv, jnp.asarray(self.w_scale),
                                self.weight_bits)
                y = self._conv(xq, wq)
                if bias:
                    y = y + bias[0].reshape(1, -1, 1, 1)
                return y

            args = [x if isinstance(x, Tensor) else Tensor(xv),
                    self.inner.weight]
            if self.inner.bias is not None:
                args.append(self.inner.bias)
            return apply_op(core, "quanted_conv2d", tuple(args), {})
        a_scale = self.act_observer.scale or 1.0
        xq = quantize_symmetric(xv, a_scale, self.activation_bits)
        acc = self._conv(xq, self._wq, preferred=jnp.int32)
        rescale = (a_scale / _qmax(self.activation_bits)) * \
            (self.w_scale / _qmax(self.weight_bits))
        y = acc.astype(jnp.float32) * rescale
        if self.inner.bias is not None:
            y = y + self.inner.bias._value.reshape(1, -1, 1, 1)
        return Tensor(y)


_QUANTABLE = {}


def _quantable():
    if not _QUANTABLE:
        _QUANTABLE[nn.Linear] = QuantedLinear
        _QUANTABLE[nn.Conv2D] = QuantedConv2D
    return _QUANTABLE


def _swap(model, mode, weight_bits, activation_bits, act_observer):
    """Replace every quantable sublayer in place; returns the wrappers."""
    table = _quantable()
    wrapped = []

    def visit(layer):
        for name, child in list(layer._sub_layers.items()):
            cls = table.get(type(child))
            if cls is not None:
                q = cls(child, mode=mode, weight_bits=weight_bits,
                        activation_bits=activation_bits,
                        act_observer=act_observer)
                layer._sub_layers[name] = q
                if name in layer.__dict__:
                    layer.__dict__[name] = q
                wrapped.append(q)
            else:
                visit(child)

    visit(model)
    return wrapped


class ImperativeQuantAware:
    """QAT driver (ref: imperative/qat.py ImperativeQuantAware): swaps
    Linear/Conv2D for fake-quant wrappers; after training call
    `.convert(model)` for the int8 inference model."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 moving_rate=0.9, quantizable_layer_type=None):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.act_observer = activation_quantize_type
        self._wrapped = []

    def quantize(self, model):
        self._wrapped = _swap(model, "qat", self.weight_bits,
                              self.activation_bits, self.act_observer)
        return model

    def convert(self, model):
        for q in self._wrapped:
            q.convert()
        return model

    def save_quantized_model(self, layer, path, input_spec=None, **config):
        from .. import jit
        self.convert(layer)
        jit.save(layer, path, input_spec=input_spec)


class PostTrainingQuantization:
    """PTQ driver (ref: post_training_quantization.py): calibrate activation
    ranges over sample data, then convert weights+compute to int8."""

    def __init__(self, model=None, algo="hist", weight_bits=8,
                 activation_bits=8, executor=None, **kw):
        self.model = model
        self.algo = {"abs_max": "abs_max", "hist": "hist",
                     "avg": "moving_average_abs_max",
                     "mse": "hist", "KL": "hist"}.get(algo, "abs_max")
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self._wrapped = []

    def quantize(self, data_loader=None, batch_nums=None):
        """Calibration pass: run the model over data_loader batches with
        observers attached, then freeze to int8."""
        self._wrapped = _swap(self.model, "calib", self.weight_bits,
                              self.activation_bits, self.act_observer_name)
        self.model.eval()
        if data_loader is not None:
            for i, batch in enumerate(data_loader):
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                if not isinstance(x, Tensor):
                    x = Tensor(jnp.asarray(np.asarray(x)))
                self.model(x)
                if batch_nums is not None and i + 1 >= batch_nums:
                    break
        return self.convert()

    @property
    def act_observer_name(self):
        return self.algo

    def convert(self):
        for q in self._wrapped:
            q.convert()
        return self.model
