"""paddle.slim — quantization toolkit (QAT + PTQ), TPU-first.

Reference: python/paddle/fluid/contrib/slim/quantization/ —
QuantizationTransformPass inserts fake_quantize/dequantize ops into the
program (per-tensor `abs_max` and per-channel `channel_wise_abs_max`,
quantization_pass.py:329); imperative/qat.py (ImperativeQuantAware) swaps
dygraph layers for quantized variants; post_training_quantization.py
calibrates activation ranges then emits an int8 program.

TPU-first rework: int8 matmul/conv are first-class MXU ops, so the
converted path quantizes activations on the fly, runs the contraction in
int8 with an int32 accumulator (`preferred_element_type`), and folds the
(act_scale × weight_scale) rescale into one multiply — XLA fuses it into
the epilogue. Fake-quant for QAT is a straight-through estimator
(custom_vjp). Two observer designs, matching the two execution modes:

- QAT activation ranges live in a registered *buffer* updated with traced
  jnp ops (EMA of per-batch absmax). Buffers flow through
  `Layer.functional_state()`, so the update works identically in eager
  mode and inside `@to_static`/hapi's jitted train step — the jit wrapper
  returns new buffer values and writes them back (jit/__init__.py pure()).
- PTQ calibration is eager-only by contract (like the reference's
  sample-generator loop), so the 'hist'/percentile observer may keep
  host-side sample lists.

Weight scales during QAT are recomputed from the *current* weights inside
the traced computation every forward (reference fake_quantize_abs_max also
re-reads the weight each pass), so weights drifting outside their initial
range are never silently clipped.

Public API (reference names):
  ImperativeQuantAware      — QAT: .quantize(model) swaps layers in place
  PostTrainingQuantization  — PTQ: calibrate → .convert() int8 model
  fake_quant, quantize_symmetric, dequantize — functional pieces
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .. import nn


def _qmax(bits):
    return (1 << (bits - 1)) - 1


def quantize_symmetric(x, scale, bits=8):
    """x (float) -> int8/int16 codes with symmetric scale. `scale` may be a
    scalar (per-tensor) or an array broadcastable against x (per-channel)."""
    qm = _qmax(bits)
    dt = jnp.int8 if bits <= 8 else jnp.int16
    safe = jnp.maximum(scale, 1e-12)
    return jnp.clip(jnp.round(x / safe * qm), -qm, qm).astype(dt)


def dequantize(q, scale, bits=8):
    return q.astype(jnp.float32) * (scale / _qmax(bits))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fake_quant(x, scale, bits=8):
    """Quantize→dequantize with a straight-through gradient (ref:
    fake_quantize_dequantize ops in quantization_pass.py). Per-tensor or
    per-channel depending on scale's shape."""
    return dequantize(quantize_symmetric(x, scale, bits), scale, bits)


def _fq_fwd(x, scale, bits):
    safe = jnp.maximum(scale, 1e-12)
    in_range = jnp.abs(x) <= safe
    return fake_quant(x, scale, bits), (in_range, scale)


def _fq_bwd(bits, res, g):
    in_range, scale = res
    return (jnp.where(in_range, g, 0.0), jnp.zeros(jnp.shape(scale)))


fake_quant.defvjp(_fq_fwd, _fq_bwd)


def channel_axes(weight_ndim, kind):
    """Reduction axes for channel_wise_abs_max. Paddle quantizes conv weights
    per output channel (OIHW axis 0) and Linear/matmul weights per output
    feature (last axis) — quantization_pass.py:329."""
    if kind == "conv":
        return tuple(range(1, weight_ndim))
    return tuple(range(weight_ndim - 1))


def weight_scale_of(w, quantize_type, kind):
    """Current-weight scale, traced (works on tracers under jit)."""
    if quantize_type == "channel_wise_abs_max":
        return jnp.max(jnp.abs(w), axis=channel_axes(w.ndim, kind),
                       keepdims=True)
    return jnp.max(jnp.abs(w))


# ---------------------------------------------------------------- observers

class AbsmaxObserver:
    """Running max(|x|) (ref algo='abs_max')."""

    def __init__(self):
        self.scale = 0.0

    def update(self, x):
        self.scale = max(self.scale, float(jnp.max(jnp.abs(x))))


class MovingAverageAbsmaxObserver:
    """EMA of per-batch max(|x|) (ref algo='moving_average_abs_max')."""

    def __init__(self, momentum=0.9):
        self.momentum = momentum
        self.scale = 0.0
        self._init = False

    def update(self, x):
        cur = float(jnp.max(jnp.abs(x)))
        if not self._init:
            self.scale, self._init = cur, True
        else:
            self.scale = self.momentum * self.scale \
                + (1 - self.momentum) * cur


class PercentileObserver:
    """Percentile of |x| over calibration (ref algo='hist'-style, robust to
    outliers)."""

    def __init__(self, percentile=99.9):
        self.percentile = percentile
        self._samples = []

    def update(self, x):
        a = np.abs(np.asarray(x)).ravel()
        if a.size > 4096:  # subsample to bound memory
            a = a[:: max(1, a.size // 4096)]
        self._samples.append(a)

    @property
    def scale(self):
        if not self._samples:
            return 0.0
        return float(np.percentile(np.concatenate(self._samples),
                                   self.percentile))


_OBSERVERS = {
    "abs_max": AbsmaxObserver,
    "moving_average_abs_max": MovingAverageAbsmaxObserver,
    "hist": PercentileObserver,
}


# ---------------------------------------------------------- quantized layers

class _QuantedBase(nn.Layer):
    """Shared machinery for QuantedLinear/QuantedConv2D.

    Modes:
    - 'qat': fake-quant weight (scale recomputed from current weights,
      in-trace) + input (EMA buffer scale) each call, STE grads;
    - 'calib': float forward, host observer records input range (eager);
    - 'int8': real int8×int8→int32 contraction on the MXU, one rescale.
    """

    _kind = "linear"

    def __init__(self, inner, mode="qat", weight_bits=8, activation_bits=8,
                 act_observer="moving_average_abs_max",
                 weight_quantize_type="abs_max", moving_rate=0.9):
        super().__init__()
        self.inner = inner
        self.mode = mode
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.weight_quantize_type = weight_quantize_type
        self.moving_rate = moving_rate
        self.act_quantize_type = act_observer
        self.act_observer = _OBSERVERS[act_observer]()
        # traced per-batch activation range stat; 0.0 == uninitialized.
        # abs_max -> running max (never decreases); moving_average_abs_max/
        # hist -> EMA (hist's percentile host observer is calib-only, QAT
        # falls back to EMA like the reference's MovingAverageAbsMaxScale).
        self.register_buffer("act_scale", Tensor(jnp.zeros((), jnp.float32)))
        self._wq = None
        self._w_scale_frozen = None
        self._a_scale_frozen = None

    # -- activation range tracking ------------------------------------
    def _track_act(self, xv):
        """Absmax-stat update as traced ops on the act_scale buffer — runs
        under jit (buffer round-trips through functional_state) and eagerly."""
        cur = jnp.max(jnp.abs(xv)).astype(jnp.float32)
        old = self.act_scale._value
        if self.act_quantize_type == "abs_max":
            new = jnp.maximum(old, cur)
        else:
            new = jnp.where(
                old > 0,
                self.moving_rate * old + (1 - self.moving_rate) * cur,
                cur)
        self.act_scale._value = new
        return new

    def _act_scale_for_eval(self, xv):
        """Frozen stat for eval-mode QAT forwards (no observer pollution —
        ref MovingAverageAbsMaxScale only updates when training)."""
        buf = self.act_scale._value
        return jnp.where(buf > 0, buf, jnp.max(jnp.abs(xv)))

    def _observe_host(self, xv):
        import jax.core as jcore
        if not isinstance(xv, jcore.Tracer):  # calib path is eager-only
            self.act_observer.update(xv)

    def _calib_scale(self):
        """Best activation scale available at convert time."""
        host = self.act_observer.scale
        buf = float(self.act_scale._value)
        return host or buf or 1.0

    # -- contraction (subclass hook) ----------------------------------
    def _contract(self, x, w, preferred=None):
        raise NotImplementedError

    def _add_bias(self, y, bias):
        raise NotImplementedError

    def _per_channel_acc_scale(self, w_scale):
        """Reshape the per-channel weight scale to broadcast against the
        contraction output."""
        raise NotImplementedError

    # -- lifecycle ----------------------------------------------------
    def convert(self):
        """Freeze to int8: quantize the weight once with the final scale."""
        w = self.inner.weight._value
        ws = weight_scale_of(w, self.weight_quantize_type, self._kind)
        self._w_scale_frozen = jnp.asarray(ws)
        self._wq = quantize_symmetric(w, self._w_scale_frozen,
                                      self.weight_bits)
        self._a_scale_frozen = self._calib_scale()
        self.mode = "int8"
        return self

    # back-compat: round-2 tests/code read `.w_scale` as the per-tensor float
    @property
    def w_scale(self):
        if self._w_scale_frozen is not None:
            return float(jnp.max(self._w_scale_frozen))
        return float(jnp.max(jnp.abs(self.inner.weight._value)))

    def forward(self, x):
        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        if self.mode == "calib":
            self._observe_host(xv)
            self._track_act(xv)
            return self.inner(x)
        if self.mode == "qat":
            a_scale = self._track_act(xv) if self.training \
                else self._act_scale_for_eval(xv)
            from ..ops._registry import apply_op
            wq_type, kind, bits_w, bits_a = (self.weight_quantize_type,
                                             self._kind, self.weight_bits,
                                             self.activation_bits)

            def core(xv, wv, *bias):
                xq = fake_quant(xv, a_scale, bits_a)
                # live scale from the *current* weight, so drifting weights
                # are never clipped by a stale construction-time range
                ws = weight_scale_of(jax.lax.stop_gradient(wv), wq_type, kind)
                wq = fake_quant(wv, ws, bits_w)
                y = self._contract(xq, wq)
                return self._add_bias(y, bias[0]) if bias else y

            args = [x if isinstance(x, Tensor) else Tensor(xv),
                    self.inner.weight]
            if self.inner.bias is not None:
                args.append(self.inner.bias)
            return apply_op(core, f"quanted_{self._kind}", tuple(args), {})
        # int8 inference path
        a_scale = self._a_scale_frozen if self._a_scale_frozen is not None \
            else self._calib_scale()  # `or` would bool() a traced array
        xq = quantize_symmetric(xv, a_scale, self.activation_bits)
        acc = self._contract(xq, self._wq, preferred=jnp.int32)
        w_rescale = self._per_channel_acc_scale(
            self._w_scale_frozen / _qmax(self.weight_bits))
        y = acc.astype(jnp.float32) * \
            ((a_scale / _qmax(self.activation_bits)) * w_rescale)
        if self.inner.bias is not None:
            y = self._add_bias(y, self.inner.bias)
        # serve in the caller's precision: a bf16 pipeline gets bf16 back
        # (halves the epilogue HBM write and every downstream read); f32
        # callers see unchanged behavior
        if xv.dtype == jnp.bfloat16:
            y = y.astype(jnp.bfloat16)
        return Tensor(y)


class QuantedLinear(_QuantedBase):
    """Linear with per-tensor or per-output-feature (channel_wise_abs_max)
    weight quantization. Weight layout [in, out]; channel scale shape
    [1, out] broadcasts over both the weight and the [..., out] output."""

    _kind = "linear"

    def _contract(self, x, w, preferred=None):
        kw = {"preferred_element_type": preferred} if preferred else {}
        return jax.lax.dot_general(
            x, w, (((x.ndim - 1,), (0,)), ((), ())), **kw)

    def _add_bias(self, y, bias):
        b = bias._value if isinstance(bias, Tensor) else bias
        return y + b

    def _per_channel_acc_scale(self, ws):
        return ws.reshape(-1) if ws.ndim else ws


class QuantedConv2D(_QuantedBase):
    """Conv2D counterpart (NCHW, OIHW weights; channel scale over axis O)."""

    _kind = "conv"

    def _contract(self, x, w, preferred=None):
        inner = self.inner
        st = inner.stride if isinstance(inner.stride, (list, tuple)) \
            else (inner.stride, inner.stride)
        pd = inner.padding if isinstance(inner.padding, (list, tuple)) \
            else (inner.padding, inner.padding)
        dl = inner.dilation if isinstance(inner.dilation, (list, tuple)) \
            else (inner.dilation, inner.dilation)
        kw = {}
        if preferred is not None:
            kw["preferred_element_type"] = preferred
        return jax.lax.conv_general_dilated(
            x, w, window_strides=tuple(st),
            padding=[(pd[0], pd[0]), (pd[1], pd[1])],
            rhs_dilation=tuple(dl), feature_group_count=inner.groups,
            dimension_numbers=("NCHW", "OIHW", "NCHW"), **kw)

    def _add_bias(self, y, bias):
        b = bias._value if isinstance(bias, Tensor) else bias
        return y + b.reshape(1, -1, 1, 1)

    def _per_channel_acc_scale(self, ws):
        # [O,1,1,1] -> [1,O,1,1] to broadcast against NCHW accumulators
        return ws.reshape(1, -1, 1, 1) if ws.ndim else ws


_QUANTABLE = {}


def _quantable():
    if not _QUANTABLE:
        _QUANTABLE[nn.Linear] = QuantedLinear
        _QUANTABLE[nn.Conv2D] = QuantedConv2D
    return _QUANTABLE


def _swap(model, mode, weight_bits, activation_bits, act_observer,
          weight_quantize_type="abs_max", moving_rate=0.9):
    """Replace every quantable sublayer in place; returns the wrappers."""
    table = _quantable()
    wrapped = []

    def visit(layer):
        for name, child in list(layer._sub_layers.items()):
            cls = table.get(type(child))
            if cls is not None:
                q = cls(child, mode=mode, weight_bits=weight_bits,
                        activation_bits=activation_bits,
                        act_observer=act_observer,
                        weight_quantize_type=weight_quantize_type,
                        moving_rate=moving_rate)
                layer._sub_layers[name] = q
                if name in layer.__dict__:
                    layer.__dict__[name] = q
                wrapped.append(q)
            else:
                visit(child)

    visit(model)
    return wrapped


def fuse_conv_bn_weights(w, b, running_mean, running_var, eps, gamma, beta):
    """Fold BatchNorm stats into conv weights (ref: the reference's
    conv+bn fuse passes in slim quantization): w' = w·γ/σ per out channel,
    b' = (b-μ)·γ/σ + β."""
    std = jnp.sqrt(running_var + eps)
    scale = (gamma / std) if gamma is not None else (1.0 / std)
    w2 = w * scale.reshape(-1, *([1] * (w.ndim - 1)))
    b0 = b if b is not None else jnp.zeros_like(running_mean)
    b2 = (b0 - running_mean) * scale + (beta if beta is not None else 0.0)
    return w2, b2


def fuse_conv_bn(model):
    """Fuse every adjacent (Conv2D, BatchNorm2D) pair inside Sequential
    containers into a single Conv2D with folded weights — the standard
    pre-quantization transform (run before PTQ/QAT so the int8 conv sees
    the deployed weights). Returns the number of pairs fused."""
    from ..nn.layer.layers import Sequential
    fused = 0

    def visit(layer):
        nonlocal fused
        if isinstance(layer, Sequential):
            names = list(layer._sub_layers)
            i = 0
            while i + 1 < len(names):
                a = layer._sub_layers[names[i]]
                bnl = layer._sub_layers[names[i + 1]]
                if type(a) is nn.Conv2D and isinstance(
                        bnl, (nn.BatchNorm2D, nn.BatchNorm)):
                    w2, b2 = fuse_conv_bn_weights(
                        a.weight._value,
                        a.bias._value if a.bias is not None else None,
                        bnl._mean._value, bnl._variance._value,
                        bnl.epsilon,
                        bnl.weight._value if bnl.weight is not None
                        else None,
                        bnl.bias._value if bnl.bias is not None else None)
                    a.weight._value = w2
                    if a.bias is None:
                        from ..core.tensor import Parameter
                        a.bias = Parameter(b2)
                    else:
                        a.bias._value = b2
                    from ..nn import Identity
                    layer._sub_layers[names[i + 1]] = Identity()
                    fused += 1
                    i += 2
                    continue
                i += 1
        for child in layer._sub_layers.values():
            visit(child)

    visit(model)
    return fused


class ImperativeQuantAware:
    """QAT driver (ref: imperative/qat.py ImperativeQuantAware): swaps
    Linear/Conv2D for fake-quant wrappers; after training call
    `.convert(model)` for the int8 inference model."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 moving_rate=0.9, quantizable_layer_type=None):
        if weight_quantize_type not in ("abs_max", "channel_wise_abs_max"):
            raise ValueError(
                f"unsupported weight_quantize_type {weight_quantize_type!r}")
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.act_observer = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type
        self.moving_rate = moving_rate
        self._wrapped = []

    def quantize(self, model):
        self._wrapped = _swap(model, "qat", self.weight_bits,
                              self.activation_bits, self.act_observer,
                              self.weight_quantize_type, self.moving_rate)
        return model

    def convert(self, model):
        for q in self._wrapped:
            q.convert()
        return model

    def save_quantized_model(self, layer, path, input_spec=None, **config):
        from .. import jit
        self.convert(layer)
        jit.save(layer, path, input_spec=input_spec)


class PostTrainingQuantization:
    """PTQ driver (ref: post_training_quantization.py): calibrate activation
    ranges over sample data, then convert weights+compute to int8."""

    def __init__(self, model=None, algo="hist", weight_bits=8,
                 activation_bits=8, executor=None,
                 weight_quantize_type="channel_wise_abs_max", **kw):
        if weight_quantize_type not in ("abs_max", "channel_wise_abs_max"):
            raise ValueError(
                f"unsupported weight_quantize_type {weight_quantize_type!r}")
        self.model = model
        self.algo = {"abs_max": "abs_max", "hist": "hist",
                     "avg": "moving_average_abs_max",
                     "mse": "hist", "KL": "hist"}.get(algo, "abs_max")
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.weight_quantize_type = weight_quantize_type
        self._wrapped = []

    def quantize(self, data_loader=None, batch_nums=None):
        """Calibration pass: run the model over data_loader batches with
        observers attached, then freeze to int8."""
        self._wrapped = _swap(self.model, "calib", self.weight_bits,
                              self.activation_bits, self.act_observer_name,
                              self.weight_quantize_type)
        self.model.eval()
        if data_loader is not None:
            for i, batch in enumerate(data_loader):
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                if not isinstance(x, Tensor):
                    x = Tensor(jnp.asarray(np.asarray(x)))
                self.model(x)
                if batch_nums is not None and i + 1 >= batch_nums:
                    break
        return self.convert()

    @property
    def act_observer_name(self):
        return self.algo

    def convert(self):
        for q in self._wrapped:
            q.convert()
        return self.model

    def save_quantized_model(self, path, input_spec=None, **config):
        from .. import jit
        jit.save(self.model, path, input_spec=input_spec)
