"""paddle.reader — reader (generator) decorators.

Reference: python/paddle/reader/decorator.py:1-672. A "reader" is a
zero-arg callable returning an iterator over samples; decorators wrap
readers into new readers. These feed the host-side input pipeline (the
device pipeline is io.DataLoader); they are pure-Python by design — the
TPU never sees a reader, only the batched arrays the pipeline emits.

Implemented (reference names + semantics):
  cache, map_readers, shuffle, chain, compose, buffered, firstn,
  xmap_readers, multiprocess_reader
"""
from __future__ import annotations

import itertools
import multiprocessing
import queue as _queue
import random
import threading

__all__ = [
    "cache", "map_readers", "buffered", "compose", "chain", "shuffle",
    "firstn", "xmap_readers", "multiprocess_reader", "batch",
]


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of batch_size (ref: python/paddle/batch.py
    — also exported as paddle.batch / fluid.io.batch)."""
    if batch_size <= 0:
        raise ValueError(f"batch_size must be positive, got {batch_size}")

    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


def cache(reader):
    """Cache the reader's full output in memory on the first pass (ref:
    decorator.py:51)."""
    all_data = tuple(reader())

    def cached_reader():
        return iter(all_data)

    return cached_reader


def map_readers(func, *readers):
    """Aligned map over several readers: yields func(*one_sample_each)
    (ref: decorator.py:91)."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle (ref: decorator.py:133): fill a buf_size window,
    shuffle it, emit; repeat."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """Concatenate readers back to back (ref: decorator.py:182)."""

    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """Zip readers into combined samples, flattening tuple outputs (ref:
    decorator.py:247). check_alignment=True raises ComposeNotAligned when
    readers run out at different lengths."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(map(make_tuple, outputs), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum(map(make_tuple, outputs), ())

    return reader


def buffered(reader, size):
    """Decouple producer/consumer with a `size`-deep queue fed by a
    background thread (ref: decorator.py:307)."""

    class _End:
        pass

    def data_reader():
        r = reader()
        q = _queue.Queue(maxsize=size)

        def feed():
            try:
                for d in r:
                    q.put(d)
            finally:
                q.put(_End)

        t = threading.Thread(target=feed, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e

    return data_reader


def firstn(reader, n):
    """First n samples (ref: decorator.py:366)."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map with `process_num` worker threads over a buffered
    queue; order=True restores input order (ref: decorator.py:411 — the
    reference also uses threads here, not processes)."""

    end = object()

    def xreader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)

        def feed():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, sample = item
                out_q.put((i, mapper(sample)))

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()

        finished = 0
        if not order:
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                yield item[1]
        else:
            pending = {}
            next_i = 0
            while finished < process_num or pending:
                if next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
                    continue
                if finished == process_num:
                    break
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                i, mapped = item
                pending[i] = mapped

    return xreader


def _mp_feed(reader_fn, q):
    try:
        for sample in reader_fn():
            q.put(sample)
    finally:
        q.put(None)


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Run each reader in its OWN process, merging their outputs through a
    shared queue (ref: decorator.py:504). Order across readers is
    arbitrary, like the reference. `use_pipe` is accepted for API parity;
    both modes use a multiprocessing.Queue here (the reference's pipe mode
    exists to dodge a CPython queue bug this runtime doesn't have)."""
    assert len(readers) > 0, "readers should not be empty"

    def reader():
        ctx = multiprocessing.get_context("fork")
        q = ctx.Queue(queue_size)
        procs = [ctx.Process(target=_mp_feed, args=(r, q), daemon=True)
                 for r in readers]
        for p in procs:
            p.start()
        finished = 0
        try:
            while finished < len(readers):
                sample = q.get()
                if sample is None:
                    finished += 1
                    continue
                yield sample
        finally:
            for p in procs:
                p.join(timeout=5)
                if p.is_alive():
                    p.terminate()

    return reader
