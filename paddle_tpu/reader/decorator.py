"""paddle.reader.decorator module path (ref: reader/decorator.py) — the
1.x reader combinators live in the package __init__; this module is the
import-path twin the reference also exposes."""
from . import (  # noqa: F401
    batch, buffered, cache, chain, compose, ComposeNotAligned, firstn,
    map_readers, multiprocess_reader, shuffle, xmap_readers,
)

__all__ = ["cache", "map_readers", "buffered", "shuffle", "chain",
           "ComposeNotAligned", "firstn", "xmap_readers",
           "multiprocess_reader", "compose"]
