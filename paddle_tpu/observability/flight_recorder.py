"""Flight recorder + stall watchdog (ISSUE 10 tentpole, part c).

A serving engine that slows down or wedges AFTER the fact is
undiagnosable from counters alone — counters say *that* throughput
dropped, not *which* request/dispatch/pool state it dropped on. The
flight recorder is a bounded, deterministic ring buffer of structured
engine events (admission, chunk plans, dispatch shapes, preemptions,
pool levels, compile events, exceptions) that costs one bool check per
event when disabled and whose `dump()` reconstructs the last N engine
decisions on demand.

Two triggers auto-dump it:

  * the **stall watchdog** — a daemon thread sampling an engine-owned
    progress counter; work pending with no dispatch progress past the
    threshold flips health to "stalled" and dumps the ring (the
    post-hoc record of WHAT the engine was doing when it stopped);
  * an **unhandled engine exception** — the engine's dispatch except
    paths record the error and dump before fanning it to futures.

Both the recorder and the watchdog are owned per-server (the ops plane
of `PagedGenerationServer(expose_port=...)` enables them); the classes
here are engine-agnostic and instantiable for tests. Events carry a
monotonic sequence number and `time.perf_counter()` timestamps, so a
dump is deterministic and totally ordered even across threads.
"""
from __future__ import annotations

import collections
import itertools
import json
import threading
import time

from . import log as _log
from . import metrics as _metrics

_logger = _log.get_logger(__name__)

DEFAULT_CAPACITY = 512

_m_stalls = _metrics.counter(
    "serving_stalls_total",
    "stall-watchdog trips: work pending with no dispatch progress past "
    "the threshold (health flips to 'stalled', flight recorder dumps)")
_m_dumps = _metrics.counter(
    "serving_flight_recorder_dumps_total",
    "flight-recorder auto-dumps, by what triggered them",
    labelnames=("trigger",))


class FlightRecorder:
    """Bounded ring buffer of structured engine events.

    enabled=False (the default) makes `record()` one attribute load and
    a bool branch — the engine hooks stay in place at zero cost, the
    telemetry convention of the whole observability package. The ops
    plane enables it; tests can pass enabled=True directly.
    """

    def __init__(self, capacity=DEFAULT_CAPACITY, enabled=False):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=self.capacity)
        self._seq = itertools.count()
        self._dumps = 0
        self.last_dump = None  # {"trigger", "ts", "events"} of the last
        # auto- or manual dump, kept for /statusz

    def enable(self):
        self.enabled = True

    def disable(self):
        self.enabled = False

    # -- recording -------------------------------------------------------
    def record(self, name, **attrs):
        """Append one event; no-op when disabled. `attrs` must be
        JSON-serializable (the engine passes ints/floats/strings)."""
        if not self.enabled:
            return
        ev = {"name": name, "ts": time.perf_counter()}
        ev.update(attrs)
        with self._lock:
            ev["seq"] = next(self._seq)
            self._ring.append(ev)

    # -- dumping ---------------------------------------------------------
    def events(self):
        """Snapshot of the ring, oldest first (bounded at capacity)."""
        with self._lock:
            return list(self._ring)

    def dump(self, trigger="manual", sink=None):
        """Snapshot the ring and remember it as `last_dump`. Auto-dump
        callers pass their trigger ("stall", "engine_exception"); the
        dump also goes to the library logger (one line per event would
        flood — the whole dump is one JSON blob) and to `sink(dump)`
        when given."""
        evs = self.events()
        d = {"trigger": trigger, "ts": time.perf_counter(),
             "events": evs, "n_events": len(evs)}
        with self._lock:
            self._dumps += 1
            self.last_dump = d
        _m_dumps.labels(trigger=trigger).inc()
        if trigger != "manual":
            _logger.error("flight recorder dump (%s): %s", trigger,
                          json.dumps(evs))
        if sink is not None:
            try:
                sink(d)
            except Exception:  # noqa: BLE001 — a sink must not cascade
                _logger.exception("flight recorder dump sink failed")
        return d

    def stats(self):
        with self._lock:
            return {"enabled": self.enabled, "capacity": self.capacity,
                    "events": len(self._ring), "dumps": self._dumps,
                    "last_dump_trigger": (self.last_dump or {}).get(
                        "trigger")}

    def clear(self):
        with self._lock:
            self._ring.clear()


class StallWatchdog:
    """Flags an engine that has pending work but makes no dispatch
    progress for longer than `timeout` seconds.

    progress_fn: returns a monotonically increasing int the engine bumps
        on every dispatch/admission (reads are lock-free — the GIL makes
        int loads atomic and staleness only delays detection one poll).
    pending_fn: returns True while the engine has work (busy slots or a
        non-empty queue) — an idle engine is never stalled.
    on_stall: called ONCE per stall episode (the flight-recorder
        auto-dump); exceptions are logged, never propagated.
    on_recover: called when progress resumes after a stall.
    """

    def __init__(self, progress_fn, pending_fn, timeout=30.0,
                 on_stall=None, on_recover=None, poll=None):
        self.timeout = float(timeout)
        if self.timeout <= 0:
            raise ValueError("timeout must be > 0")
        self._progress_fn = progress_fn
        self._pending_fn = pending_fn
        self._on_stall = on_stall
        self._on_recover = on_recover
        self.poll = poll if poll is not None else min(
            1.0, self.timeout / 4)
        self._stalled = False
        self._stalls = 0
        self._stop = None
        self._thread = None

    @property
    def stalled(self):
        return self._stalled

    @property
    def stalls(self):
        return self._stalls

    def start(self):
        if self._thread is not None:
            return self
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(self._stop,), daemon=True,
            name="paddle-tpu-stall-watchdog")
        self._thread.start()
        return self

    def stop(self):
        if self._stop is not None:
            self._stop.set()
        t, self._thread = self._thread, None
        if t is not None and t is not threading.current_thread():
            t.join(timeout=self.poll * 4)

    def _fire(self, cb):
        if cb is None:
            return
        try:
            cb()
        except Exception:  # noqa: BLE001 — watchdog must keep running
            _logger.exception("stall watchdog callback failed")

    def _run(self, stop):
        last_progress = self._progress_fn()
        last_change = time.monotonic()
        while not stop.wait(self.poll):
            try:
                progress = self._progress_fn()
                pending = self._pending_fn()
            except Exception:  # noqa: BLE001 — a dying engine must not
                continue  # kill its own diagnostics thread
            now = time.monotonic()
            if progress != last_progress or not pending:
                last_progress = progress
                last_change = now
                if self._stalled:
                    self._stalled = False
                    _logger.warning(
                        "stall watchdog: progress resumed after %d "
                        "stall(s)", self._stalls)
                    self._fire(self._on_recover)
                continue
            if not self._stalled and now - last_change > self.timeout:
                self._stalled = True
                self._stalls += 1
                _m_stalls.inc()
                _logger.error(
                    "stall watchdog: no dispatch progress for %.1fs "
                    "with work pending (threshold %.1fs)",
                    now - last_change, self.timeout)
                self._fire(self._on_stall)
