"""Causal trace propagation across the serving fleet (ISSUE 14
tentpole, part a).

The r7 tracer answers "where did this request's time go" for ONE
engine; a fleet request that is shed, retried, failed over, or
migrated leaves per-replica fragments with no causal story. The fix is
one tiny immutable context minted ONCE where the request enters the
system (`FrontDoor`/`FleetRouter`/`PagedGenerationServer.submit`) and
carried through every placement hop:

    TraceContext(trace_id, hop, cause)

  * trace_id — stable for the request's whole fleet lifetime; every
    event/span/flight-recorder entry/journal record it touches is
    stamped with it;
  * hop — a counter that increments each time the request is RE-ADMITTED
    somewhere (fault retry on the same engine, failover to a survivor,
    planned migration). Preempt/resume inside one residency stays in
    the same hop — that gap is already reported as `requeue_ms`;
  * cause — why this hop exists: `admit` (hop 0) | `retry` (r17
    recovery-ladder requeue) | `failover` (r18 replica death) |
    `migration` (planned live migration).

The context crosses process/replica boundaries as three plain fields
inside the journal-shape session entry (`SessionJournal.entry_for`), so
replica takeover and migration carry it for free.

`assemble_causal_traces` folds a stamped event stream back into ONE
causal tree per trace: root = the request's fleet lifetime, children =
hops (each on its replica, with its cause), grandchildren = the hop's
contiguous phases (queue_wait / admission / prefill / decode /
detokenize) which tile the hop's wall-clock exactly; the requeue gaps
BETWEEN hops appear as explicit `requeue` spans, so hop spans + gap
spans tile the root exactly too. Every span node carries
`replica` / `hop` / `cause` attributes, and a hop created by failover
or migration is linked to its source via `from_replica`.
"""
from __future__ import annotations

import itertools
import os
import threading

from . import tracing as _tracing

CAUSES = ("admit", "retry", "failover", "migration")

_mint_lock = threading.Lock()
_mint_counter = itertools.count()
# per-process salt: trace ids stay unique across the processes whose
# JSONL sinks might later be merged (subprocess replicas, bench runs)
_SALT = f"{os.getpid():05x}{int.from_bytes(os.urandom(3), 'big'):06x}"


class TraceContext:
    """Immutable (trace_id, hop, cause) triple. `child(cause)` is the
    ONLY way to advance it — hop bumps by one and the cause records why
    the request moved."""

    __slots__ = ("trace_id", "hop", "cause")

    def __init__(self, trace_id, hop=0, cause="admit"):
        if cause not in CAUSES:
            raise ValueError(f"unknown hop cause {cause!r} "
                             f"(causes: {CAUSES})")
        if int(hop) < 0:
            raise ValueError(f"hop must be >= 0, got {hop}")
        object.__setattr__(self, "trace_id", str(trace_id))
        object.__setattr__(self, "hop", int(hop))
        object.__setattr__(self, "cause", cause)

    def __setattr__(self, name, value):  # immutability guard
        raise AttributeError("TraceContext is immutable; use child()")

    def __repr__(self):
        return (f"TraceContext({self.trace_id!r}, hop={self.hop}, "
                f"cause={self.cause!r})")

    def __eq__(self, other):
        return (isinstance(other, TraceContext)
                and (self.trace_id, self.hop, self.cause)
                == (other.trace_id, other.hop, other.cause))

    def __hash__(self):
        return hash((self.trace_id, self.hop, self.cause))

    @classmethod
    def mint(cls):
        """A fresh hop-0 context (cause `admit`)."""
        with _mint_lock:
            n = next(_mint_counter)
        return cls(f"t{_SALT}{n:x}")

    def child(self, cause):
        """The next hop: same trace, hop+1, the given cause."""
        return TraceContext(self.trace_id, self.hop + 1, cause)

    def attrs(self, replica=None):
        """The stamping dict events/spans/ring entries carry."""
        d = {"trace_id": self.trace_id, "hop": self.hop,
             "cause": self.cause}
        if replica is not None:
            d["replica"] = replica
        return d

    def to_dict(self):
        return {"trace_id": self.trace_id, "hop": self.hop,
                "cause": self.cause}

    @classmethod
    def from_dict(cls, d):
        if d is None:
            return None
        return cls(d["trace_id"], d.get("hop", 0),
                   d.get("cause", "admit"))


# ---- causal trace assembly ---------------------------------------------

def _span(name, ts, dur, **attrs):
    node = {"name": name, "ts": ts, "dur": max(0.0, dur)}
    node.update(attrs)
    return node


def _hop_node(hop_no, evs, clip_end=None):
    """One hop's span node: phases tile [hop start, hop end] exactly
    (the r7 clamping discipline, applied per hop). `evs` is the hop's
    time-sorted stamped events. `clip_end` truncates the hop at the
    NEXT hop's start: a killed replica's in-flight dispatch can finish
    (and emit) after the router already failed the session over — the
    request's causal lifetime transfers at takeover, so the zombie
    tail is reported as `overlap_ms` instead of stretching the hop."""
    def end_of(ev):
        return ev["ts"] + ev.get("dur", 0.0)

    t0 = min(ev["ts"] for ev in evs)
    t1_raw = max(end_of(ev) for ev in evs)
    t1 = t1_raw
    if clip_end is not None:
        t1 = max(t0, min(t1, clip_end))
    replica = next((ev["replica"] for ev in evs if "replica" in ev),
                   None)
    cause = next((ev["cause"] for ev in evs if "cause" in ev), "admit")
    by_name = {}
    for ev in evs:
        by_name.setdefault(ev["name"], ev)  # first occurrence wins
    t_admit = by_name.get("request_admitted", {}).get("ts", t0)
    pre = by_name.get("prefill")
    t_pre0 = pre["ts"] if pre is not None else t_admit
    t_first = end_of(pre) if pre is not None else t_pre0
    done = by_name.get("request_done")
    t_done = done["ts"] if done is not None else t1
    det = by_name.get("detokenize")
    t_end = end_of(det) if det is not None else t_done
    # clamp to monotonic order inside [t0, t1] — a missing event's
    # phase collapses to zero instead of going negative
    t_admit = min(max(t_admit, t0), t1)
    t_pre0 = min(max(t_pre0, t_admit), t1)
    t_first = min(max(t_first, t_pre0), t1)
    t_done = min(max(t_done, t_first), t1)
    t_end = min(max(t_end, t_done), t1)
    tail = t1 - t_end  # events after the terminal record (none in a
    # finished hop; an interrupted hop ends at its last sighting)
    attrs = {"replica": replica, "hop": hop_no, "cause": cause}
    phases = [
        _span("queue_wait", t0, t_admit - t0, **attrs),
        _span("admission", t_admit, t_pre0 - t_admit, **attrs),
        _span("prefill", t_pre0, t_first - t_pre0, **attrs),
        _span("decode", t_first, t_done - t_first + tail, **attrs),
        _span("detokenize", t_done + tail, t_end - t_done, **attrs),
    ]
    node = _span("hop", t0, t1 - t0, **attrs)
    node["children"] = phases
    node["complete"] = done is not None
    node["events"] = [ev["name"] for ev in evs]
    if t1 < t1_raw:
        node["overlap_ms"] = round((t1_raw - t1) * 1e3, 4)
    if "migrate_out" in by_name:
        node["migrated_out"] = True
    return node


def assemble_causal_traces(evs=None, path=None):
    """Fold a stamped event stream into one causal tree per trace_id.

    Returns {trace_id: record} where record["tree"] is the nested span
    tree (root -> hop/requeue spans -> phase leaves; every span node
    carries `replica`/`hop`/`cause`), record["hops"] is the flat hop
    list, and the tiling invariants hold exactly:

        sum(phase durs of a hop)          == the hop's dur
        sum(hop durs) + sum(requeue durs) == record["wall_ms"] / 1e3

    A hop whose cause is `failover` or `migration` carries
    `from_replica` — the replica the request left. Events without a
    `trace_id` stamp (pre-r19 streams, batch dispatch spans) are
    ignored here; the per-engine `assemble_request_traces` still reads
    them.
    """
    if evs is None:
        if path is None:
            evs = _tracing.events()
        else:
            evs = _tracing.load_events(path)
    traces: dict[str, list] = {}
    for ev in evs:
        tid = ev.get("trace_id")
        if tid is not None and "ts" in ev:
            traces.setdefault(tid, []).append(ev)
    out = {}
    for tid, events in traces.items():
        events.sort(key=lambda e: (e["ts"], e.get("id", 0)))
        hops: dict[int, list] = {}
        rid = None
        for ev in events:
            hops.setdefault(int(ev.get("hop", 0)), []).append(ev)
            if rid is None:
                rid = ev.get("request_id")
        order = sorted(hops)
        starts = [min(ev["ts"] for ev in hops[h]) for h in order]
        nodes = [_hop_node(h, hops[h],
                           clip_end=(starts[k + 1]
                                     if k + 1 < len(order) else None))
                 for k, h in enumerate(order)]
        children = []
        requeue_ms = 0.0
        for prev, nxt in zip(nodes, nodes[1:]):
            if nxt["cause"] in ("failover", "migration"):
                nxt["from_replica"] = prev["replica"]
        for k, node in enumerate(nodes):
            if k > 0:
                prev = nodes[k - 1]
                gap_t0 = prev["ts"] + prev["dur"]
                gap = node["ts"] - gap_t0
                requeue_ms += max(0.0, gap) * 1e3
                children.append(_span(
                    "requeue", gap_t0, gap, hop=node["hop"],
                    cause=node["cause"], replica=node["replica"]))
            children.append(node)
        t0 = nodes[0]["ts"]
        t1 = nodes[-1]["ts"] + nodes[-1]["dur"]
        root = _span("request", t0, t1 - t0, trace_id=tid,
                     request_id=rid, replica=nodes[0]["replica"],
                     hop=0, cause=nodes[0]["cause"])
        root["children"] = children
        out[tid] = {
            "trace_id": tid,
            "request_id": rid,
            "tree": root,
            "hops": nodes,
            "n_hops": len(nodes),
            "replicas": [n["replica"] for n in nodes],
            "causes": [n["cause"] for n in nodes],
            "complete": nodes[-1]["complete"],
            "wall_ms": round((t1 - t0) * 1e3, 4),
            "requeue_ms": round(requeue_ms, 4),
        }
    return out


def check_tiling(record, tol_ms=0.05):
    """Assert-helper: the record's spans tile wall-clock exactly (up to
    float rounding). Returns the worst absolute error in ms."""
    worst = 0.0
    for hop in record["hops"]:
        s = sum(c["dur"] for c in hop["children"])
        worst = max(worst, abs(s - hop["dur"]) * 1e3)
    total = sum(c["dur"] for c in record["tree"]["children"])
    worst = max(worst, abs(total * 1e3 - record["wall_ms"]))
    return worst
