"""Library logger with env-var verbosity (ISSUE 2 satellite).

Library code must not `print()` (enforced by scripts/check_no_print.py):
diagnostics go through `paddle_tpu.observability.log.get_logger`, whose
verbosity is controlled by the PADDLE_TPU_LOG_LEVEL environment variable
(debug | info | warning | error, or a numeric logging level; default
info so existing user-visible diagnostics keep appearing). Messages go
to stderr so they never pollute machine-parsed stdout (bench JSON
lines).

    from paddle_tpu.observability import log
    logger = log.get_logger(__name__)
    logger.info("trace written to %s", path)
"""
from __future__ import annotations

import logging
import os
import sys

ENV_LEVEL = "PADDLE_TPU_LOG_LEVEL"
_ROOT = "paddle_tpu"
_configured = False


def _level_from_env(default=logging.INFO):
    raw = os.environ.get(ENV_LEVEL, "").strip()
    if not raw:
        return default
    if raw.isdigit():
        return int(raw)
    return {
        "debug": logging.DEBUG, "info": logging.INFO,
        "warning": logging.WARNING, "warn": logging.WARNING,
        "error": logging.ERROR, "critical": logging.CRITICAL,
        "off": logging.CRITICAL + 10, "none": logging.CRITICAL + 10,
    }.get(raw.lower(), default)


def _configure_root():
    global _configured
    root = logging.getLogger(_ROOT)
    if _configured:
        return root
    root.setLevel(_level_from_env())
    root.propagate = False  # the app's root logger must not double-print
    if not root.handlers:
        h = logging.StreamHandler(stream=sys.stderr)
        h.setFormatter(logging.Formatter("%(message)s"))
        root.addHandler(h)
    _configured = True
    return root


def get_logger(name=None):
    """A logger under the `paddle_tpu` root (configured once: stderr
    handler, level from PADDLE_TPU_LOG_LEVEL). `name` may be a module
    __name__ — anything outside the paddle_tpu.* namespace is nested
    under it so the root handler/level always applies."""
    _configure_root()
    if not name or name == _ROOT:
        return logging.getLogger(_ROOT)
    if not name.startswith(_ROOT + "."):
        name = f"{_ROOT}.{name}"
    return logging.getLogger(name)


def set_level(level):
    """Programmatic override of the env-var verbosity (accepts logging
    constants or the same strings as PADDLE_TPU_LOG_LEVEL)."""
    if isinstance(level, str):
        os.environ[ENV_LEVEL] = level
        level = _level_from_env()
    _configure_root().setLevel(level)


logger = get_logger()
