"""Declarative SLOs with multi-window burn rates (ISSUE 14 tentpole,
part b).

The r7 registry's fixed-bucket histograms are CUMULATIVE — they answer
"what was the TTFT distribution since reset", never "are we meeting the
latency objective RIGHT NOW". This module adds the missing production
layer:

  * `SLO` — one declarative objective: "`target` fraction of
    `objective` events must be good over `window_s`", where an event is
    good by latency threshold (ttft / itl under `threshold_s`) or by
    outcome (availability: the request finished; goodput: the decoded
    token reached a client). Scope by `lane` / `tenant` / `replica`
    (None = match everything — the fleet-wide objective).
  * sliding-window reservoirs — each SLO accumulates good/bad counts in
    coarse time buckets pruned past the window, so observation is O(1)
    and memory is O(window / bucket), never O(events).
  * multi-window burn rates — burn = (bad fraction) / (1 - target),
    i.e. how many times faster than budget the error budget is being
    spent. Evaluated over a FAST window (default window/12) and the
    SLOW window; state is

        page   burn >= page_burn on BOTH windows (the sustained-AND
               discipline of multiwindow burn alerts: the fast window
               proves it is still happening, the slow one that enough
               budget actually burned)
        warn   burn >= warn_burn on both windows
        ok     otherwise (including "not enough data": fewer than
               min_events in the slow window never alarms)

  * exports — `slo_burn_rate{slo,window}`, `slo_error_budget_remaining
    {slo}` and `slo_state{slo}` gauges on every `evaluate()`, plus the
    JSON report the `/slo` ops endpoint serves.
  * degrade hook — `paging(now, sustain_s)` names the SLOs that have
    been in `page` continuously for `sustain_s`; the fleet router feeds
    replica-scoped sustained pages into the r18 replica state machine
    (`ReplicaHealth.note_not_ready`) so a latency-burning replica stops
    taking new placements.

All clocks are explicit (`now=` everywhere, `time.monotonic()` by
default) so the state machine is deterministic and unit-testable
without sleeping — the r18 health-machine discipline.
"""
from __future__ import annotations

import collections
import math
import threading
import time

from . import metrics as _metrics

OBJECTIVES = ("ttft", "itl", "availability", "goodput")
LATENCY_OBJECTIVES = ("ttft", "itl")
STATES = ("ok", "warn", "page")
STATE_CODES = {"ok": 0.0, "warn": 1.0, "page": 2.0}

_m_burn = _metrics.gauge(
    "slo_burn_rate",
    "error-budget burn rate per SLO and evaluation window (1.0 = "
    "spending exactly the budget; page/warn thresholds are per-SLO "
    "config)", labelnames=("slo", "window"))
_m_budget = _metrics.gauge(
    "slo_error_budget_remaining",
    "fraction of the SLO's error budget left over its slow window "
    "(1 - burn; negative = budget overspent)", labelnames=("slo",))
_m_state = _metrics.gauge(
    "slo_state",
    "SLO burn state: 0 ok, 1 warn, 2 page", labelnames=("slo",))


class SLO:
    """One declarative objective.

    objective: `ttft` | `itl` (latency: good = value <= threshold_s) or
        `availability` | `goodput` (outcome: good/bad fed directly).
    target: required good fraction over the window, in (0, 1)
        (e.g. 0.99 = "99% of first tokens under the threshold"). The
        error budget is 1 - target.
    threshold_s: the latency bound (required for ttft/itl, forbidden
        otherwise).
    window_s: the slow evaluation window. fast_window_s defaults to
        window_s / 12 (the classic 5m-of-1h ratio).
    lane / tenant / replica: scope filters; None matches every
        observation (the fleet-/server-wide objective).
    warn_burn / page_burn: burn-rate thresholds (both windows must
        cross — see module docstring).
    min_events: fewer observations than this in the slow window never
        alarms (cold start / idle server).
    """

    __slots__ = ("name", "objective", "target", "threshold_s",
                 "window_s", "fast_window_s", "lane", "tenant",
                 "replica", "warn_burn", "page_burn", "min_events")

    def __init__(self, objective, target, *, threshold_s=None,
                 window_s=300.0, fast_window_s=None, name=None,
                 lane=None, tenant=None, replica=None, warn_burn=2.0,
                 page_burn=10.0, min_events=10):
        if objective not in OBJECTIVES:
            raise ValueError(f"unknown objective {objective!r} "
                             f"(objectives: {OBJECTIVES})")
        if not 0.0 < float(target) < 1.0:
            raise ValueError(f"target must be in (0, 1), got {target}")
        if objective in LATENCY_OBJECTIVES:
            if threshold_s is None or float(threshold_s) <= 0:
                raise ValueError(
                    f"objective {objective!r} needs threshold_s > 0, "
                    f"got {threshold_s}")
        elif threshold_s is not None:
            raise ValueError(f"objective {objective!r} takes no "
                             f"threshold_s (got {threshold_s})")
        if float(window_s) <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        if fast_window_s is None:
            fast_window_s = float(window_s) / 12.0
        if not 0 < float(fast_window_s) <= float(window_s):
            raise ValueError(
                f"fast_window_s must be in (0, window_s], "
                f"got {fast_window_s}")
        if float(warn_burn) <= 0 or float(page_burn) < float(warn_burn):
            raise ValueError(
                f"need 0 < warn_burn <= page_burn, got "
                f"warn_burn={warn_burn} page_burn={page_burn}")
        if int(min_events) < 1:
            raise ValueError(f"min_events must be >= 1, "
                             f"got {min_events}")
        self.objective = objective
        self.target = float(target)
        self.threshold_s = (None if threshold_s is None
                            else float(threshold_s))
        self.window_s = float(window_s)
        self.fast_window_s = float(fast_window_s)
        self.lane = lane
        self.tenant = tenant
        self.replica = replica
        self.warn_burn = float(warn_burn)
        self.page_burn = float(page_burn)
        self.min_events = int(min_events)
        if name is None:
            scope = "/".join(str(s) for s in (lane, tenant, replica)
                             if s is not None) or "all"
            thr = (f"<{self.threshold_s * 1e3:g}ms"
                   if self.threshold_s is not None else "")
            name = f"{objective}{thr}@{self.target:g}[{scope}]"
        self.name = str(name)

    @property
    def budget(self):
        return 1.0 - self.target

    def matches(self, lane=None, tenant=None, replica=None):
        return ((self.lane is None or self.lane == lane)
                and (self.tenant is None or self.tenant == tenant)
                and (self.replica is None or self.replica == replica))

    def describe(self):
        return {
            "name": self.name, "objective": self.objective,
            "target": self.target, "threshold_s": self.threshold_s,
            "window_s": self.window_s,
            "fast_window_s": self.fast_window_s,
            "lane": self.lane, "tenant": self.tenant,
            "replica": self.replica, "warn_burn": self.warn_burn,
            "page_burn": self.page_burn,
        }


def default_slos():
    """A reasonable server-wide starter set (`slos=True`): interactive
    TTFT, steady ITL, availability, goodput."""
    return [
        SLO("ttft", 0.99, threshold_s=2.0, window_s=300.0,
            name="ttft_p99_2s"),
        SLO("itl", 0.99, threshold_s=0.5, window_s=300.0,
            name="itl_p99_500ms"),
        SLO("availability", 0.999, window_s=300.0,
            name="availability_999"),
        SLO("goodput", 0.90, window_s=300.0, name="goodput_90"),
    ]


class _BucketWindow:
    """Good/bad counts in coarse time buckets, pruned past window_s —
    the sliding-window reservoir behind one SLO."""

    __slots__ = ("window_s", "bucket_s", "_buckets")

    def __init__(self, window_s, fast_window_s):
        self.window_s = float(window_s)
        # fast-window reads need several buckets of resolution
        self.bucket_s = max(float(fast_window_s) / 6.0, 0.01)
        self._buckets = collections.deque()  # [bucket_idx, good, bad]

    def add(self, now, good, n=1):
        b = math.floor(now / self.bucket_s)
        if self._buckets and self._buckets[-1][0] == b:
            rec = self._buckets[-1]
        else:
            self._prune(now)
            rec = [b, 0, 0]
            self._buckets.append(rec)
        rec[1 if good else 2] += int(n)

    def _prune(self, now):
        cutoff = math.floor((now - self.window_s) / self.bucket_s)
        while self._buckets and self._buckets[0][0] <= cutoff:
            self._buckets.popleft()

    def counts(self, now, horizon_s):
        """(good, bad) over the trailing horizon_s."""
        self._prune(now)
        cutoff = math.floor((now - horizon_s) / self.bucket_s)
        g = b = 0
        for idx, good, bad in self._buckets:
            if idx > cutoff:
                g += good
                b += bad
        return g, b


class SLOEngine:
    """Evaluates a set of SLOs over a live observation stream.

    slos: iterable of `SLO` (or True for `default_slos()`).
    Thread-safe; every method takes an explicit `now=` (monotonic
    seconds) for determinism, defaulting to time.monotonic().
    """

    def __init__(self, slos=True):
        if slos is True:
            slos = default_slos()
        slos = list(slos)
        if not slos:
            raise ValueError("SLOEngine needs >= 1 SLO")
        names = []
        for s in slos:
            if not isinstance(s, SLO):
                raise TypeError(f"slos must be SLO instances, "
                                f"got {type(s).__name__}")
            names.append(s.name)
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.slos = slos
        self._lock = threading.Lock()
        self._win = {s.name: _BucketWindow(s.window_s, s.fast_window_s)
                     for s in slos}
        self._page_since: dict[str, float] = {}
        self._last_eval: list | None = None

    # ---- observation ---------------------------------------------------
    def observe(self, objective, *, value_s=None, good=None, n=1,
                now=None, lane=None, tenant=None, replica=None):
        """Feed one (or `n` identical) observations. Latency
        objectives take `value_s` (good = under each matching SLO's
        threshold); outcome objectives take `good=`."""
        if objective not in OBJECTIVES:
            raise ValueError(f"unknown objective {objective!r}")
        if now is None:
            now = time.monotonic()
        with self._lock:
            for s in self.slos:
                if s.objective != objective:
                    continue
                if not s.matches(lane=lane, tenant=tenant,
                                 replica=replica):
                    continue
                if s.threshold_s is not None:
                    if value_s is None:
                        raise ValueError(
                            f"objective {objective!r} needs value_s")
                    ok = float(value_s) <= s.threshold_s
                else:
                    if good is None:
                        raise ValueError(
                            f"objective {objective!r} needs good=")
                    ok = bool(good)
                self._win[s.name].add(now, ok, n)

    def observe_counts(self, objective, good_n, bad_n, *, now=None,
                       lane=None, tenant=None, replica=None):
        """Bulk outcome feed (goodput deltas per engine round)."""
        if good_n:
            self.observe(objective, good=True, n=good_n, now=now,
                         lane=lane, tenant=tenant, replica=replica)
        if bad_n:
            self.observe(objective, good=False, n=bad_n, now=now,
                         lane=lane, tenant=tenant, replica=replica)

    # ---- evaluation ----------------------------------------------------
    def evaluate(self, now=None):
        """Evaluate every SLO now; updates the slo_* gauges and the
        page-since timestamps, returns the per-SLO report list."""
        if now is None:
            now = time.monotonic()
        out = []
        with self._lock:
            for s in self.slos:
                win = self._win[s.name]
                fg, fb = win.counts(now, s.fast_window_s)
                sg, sb = win.counts(now, s.window_s)
                fast_n, slow_n = fg + fb, sg + sb
                burn_fast = ((fb / fast_n) / s.budget) if fast_n else 0.0
                burn_slow = ((sb / slow_n) / s.budget) if slow_n else 0.0
                if slow_n < s.min_events:
                    state = "ok"
                elif (burn_fast >= s.page_burn
                        and burn_slow >= s.page_burn):
                    state = "page"
                elif (burn_fast >= s.warn_burn
                        and burn_slow >= s.warn_burn):
                    state = "warn"
                else:
                    state = "ok"
                if state == "page":
                    self._page_since.setdefault(s.name, now)
                else:
                    self._page_since.pop(s.name, None)
                budget_remaining = 1.0 - burn_slow
                rec = {
                    "name": s.name,
                    "objective": s.objective,
                    "target": s.target,
                    "threshold_s": s.threshold_s,
                    "state": state,
                    "burn_fast": round(burn_fast, 4),
                    "burn_slow": round(burn_slow, 4),
                    "budget_remaining": round(budget_remaining, 4),
                    "events_fast": fast_n,
                    "events_slow": slow_n,
                    "bad_slow": sb,
                    "page_for_s": (round(now - self._page_since[s.name],
                                         3)
                                   if s.name in self._page_since
                                   else 0.0),
                    "scope": {"lane": s.lane, "tenant": s.tenant,
                              "replica": s.replica},
                }
                out.append(rec)
                _m_burn.labels(slo=s.name, window="fast").set(burn_fast)
                _m_burn.labels(slo=s.name, window="slow").set(burn_slow)
                _m_budget.labels(slo=s.name).set(budget_remaining)
                _m_state.labels(slo=s.name).set(STATE_CODES[state])
            self._last_eval = out
        return out

    def state(self, name, now=None):
        """One SLO's current state string."""
        for rec in self.evaluate(now):
            if rec["name"] == name:
                return rec["state"]
        raise KeyError(f"unknown SLO {name!r}")

    def worst_state(self, now=None):
        order = {s: i for i, s in enumerate(STATES)}
        return max((r["state"] for r in self.evaluate(now)),
                   key=order.__getitem__, default="ok")

    def paging(self, now=None, sustain_s=0.0):
        """Names of SLOs in `page` continuously for >= sustain_s — the
        replica-degrade hook the fleet router polls."""
        if now is None:
            now = time.monotonic()
        self.evaluate(now)
        with self._lock:
            return {name for name, t0 in self._page_since.items()
                    if now - t0 >= float(sustain_s)}

    def report(self, now=None):
        """The JSON document the /slo ops endpoint serves."""
        slos = self.evaluate(now)
        return {"slos": slos,
                "worst": max((r["state"] for r in slos),
                             key=lambda s: STATE_CODES[s],
                             default="ok"),
                "paging": sorted(self._page_since)}
