"""Process-wide metrics registry (ISSUE 2 tentpole, part 1).

Counters, gauges, and fixed-bucket histograms with labels, answering
"what is the pool fill right now" for any instrumented subsystem from
one place. Reference direction: the production-visibility layer the
paper's framework gets from its Fleet/profiler stack (TensorFlow,
arXiv:1605.08695) and every serving engine's /metrics endpoint.

Design constraints:

  * near-zero cost when disabled — every mutator checks ONE bool before
    doing any work, so instrumented hot loops (the decode step, the
    admission path) pay an attribute load + branch and nothing else;
  * process-wide default registry, but `Registry` is instantiable for
    tests and embedded use;
  * two exporters: `to_prometheus()` (text exposition format, ready for
    a scrape endpoint or a file snapshot) and `snapshot()` (plain JSON
    dict for bench records and assertions).

Enable with PADDLE_TPU_TELEMETRY=1 in the environment or
`metrics.enable()` at runtime; both the registry and the tracer
(tracing.py) honor the same env var.

    from paddle_tpu.observability import metrics
    reqs = metrics.counter("serving_requests_total",
                           "requests completed", labelnames=("server",))
    reqs.labels(server="paged").inc()
    depth = metrics.gauge("serving_queue_depth", "pending requests")
    depth.set(len(queue))
    h = metrics.histogram("ttft_seconds", "time to first token",
                          buckets=(.01, .05, .1, .5, 1, 5))
    h.observe(0.093)
    print(metrics.to_prometheus())
"""
from __future__ import annotations

import json
import math
import os
import threading

ENV_ENABLE = "PADDLE_TPU_TELEMETRY"

# Prometheus' default latency buckets (seconds) — a sane default for the
# step-time/TTFT histograms this registry mostly holds.
DEFAULT_BUCKETS = (.005, .01, .025, .05, .1, .25, .5, 1.0, 2.5, 5.0, 10.0)


def _check_labels(labelnames, labels):
    if tuple(sorted(labels)) != tuple(sorted(labelnames)):
        raise ValueError(f"labels {sorted(labels)} != declared "
                         f"{sorted(labelnames)}")


class _Child:
    """One labeled series of a metric. Mutators no-op when the owning
    registry is disabled."""

    __slots__ = ("_m", "_key", "value", "_sum", "_count", "_bucket_counts")

    def __init__(self, metric, key):
        self._m = metric
        self._key = key
        self.value = 0.0
        if metric.kind == "histogram":
            self._sum = 0.0
            self._count = 0
            self._bucket_counts = [0] * (len(metric.buckets) + 1)

    # -- counter / gauge -------------------------------------------------
    def inc(self, amount=1.0):
        m = self._m
        if not m._reg.enabled:
            return
        if m.kind == "counter" and amount < 0:
            raise ValueError("counters can only increase")
        with m._lock:
            self.value += amount

    def dec(self, amount=1.0):
        m = self._m
        if m.kind != "gauge":
            raise TypeError(f"dec() on a {m.kind}")
        if not m._reg.enabled:
            return
        with m._lock:
            self.value -= amount

    def set(self, value):
        m = self._m
        if m.kind != "gauge":
            raise TypeError(f"set() on a {m.kind}")
        if not m._reg.enabled:
            return
        with m._lock:
            self.value = float(value)

    # -- histogram -------------------------------------------------------
    def observe(self, value):
        m = self._m
        if m.kind != "histogram":
            raise TypeError(f"observe() on a {m.kind}")
        if not m._reg.enabled:
            return
        value = float(value)
        with m._lock:
            self._sum += value
            self._count += 1
            for i, ub in enumerate(m.buckets):
                if value <= ub:
                    self._bucket_counts[i] += 1
                    break
            else:
                self._bucket_counts[-1] += 1  # +Inf bucket

    def percentile(self, p):
        """Histogram-estimated p-quantile (0..1): linear interpolation
        inside the bucket holding the target rank (the +Inf bucket
        answers with the last finite bound). 0.0 when empty."""
        m = self._m
        if m.kind != "histogram":
            raise TypeError(f"percentile() on a {m.kind}")
        with m._lock:
            total = self._count
            if not total:
                return 0.0
            rank = p * total
            seen = 0
            lo = 0.0
            for i, ub in enumerate(m.buckets):
                n = self._bucket_counts[i]
                if seen + n >= rank and n:
                    frac = (rank - seen) / n
                    return lo + (ub - lo) * min(max(frac, 0.0), 1.0)
                seen += n
                lo = ub
            return m.buckets[-1] if m.buckets else 0.0


class Metric:
    """One named metric family; `labels(**kv)` returns the per-series
    child (the unlabeled family IS the child keyed by ())."""

    def __init__(self, registry, name, help_, kind, labelnames=(),
                 buckets=None):
        self._reg = registry
        self.name = name
        self.help = help_
        self.kind = kind
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: dict[tuple, _Child] = {}
        if kind == "histogram":
            bs = tuple(float(b) for b in (buckets or DEFAULT_BUCKETS))
            if list(bs) != sorted(bs) or len(set(bs)) != len(bs):
                raise ValueError(f"histogram buckets must be strictly "
                                 f"increasing, got {bs}")
            self.buckets = bs
        if not self.labelnames:  # pre-bind the unlabeled series
            self._default = self._child(())
        else:
            self._default = None

    def _child(self, key):
        with self._lock:
            c = self._children.get(key)
            if c is None:
                c = self._children[key] = _Child(self, key)
            return c

    def labels(self, **labels):
        _check_labels(self.labelnames, labels)
        return self._child(tuple(labels[k] for k in self.labelnames))

    def _only(self):
        if self._default is None:
            raise ValueError(f"metric {self.name} has labels "
                             f"{self.labelnames}; use .labels(...)")
        return self._default

    # unlabeled convenience surface
    def inc(self, amount=1.0):
        self._only().inc(amount)

    def dec(self, amount=1.0):
        self._only().dec(amount)

    def set(self, value):
        self._only().set(value)

    def observe(self, value):
        self._only().observe(value)

    def percentile(self, p):
        return self._only().percentile(p)

    @property
    def value(self):
        return self._only().value


class Registry:
    """Name -> Metric map with get-or-create semantics: registering the
    same name twice returns the SAME metric (kind/labelnames must
    match), so any module can declare its metrics at import time without
    coordination."""

    def __init__(self, enabled=None):
        self._lock = threading.Lock()
        self._metrics: dict[str, Metric] = {}
        self._gauge_fns: dict[str, object] = {}
        if enabled is None:
            enabled = os.environ.get(ENV_ENABLE, "0") not in ("", "0",
                                                              "false")
        self.enabled = bool(enabled)

    def enable(self):
        self.enabled = True

    def disable(self):
        self.enabled = False

    # -- declaration -----------------------------------------------------
    def _register(self, name, help_, kind, labelnames, buckets=None):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.kind != kind or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}"
                        f"{m.labelnames}, not {kind}{tuple(labelnames)}")
                return m
            m = Metric(self, name, help_, kind, labelnames, buckets)
            self._metrics[name] = m
            return m

    def counter(self, name, help_="", labelnames=()):
        return self._register(name, help_, "counter", labelnames)

    def gauge(self, name, help_="", labelnames=()):
        return self._register(name, help_, "gauge", labelnames)

    def histogram(self, name, help_="", labelnames=(), buckets=None):
        return self._register(name, help_, "histogram", labelnames,
                              buckets)

    def gauge_fn(self, name, help_, fn):
        """A gauge whose value is pulled from `fn()` at export time —
        for state someone else owns (heartbeat age, pool fill) where a
        push on every change would be invasive."""
        g = self.gauge(name, help_)
        with self._lock:
            self._gauge_fns[name] = fn
        return g

    # -- export ----------------------------------------------------------
    def _pull_gauges(self):
        for name, fn in list(self._gauge_fns.items()):
            try:
                v = float(fn())
            except Exception:  # noqa: BLE001 — a dead provider must not
                continue  # poison the whole export
            m = self._metrics[name]
            c = m._default if m._default is not None else None
            if c is not None:
                with m._lock:
                    c.value = v

    def snapshot(self):
        """JSON-ready dict of every series' current value."""
        self._pull_gauges()
        out = {}
        for name, m in sorted(self._metrics.items()):
            series = []
            with m._lock:
                children = list(m._children.items())
            for key, c in children:
                lbl = dict(zip(m.labelnames, key))
                if m.kind == "histogram":
                    series.append({
                        "labels": lbl, "sum": c._sum, "count": c._count,
                        "buckets": {
                            **{str(ub): n for ub, n in
                               zip(m.buckets, c._bucket_counts)},
                            "+Inf": c._bucket_counts[-1]},
                    })
                else:
                    series.append({"labels": lbl, "value": c.value})
            out[name] = {"kind": m.kind, "help": m.help, "series": series}
        return out

    def to_json(self, **dump_kw):
        return json.dumps(self.snapshot(), **dump_kw)

    def to_prometheus(self):
        """Prometheus text exposition format (histograms as cumulative
        _bucket/_sum/_count, the standard scrape shape)."""
        self._pull_gauges()
        lines = []
        for name, m in sorted(self._metrics.items()):
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            with m._lock:
                children = list(m._children.items())
            for key, c in children:
                base = _fmt_labels(m.labelnames, key)
                if m.kind == "histogram":
                    cum = 0
                    for ub, n in zip(m.buckets, c._bucket_counts):
                        cum += n
                        lines.append(
                            f"{name}_bucket"
                            f"{_fmt_labels(m.labelnames, key, le=_le(ub))}"
                            f" {cum}")
                    cum += c._bucket_counts[-1]
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(m.labelnames, key, le='+Inf')}"
                        f" {cum}")
                    lines.append(f"{name}_sum{base} {_num(c._sum)}")
                    lines.append(f"{name}_count{base} {c._count}")
                else:
                    lines.append(f"{name}{base} {_num(c.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self):
        """Zero every series (definitions and gauge providers stay
        registered) — bench measurement windows."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            with m._lock:
                for c in m._children.values():
                    c.value = 0.0
                    if m.kind == "histogram":
                        c._sum = 0.0
                        c._count = 0
                        c._bucket_counts = [0] * (len(m.buckets) + 1)

    def clear(self):
        """Drop every metric definition (tests)."""
        with self._lock:
            self._metrics.clear()
            self._gauge_fns.clear()


def _le(ub):
    return _num(ub)


def _num(v):
    f = float(v)
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    if f != f:
        return "NaN"
    if f == math.floor(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_labels(names, values, **extra):
    pairs = [*zip(names, values), *extra.items()]
    if not pairs:
        return ""
    body = ",".join(f'{k}="{_escape(str(v))}"' for k, v in pairs)
    return "{" + body + "}"


def _escape(s):
    return s.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


# ---- process-wide default registry ------------------------------------
REGISTRY = Registry()


def counter(name, help_="", labelnames=()):
    return REGISTRY.counter(name, help_, labelnames)


def gauge(name, help_="", labelnames=()):
    return REGISTRY.gauge(name, help_, labelnames)


def histogram(name, help_="", labelnames=(), buckets=None):
    return REGISTRY.histogram(name, help_, labelnames, buckets)


def gauge_fn(name, help_, fn):
    return REGISTRY.gauge_fn(name, help_, fn)


def enable():
    REGISTRY.enable()


def disable():
    REGISTRY.disable()


def enabled():
    return REGISTRY.enabled


def snapshot():
    return REGISTRY.snapshot()


def to_prometheus():
    return REGISTRY.to_prometheus()


def to_json(**kw):
    return REGISTRY.to_json(**kw)


def reset():
    REGISTRY.reset()
