"""XLA compile tracking at the jit boundaries (ISSUE 10 tentpole,
part b).

Two bench rounds were silently poisoned by untracked in-window XLA
compiles (PERF.md r12/r13: one fresh packed-prefill bucket costs ~0.7s
and lands on whatever requests are in flight). This module makes every
compile a first-class, attributable event:

  * `wrap(program, jit_fn)` returns a call-through wrapper that detects
    a compile EXACTLY — jax's jitted callables expose `_cache_size()`,
    so "the executable cache grew across this call" is the compile,
    not a heuristic over argument shapes (it also catches recompiles
    after a cache drop, e.g. the tier-1 map-count guard);
  * each compile records `serving_xla_compiles_total{program,in_flight,
    shard}` + a `serving_xla_compile_seconds{program,shard}` histogram
    observation, emits a `compile` trace event (ts/dur — the PR 2
    request assembler uses it to attribute TTFT/ITL outliers to
    compiles instead of queue/prefill time), notifies registered
    listeners (the per-server flight recorders), and lands in a
    bounded in-process event log;
  * `in_flight` comes from registered probes (each serving engine
    registers "do I have busy slots or queued work" via a weakref, so
    dead servers fall away) — `warm_buckets()` compiles before start()
    therefore label `in_flight="false"`, and a compile-clean
    measurement window is `count_since(mark, in_flight=True) == 0`.

The tracker is ALWAYS on: compiles are rare, the per-dispatch cost of
detection is one C-level `_cache_size()` call, and a tracker that only
counts while telemetry is enabled would misreport pre-enable buckets
as fresh compiles. Metric emission still goes through the registry's
enabled gate like everything else; the event log and `count_since()`
window API work regardless, which is what lets `bench.py` prove a
window compile-clean without enabling the full telemetry stack.
"""
from __future__ import annotations

import collections
import threading
import time
import weakref

from . import metrics as _metrics
from . import tracing as _tracing

# compile durations are big (0.1s..minutes) — the default latency
# buckets top out at 10s and would crush everything into +Inf
COMPILE_BUCKETS = (.05, .1, .25, .5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                   120.0)

_m_compiles = _metrics.counter(
    "serving_xla_compiles_total",
    "XLA compiles observed at the decode jit boundaries, by program "
    "(prefill | decode_step | packed_prefill | packed_verify | "
    "multistep), whether requests were in flight, and mesh shard "
    "label ('none' unsharded)",
    labelnames=("program", "in_flight", "shard"))
_m_compile_s = _metrics.histogram(
    "serving_xla_compile_seconds",
    "wall duration of the dispatch that compiled (trace + compile + "
    "first run — the latency that lands on in-flight requests)",
    labelnames=("program", "shard"), buckets=COMPILE_BUCKETS)

EVENT_LOG_CAPACITY = 4096


class CompileTracker:
    """Process-wide compile event log + in-flight probe registry.
    Instantiable for tests; `TRACKER` is the default instance the
    decode wrappers use."""

    def __init__(self, capacity=EVENT_LOG_CAPACITY):
        self._lock = threading.Lock()
        self._events = collections.deque(maxlen=capacity)
        self._total = 0
        self._total_in_flight = 0
        self._probes = []     # weakref.WeakMethod / weakref.ref
        self._listeners = []  # same, called with each event dict

    # -- probes / listeners ----------------------------------------------
    def _weak(self, fn):
        try:
            return weakref.WeakMethod(fn)
        except TypeError:
            return weakref.ref(fn)

    def register_in_flight_probe(self, fn):
        """Register a zero-arg callable answering "does your engine
        have live work right now". Held by weakref (bound methods via
        WeakMethod) so a garbage-collected server needs no unregister."""
        with self._lock:
            self._probes.append(self._weak(fn))

    def add_listener(self, fn):
        """Register a callable(event_dict) notified on every compile —
        the per-server flight recorders. Weakly held, like probes."""
        with self._lock:
            self._listeners.append(self._weak(fn))

    def _live(self, refs):
        out, dead = [], False
        for r in refs:
            fn = r()
            if fn is None:
                dead = True
            else:
                out.append((r, fn))
        if dead:
            refs[:] = [r for r, _ in out]
        return [fn for _, fn in out]

    def in_flight(self):
        with self._lock:
            probes = self._live(self._probes)
        for p in probes:
            try:
                if p():
                    return True
            except Exception:  # noqa: BLE001 — a dying server's probe
                continue  # must not break compile accounting
        return False

    # -- recording -------------------------------------------------------
    def record(self, program, dur_s, shard="none", in_flight=None):
        if in_flight is None:
            in_flight = self.in_flight()
        ev = {"program": program, "dur_s": float(dur_s),
              "in_flight": bool(in_flight), "shard": shard,
              "ts": time.perf_counter()}
        with self._lock:
            self._total += 1
            if ev["in_flight"]:
                self._total_in_flight += 1
            self._events.append(ev)
            listeners = self._live(self._listeners)
        flag = "true" if ev["in_flight"] else "false"
        _m_compiles.labels(program=program, in_flight=flag,
                           shard=shard).inc()
        _m_compile_s.labels(program=program, shard=shard).observe(dur_s)
        # the trace event carries the dispatch START ts so the request
        # assembler can overlap it with request windows
        _tracing.event("compile", ts=ev["ts"] - ev["dur_s"],
                       dur=ev["dur_s"], program=program,
                       in_flight=ev["in_flight"], shard=shard)
        for fn in listeners:
            try:
                fn(ev)
            except Exception:  # noqa: BLE001
                pass
        return ev

    # -- window API ------------------------------------------------------
    def mark(self):
        """Opaque window mark: pass back to count_since/events_since."""
        with self._lock:
            return self._total

    def count_since(self, mark, in_flight=None):
        """Compiles since `mark`, optionally only those with the given
        in-flight flag — the bench's compile-clean-window assertion."""
        evs = self.events_since(mark)
        if in_flight is None:
            return len(evs)
        return sum(1 for e in evs if e["in_flight"] == bool(in_flight))

    def events_since(self, mark):
        with self._lock:
            n = self._total - int(mark)
            if n <= 0:
                return []
            return list(self._events)[-min(n, len(self._events)):]

    def stats(self):
        with self._lock:
            return {"total": self._total,
                    "total_in_flight": self._total_in_flight}

    # -- the jit-boundary wrapper ----------------------------------------
    def wrap(self, program, fn, shard="none"):
        """Wrap a jitted callable: every call whose executable cache
        grew is recorded as a compile of `program`. Falls through
        untouched (no detection) when `fn` has no `_cache_size` —
        non-jit callables in tests."""
        cache_size = getattr(fn, "_cache_size", None)
        if cache_size is None:
            return fn
        tracker = self

        def wrapped(*args, **kw):
            n0 = cache_size()
            t0 = time.perf_counter()
            out = fn(*args, **kw)
            if cache_size() > n0:
                tracker.record(program, time.perf_counter() - t0, shard)
            return out

        wrapped.__name__ = getattr(fn, "__name__", program)
        wrapped.__wrapped__ = fn
        return wrapped


# ---- process-wide default tracker ---------------------------------------
TRACKER = CompileTracker()


def wrap(program, fn, shard="none"):
    return TRACKER.wrap(program, fn, shard)


def register_in_flight_probe(fn):
    TRACKER.register_in_flight_probe(fn)


def add_listener(fn):
    TRACKER.add_listener(fn)


def mark():
    return TRACKER.mark()


def count_since(m, in_flight=None):
    return TRACKER.count_since(m, in_flight)


def events_since(m):
    return TRACKER.events_since(m)
